"""Declarative layer functions (the ``paddle.v2.layer`` /
``trainer_config_helpers/layers.py`` twin).

Each function returns a :class:`LayerOutput` node; calling conventions
mirror the v1 helper API (``layers.py:34`` — ``fc_layer``, ``embedding``,
``lstmemory``, cost layers...) while the bodies are thin closures over the
``paddle_tpu.nn`` modules and ``paddle_tpu.ops`` functions, created with
stable names so parameters live at predictable paths.

Sequence-valued nodes are (value, mask) pairs — the TPU-native stand-in for
the reference's ``Argument.sequenceStartPositions`` padding-free batches.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.core.errors import enforce
from paddle_tpu.api.graph import LayerOutput, auto_name
from paddle_tpu.ops import losses as loss_ops
from paddle_tpu.ops import nested as nested_ops
from paddle_tpu.ops import sequence as seq_ops


def _node(kind, fn, inputs, name=None, **attrs):
    node = LayerOutput(name=auto_name(kind, name), kind=kind, fn=fn,
                       inputs=tuple(inputs),
                       attrs=tuple(sorted(attrs.items())))
    # Inside a recurrent_group step trace, register the node so memory()
    # can link to it even when it is not a group output.
    from paddle_tpu.api import recurrent as _rec
    _rec._register_node(node)
    return node


def _is_seq(v) -> bool:
    return isinstance(v, tuple) and len(v) == 2


def _val(v):
    return v[0] if _is_seq(v) else v


def _mask(v):
    return v[1] if _is_seq(v) else None


# ---- inputs ----------------------------------------------------------------

def data(name: str, dtype: str = "float32", sequence: bool = False):
    """Input node reading ``batch[name]`` (v2 ``layer.data`` twin).  With
    ``sequence=True`` the node reads ``batch[name]`` and
    ``batch[name + "_mask"]`` as a (value, mask) pair."""
    if not sequence:
        return LayerOutput(name=name, kind="data")
    base = LayerOutput(name=name, kind="data")
    mask = LayerOutput(name=f"{name}_mask", kind="data")
    return _node("seq_pair", lambda ctx, v, m: (v, m), [base, mask],
                 name=f"{name}_seq")


# ---- core layers -----------------------------------------------------------

def fc(input, size: int, act: str = "linear", bias: bool = True,
       name: Optional[str] = None):
    def run(ctx, x, **a):
        m = _mask(x)
        v = _val(x)
        if m is None and v.ndim > 2:
            # Non-sequence multi-dim input (e.g. conv feature maps): the
            # reference's fc_layer treats it as one flat vector per sample.
            v = v.reshape(v.shape[0], -1)
        y = nn.Linear(a["size"], act=a["act"], bias=a["bias"],
                      name=a["_name"])(v)
        return (y, m) if m is not None else y
    n = auto_name("fc", name)
    return _node("fc", run, [input], name=n, size=size, act=act, bias=bias,
                 _name=n)


def embedding(input, size: int, vocab_size: int, name: Optional[str] = None):
    def run(ctx, ids, **a):
        m = _mask(ids)
        y = nn.Embedding(a["vocab_size"], a["size"], name=a["_name"])(_val(ids))
        return (y, m) if m is not None else y
    n = auto_name("embedding", name)
    return _node("embedding", run, [input], name=n, size=size,
                 vocab_size=vocab_size, _name=n)


def conv2d(input, channels: int, kernel: int = 3, stride: int = 1,
           act: str = "relu", padding="SAME", name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.Conv2D(a["channels"], a["kernel"], stride=a["stride"],
                         padding=a["padding"], act=a["act"],
                         name=a["_name"])(x)
    n = auto_name("conv2d", name)
    return _node("conv2d", run, [input], name=n, channels=channels,
                 kernel=kernel, stride=stride, act=act, padding=padding,
                 _name=n)


def pool2d(input, kernel: int = 2, stride: Optional[int] = None,
           pool_type: str = "max", name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.Pool2D(a["kernel"], stride=a["stride"],
                         pool_type=a["pool_type"])(x)
    return _node("pool2d", run, [input], name=name, kernel=kernel,
                 stride=stride, pool_type=pool_type)


def batch_norm(input, act: str = "linear", name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.BatchNorm(act=a["act"], name=a["_name"])(x)
    n = auto_name("batch_norm", name)
    return _node("batch_norm", run, [input], name=n, act=act, _name=n)


def dropout(input, rate: float, name: Optional[str] = None):
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.Dropout(a["rate"], name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("dropout", name)
    return _node("dropout", run, [input], name=n, rate=rate, _name=n)


def concat(inputs: Sequence[LayerOutput], name: Optional[str] = None):
    def run(ctx, *xs):
        return jnp.concatenate([_val(x) for x in xs], axis=-1)
    return _node("concat", run, list(inputs), name=name)


def addto(inputs: Sequence[LayerOutput], act: str = "linear",
          name: Optional[str] = None):
    def run(ctx, *xs, **a):
        return nn.Addto(act=a["act"], name=a["_name"])(*[_val(x) for x in xs])
    n = auto_name("addto", name)
    return _node("addto", run, list(inputs), name=n, act=act, _name=n)


# ---- recurrent / sequence --------------------------------------------------

def lstmemory(input, size: int, reverse: bool = False,
              name: Optional[str] = None):
    """Full-sequence LSTM over a (value, mask) pair (lstmemory twin)."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "lstmemory needs a sequence input")
        from paddle_tpu.nn.recurrent import LSTM
        hs, _ = LSTM(a["size"], reverse=a["reverse"], name=a["_name"])(
            x[0], x[1])
        return (hs, x[1])
    n = auto_name("lstmemory", name)
    return _node("lstmemory", run, [input], name=n, size=size,
                 reverse=reverse, _name=n)


def grumemory(input, size: int, reverse: bool = False,
              name: Optional[str] = None):
    def run(ctx, x, **a):
        enforce(_is_seq(x), "grumemory needs a sequence input")
        from paddle_tpu.nn.recurrent import GRU
        hs, _ = GRU(a["size"], reverse=a["reverse"], name=a["_name"])(
            x[0], x[1])
        return (hs, x[1])
    n = auto_name("grumemory", name)
    return _node("grumemory", run, [input], name=n, size=size,
                 reverse=reverse, _name=n)


def seq_pool(input, pool_type: str = "avg", name: Optional[str] = None,
             agg_level: Optional[str] = None):
    """Sequence pooling (pooling_layer twin).  Flat sequences pool to a
    fixed vector; NESTED sequences ([b,o,i,...], [b,o,i] mask) pool each
    sub-sequence, yielding a flat sequence — the reference's pooling at
    ``AggregateLevel.EACH_SEQUENCE``.

    The level is implied by the input's nesting; an explicit ``agg_level``
    ("seq" / "non-seq") is validated against it so a config expecting the
    OTHER semantics errors instead of silently training differently."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "seq_pool needs a sequence input")
        nested = x[1].ndim == 3
        lvl = a["agg_level"]
        if lvl is not None:
            enforce(lvl in ("seq", "non-seq"),
                    "seq_pool: unknown agg_level %r (valid: 'seq', "
                    "'non-seq' — the AggregateLevel constants)", lvl)
            want_nested = lvl == "seq"
            enforce(want_nested == nested,
                    "seq_pool: agg_level=%r but the input is a %s "
                    "sequence — here the aggregation level follows the "
                    "input's nesting (flat pools to a vector, nested "
                    "pools each sub-sequence)",
                    lvl, "nested" if nested else "flat")
        if nested:
            return nested_ops.nested_pool(x[0], x[1], a["pool_type"])
        return seq_ops.sequence_pool(x[0], x[1], a["pool_type"])
    return _node("seq_pool", run, [input], name=name, pool_type=pool_type,
                 agg_level=agg_level)


def seq_reshape(input, inner: Optional[int] = None,
                name: Optional[str] = None):
    """Nested<->flat sequence conversion (seq_reshape_layer /
    Argument-degrade twin): with ``inner`` given, cut a flat sequence into
    ``inner``-sized sub-sequences; without it, flatten a nested sequence
    back to flat (valid steps left-packed)."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "seq_reshape needs a sequence input")
        if a["inner"] is not None:
            enforce(x[1].ndim == 2, "inner= requires a flat sequence")
            return nested_ops.split_to_nested(x[0], x[1], a["inner"])
        enforce(x[1].ndim == 3, "flattening requires a nested sequence")
        return nested_ops.flatten_nested(x[0], x[1])
    return _node("seq_reshape", run, [input], name=name, inner=inner)


def sub_nested_seq(input, selected_indices, k: int,
                   name: Optional[str] = None):
    """Select k sub-sequences per row by index
    (sub_nested_seq_layer twin; pair with kmax_seq_score)."""
    def run(ctx, x, idx, **a):
        enforce(_is_seq(x) and x[1].ndim == 3,
                "sub_nested_seq needs a nested sequence input")
        return nested_ops.sub_nested_seq(x[0], x[1], _val(idx), a["k"])
    return _node("sub_nested_seq", run, [input, selected_indices],
                 name=name, k=k)


def last_seq(input, name: Optional[str] = None):
    def run(ctx, x):
        return seq_ops.last_seq(x[0], x[1])
    return _node("last_seq", run, [input], name=name)


def first_seq(input, name: Optional[str] = None):
    def run(ctx, x):
        return seq_ops.first_seq(x[0], x[1])
    return _node("first_seq", run, [input], name=name)


def context_projection(input, context_len: int, context_start: int,
                       name: Optional[str] = None):
    def run(ctx, x, **a):
        y = seq_ops.context_projection(x[0], x[1], a["context_len"],
                                       a["context_start"])
        return (y, x[1])
    return _node("context_projection", run, [input], name=name,
                 context_len=context_len, context_start=context_start)


# ---- costs -----------------------------------------------------------------

def _record_label(ctx, logits, label, extra=None):
    ctx.outputs["logits"] = logits
    ctx.outputs["label"] = label
    if extra:
        ctx.outputs.update(extra)


def classification_cost(input, label, name: Optional[str] = None):
    """Softmax cross-entropy against integer labels
    (classification_cost twin).  Records logits/label for evaluators."""
    def run(ctx, logits, y):
        logits = _val(logits)
        _record_label(ctx, logits, y)
        return loss_ops.softmax_cross_entropy(logits, y).mean()
    return _node("classification_cost", run, [input, label], name=name)


def square_error_cost(input, label, name: Optional[str] = None):
    def run(ctx, pred, y):
        pred = _val(pred)
        ctx.outputs["pred"] = pred
        ctx.outputs["label"] = y
        return loss_ops.square_error(pred, y).mean()
    return _node("square_error_cost", run, [input, label], name=name)


def cross_entropy_with_sequence(input, label, name: Optional[str] = None):
    """Per-step CE over a (logits, mask) sequence vs int labels [b, t]."""
    def run(ctx, logits, y):
        enforce(_is_seq(logits), "needs sequence logits")
        val, mask = logits
        ce = loss_ops.softmax_cross_entropy(val, y)
        m = mask.astype(val.dtype)
        _record_label(ctx, val, y, {"label_mask": mask})
        return (ce * m).sum() / jnp.maximum(m.sum(), 1.0)
    return _node("seq_cross_entropy", run, [input, label], name=name)


def crf_cost(input, label, num_tags: int, name: Optional[str] = None):
    """Linear-chain CRF negative log-likelihood over a sequence
    (crf_layer twin, ``LinearChainCRF.cpp``)."""
    def run(ctx, emissions, y, **a):
        enforce(_is_seq(emissions), "crf needs sequence emissions")
        val, mask = emissions
        from paddle_tpu.ops import crf as crf_ops
        from paddle_tpu.nn.module import param
        from paddle_tpu.nn import initializers as init
        k = a["num_tags"]
        trans = param(f"{a['_name']}/transitions", (k, k), jnp.float32,
                      init.zeros)
        start = param(f"{a['_name']}/start", (k,), jnp.float32, init.zeros)
        stop = param(f"{a['_name']}/stop", (k,), jnp.float32, init.zeros)
        ll = crf_ops.crf_log_likelihood(val, y, mask, trans, start, stop)
        ctx.outputs["emissions"] = val
        ctx.outputs["label"] = y
        ctx.outputs["label_mask"] = mask
        return -ll.mean()
    n = auto_name("crf", name)
    return _node("crf", run, [input, label], name=n, num_tags=num_tags,
                 _name=n)


# ---- image layers ----------------------------------------------------------

def conv2d_transpose(input, channels: int, kernel: int = 3, stride: int = 1,
                     act: str = "relu", name: Optional[str] = None):
    """Transposed conv (img_conv_layer(trans=True) twin, ConvTransLayer)."""
    def run(ctx, x, **a):
        return nn.Conv2DTranspose(a["channels"], a["kernel"],
                                  stride=a["stride"], act=a["act"],
                                  name=a["_name"])(x)
    n = auto_name("conv2d_transpose", name)
    return _node("conv2d_transpose", run, [input], name=n, channels=channels,
                 kernel=kernel, stride=stride, act=act, _name=n)


def spp(input, pyramid_height: int = 3, pool_type: str = "max",
        name: Optional[str] = None):
    """Spatial pyramid pooling (spp_layer twin, SpatialPyramidPoolLayer)."""
    def run(ctx, x, **a):
        return nn.SpatialPyramidPool(levels=a["pyramid_height"],
                                     pool_type=a["pool_type"])(x)
    return _node("spp", run, [input], name=name,
                 pyramid_height=pyramid_height, pool_type=pool_type)


def maxout(input, groups: int, name: Optional[str] = None):
    """Maxout over channel groups (maxout_layer twin, MaxOutLayer)."""
    def run(ctx, x, **a):
        return nn.Maxout(a["groups"])(x)
    return _node("maxout", run, [input], name=name, groups=groups)


def img_cmrnorm(input, size: int = 5, scale: float = 0.0001,
                power: float = 0.75, name: Optional[str] = None):
    """Cross-map response normalization (img_cmrnorm_layer twin,
    CMRProjectionNormLayer — AlexNet's LRN)."""
    def run(ctx, x, **a):
        from paddle_tpu.models.alexnet import _lrn
        return _lrn(x, size=a["size"], alpha=a["scale"], beta=a["power"])
    return _node("img_cmrnorm", run, [input], name=name, size=size,
                 scale=scale, power=power)


def bilinear_interp(input, out_h: int, out_w: int,
                    name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.BilinearInterp(a["out_h"], a["out_w"])(x)
    return _node("bilinear_interp", run, [input], name=name, out_h=out_h,
                 out_w=out_w)


def crop(input, offsets, shape, name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.Crop(a["offsets"], a["shape"])(x)
    return _node("crop", run, [input], name=name, offsets=tuple(offsets),
                 shape=tuple(shape))


def pad(input, pad_c=(0, 0), pad_h=(0, 0), pad_w=(0, 0),
        name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.Pad(a["pad_h"], a["pad_w"], pad_c=a["pad_c"])(x)
    return _node("pad", run, [input], name=name, pad_c=tuple(pad_c),
                 pad_h=tuple(pad_h), pad_w=tuple(pad_w))


def rotate(input, name: Optional[str] = None):
    def run(ctx, x):
        return nn.Rotate()(x)
    return _node("rotate", run, [input], name=name)


def block_expand(input, block_x: int, block_y: int, stride_x: int = 1,
                 stride_y: int = 1, name: Optional[str] = None):
    """im2col-as-sequence (block_expand_layer twin); output is a
    (value, mask) sequence of patches for OCR/CTC pipelines."""
    def run(ctx, x, **a):
        y = nn.BlockExpand((a["block_y"], a["block_x"]),
                           (a["stride_y"], a["stride_x"]))(x)
        mask = jnp.ones(y.shape[:2], bool)
        return (y, mask)
    return _node("block_expand", run, [input], name=name, block_x=block_x,
                 block_y=block_y, stride_x=stride_x, stride_y=stride_y)


# ---- elementwise / math layers ---------------------------------------------

def interpolation(weight, input_a, input_b, name: Optional[str] = None):
    """out = w*a + (1-w)*b with per-sample scalar w (interpolation_layer)."""
    def run(ctx, w, x, y):
        m = _mask(x) if _mask(x) is not None else _mask(y)
        out = nn.Interpolation()(_val(w), _val(x), _val(y))
        return (out, m) if m is not None else out
    return _node("interpolation", run, [weight, input_a, input_b], name=name)


def scaling(weight, input, name: Optional[str] = None):
    """Per-sample scalar scaling of a vector input (scaling_layer twin)."""
    def run(ctx, w, x):
        m = _mask(x)
        w = _val(w)
        v = _val(x)
        y = w.reshape(w.shape[0], *([1] * (v.ndim - 1))) * v
        return (y, m) if m is not None else y
    return _node("scaling", run, [weight, input], name=name)


def slope_intercept(input, slope: float = 1.0, intercept: float = 0.0,
                    name: Optional[str] = None):
    def run(ctx, x, **a):
        m = _mask(x)
        y = a["slope"] * _val(x) + a["intercept"]
        return (y, m) if m is not None else y
    return _node("slope_intercept", run, [input], name=name, slope=slope,
                 intercept=intercept)


def sum_to_one_norm(input, name: Optional[str] = None):
    def run(ctx, x):
        m = _mask(x)
        y = nn.SumToOneNorm()(_val(x))
        return (y, m) if m is not None else y
    return _node("sum_to_one_norm", run, [input], name=name)


def mdlstm(input, size: int, directions=(True, True),
           name: Optional[str] = None):
    """2-D multi-dimensional LSTM (``mdlstmemory`` config-kind twin, ref
    ``gserver/layers/MDLstmLayer.cpp:180``).  The input node must carry
    a pre-projected grid ``[b, H, W, 5*size]`` (the reference requires
    its input layer to be ``(3+D)*size`` wide); output is
    ``[b, H, W, size]``.  ``directions[d]`` False scans dim d in
    reverse, like the reference's per-dim direction bools."""
    def run(ctx, x, **a):
        return nn.MDLstm2D(a["size"], directions=a["dirs"],
                           name=a["_name"])(_val(x))
    n = auto_name("mdlstm", name)
    return _node("mdlstm", run, [input], name=n, size=size,
                 dirs=tuple(directions), _name=n)


def data_norm(input, data_norm_strategy: str = "z-score",
              name: Optional[str] = None):
    """Stats-table input normalization (``data_norm`` config-kind twin,
    ref ``gserver/layers/DataNormLayer.cpp:21``,
    ``config_parser.py:2014``).  The 5×size static table
    ``[min; 1/(max-min); mean; 1/std; 1/10^j]`` is a non-trainable
    STATE buffer at ``<name>/stats`` (the reference enforces a static
    parameter; a state buffer is the form optimizers and weight decay
    cannot touch) — build it with ``nn.DataNormTable.compute_table`` in
    preprocessing or import it from a reference checkpoint via
    ``checkpoint.apply_v1_state`` with a ``name_map``."""
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.DataNormTable(strategy=a["strategy"],
                             name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("data_norm", name)
    return _node("data_norm", run, [input], name=n,
                 strategy=data_norm_strategy, _name=n)


def power(input, exponent, name: Optional[str] = None):
    """Per-sample elementwise power: out = x ** e (power_layer twin)."""
    def run(ctx, x, e):
        m = _mask(x)
        v, e = _val(x), _val(e)
        y = v ** e.reshape(e.shape[0], *([1] * (v.ndim - 1)))
        return (y, m) if m is not None else y
    return _node("power", run, [input, exponent], name=name)


def dotmul(input_a, input_b, name: Optional[str] = None):
    """Elementwise product of two layers (dotmul_operator twin)."""
    def run(ctx, x, y):
        m = _mask(x) if _mask(x) is not None else _mask(y)
        out = _val(x) * _val(y)
        return (out, m) if m is not None else out
    return _node("dotmul", run, [input_a, input_b], name=name)


def trans(input, name: Optional[str] = None):
    """Matrix transpose of [batch-free] 2-D output (trans_layer twin)."""
    def run(ctx, x):
        return jnp.swapaxes(_val(x), -1, -2)
    return _node("trans", run, [input], name=name)


def cos_sim(input_a, input_b, scale: float = 1.0,
            name: Optional[str] = None):
    """Cosine similarity of two [b, d] inputs (cos_sim twin,
    CosSimLayer.cpp)."""
    def run(ctx, x, y, **a):
        x, y = _val(x), _val(y)
        nx = jnp.sqrt(jnp.sum(x * x, -1) + 1e-12)
        ny = jnp.sqrt(jnp.sum(y * y, -1) + 1e-12)
        return a["scale"] * jnp.sum(x * y, -1) / (nx * ny)
    return _node("cos_sim", run, [input_a, input_b], name=name, scale=scale)


def linear_comb(weights, input, size: int, name: Optional[str] = None):
    """Weighted row combination (linear_comb_layer twin): input [b, m*size]
    seen as m rows of size, weights [b, m] -> [b, size]."""
    def run(ctx, w, x, **a):
        w, x = _val(w), _val(x)
        m = w.shape[-1]
        rows = x.reshape(x.shape[0], m, a["size"])
        return jnp.einsum("bm,bmd->bd", w, rows)
    return _node("linear_comb", run, [weights, input], name=name, size=size)


def multiplex(index, *inputs, name: Optional[str] = None):
    """Row-wise select among inputs by per-sample index (multiplex_layer)."""
    def run(ctx, idx, *xs):
        return nn.Multiplex()(_val(idx), *[_val(x) for x in xs])
    return _node("multiplex", run, [index, *inputs], name=name)


def repeat(input, num_repeats: int, name: Optional[str] = None):
    """Tile features along the last axis (repeat_layer twin)."""
    def run(ctx, x, **a):
        m = _mask(x)
        v = _val(x)
        y = jnp.tile(v, (1,) * (v.ndim - 1) + (a["num_repeats"],))
        return (y, m) if m is not None else y
    return _node("repeat", run, [input], name=name, num_repeats=num_repeats)


def expand(input, expand_as, name: Optional[str] = None):
    """Broadcast a per-sequence vector over the steps of ``expand_as``
    (expand_layer twin)."""
    def run(ctx, vec, seq):
        enforce(_is_seq(seq), "expand needs a sequence to expand as")
        return (seq_ops.sequence_expand(_val(vec), seq[1]), seq[1])
    return _node("expand", run, [input, expand_as], name=name)


def selective_fc(input, select, size: int, act: str = "linear",
                 name: Optional[str] = None):
    """FC computing only selected output columns (selective_fc_layer)."""
    def run(ctx, x, sel, **a):
        return nn.SelectiveFC(a["size"], act=a["act"],
                              name=a["_name"])(_val(x), _val(sel))
    n = auto_name("selective_fc", name)
    return _node("selective_fc", run, [input, select], name=n, size=size,
                 act=act, _name=n)


def mixed(inputs: Sequence[LayerOutput], projections, act: str = "linear",
          bias: bool = True, name: Optional[str] = None):
    """Sum-of-projections layer (mixed_layer twin, MixedLayer.cpp);
    ``projections`` are ``nn`` projection modules, one per input."""
    def run(ctx, *xs, **a):
        y = nn.Mixed(list(a["_projections"]), act=a["act"],
                     bias=a["bias"], name=a["_name"])(
            *[_val(x) for x in xs])
        masks = [_mask(x) for x in xs if _mask(x) is not None]
        return (y, masks[0]) if masks else y
    n = auto_name("mixed", name)
    return _node("mixed", run, list(inputs), name=n, act=act, bias=bias,
                 _name=n, _projections=tuple(projections))


# ---- more sequence layers --------------------------------------------------

def seq_reverse(input, name: Optional[str] = None):
    def run(ctx, x):
        return (seq_ops.sequence_reverse(x[0], x[1]), x[1])
    return _node("seq_reverse", run, [input], name=name)


def seq_concat(input_a, input_b, name: Optional[str] = None):
    """Concatenate two sequences end-to-end per sample (seq_concat_layer)."""
    def run(ctx, a, b):
        v, m = seq_ops.sequence_concat(a[0], a[1], b[0], b[1])
        return (v, m)
    return _node("seq_concat", run, [input_a, input_b], name=name)


def seq_slice(input, starts, sizes, name: Optional[str] = None):
    def run(ctx, x, s, z):
        v, m = seq_ops.sequence_slice(x[0], x[1], _val(s), _val(z))
        return (v, m)
    return _node("seq_slice", run, [input, starts, sizes], name=name)


def kmax_seq_score(input, k: int, name: Optional[str] = None):
    """Top-k step indices by score (kmax_sequence_score_layer twin)."""
    def run(ctx, x, **a):
        v = x[0]
        if v.ndim == 3:
            v = v[..., 0]
        return seq_ops.kmax_sequence_score(v, x[1], a["k"])
    return _node("kmax_seq_score", run, [input], name=name, k=k)


# ---- cost zoo --------------------------------------------------------------

def cross_entropy_cost(input, label, name: Optional[str] = None):
    """CE against probabilities (cross_entropy twin — input already
    softmaxed, e.g. act='softmax' fc output)."""
    def run(ctx, probs, y):
        probs = _val(probs)
        _record_label(ctx, probs, y)
        return loss_ops.cross_entropy(probs, y).mean()
    return _node("cross_entropy_cost", run, [input, label], name=name)


def soft_cross_entropy_cost(input, label_probs, name: Optional[str] = None):
    """CE against a soft label distribution (soft_binary_class CE twin)."""
    def run(ctx, logits, y):
        return loss_ops.softmax_cross_entropy_soft(_val(logits),
                                                   _val(y)).mean()
    return _node("soft_cross_entropy_cost", run, [input, label_probs],
                 name=name)


def multi_binary_label_cross_entropy_cost(input, label,
                                          name: Optional[str] = None):
    """Sigmoid CE over independent binary labels
    (multi_binary_label_cross_entropy twin)."""
    def run(ctx, logits, y):
        logits = _val(logits)
        return loss_ops.sigmoid_cross_entropy(
            logits, _val(y).astype(logits.dtype)).sum(-1).mean()
    return _node("multi_binary_ce_cost", run, [input, label], name=name)


def huber_regression_cost(input, label, delta: float = 1.0,
                          name: Optional[str] = None):
    def run(ctx, pred, y, **a):
        return loss_ops.huber_regression(_val(pred), _val(y),
                                         a["delta"]).mean()
    return _node("huber_regression_cost", run, [input, label], name=name,
                 delta=delta)


def huber_classification_cost(input, label, name: Optional[str] = None):
    """Huber loss for binary classification with -1/+1 labels
    (huber_classification_cost twin, CostLayer.cpp HuberTwoClassification)."""
    def run(ctx, pred, y):
        return loss_ops.huber_classification(_val(pred), _val(y)).mean()
    return _node("huber_classification_cost", run, [input, label], name=name)


def smooth_l1_cost(input, label, name: Optional[str] = None):
    def run(ctx, pred, y):
        return loss_ops.smooth_l1(_val(pred), _val(y)).mean()
    return _node("smooth_l1_cost", run, [input, label], name=name)


def rank_cost(left, right, label, name: Optional[str] = None):
    """Pairwise ranking cost (rank_cost twin, RankingCost)."""
    def run(ctx, l, r, y):
        lv, rv = _val(l), _val(r)
        lv = lv[:, 0] if lv.ndim == 2 else lv
        rv = rv[:, 0] if rv.ndim == 2 else rv
        return loss_ops.rank_cost(lv, rv, _val(y)).mean()
    return _node("rank_cost", run, [left, right, label], name=name)


def lambda_cost(input, score, ndcg_num: int = 5,
                name: Optional[str] = None):
    """LambdaRank over a (scores, mask) sequence (lambda_cost twin)."""
    def run(ctx, pred, rel, **a):
        enforce(_is_seq(pred), "lambda_cost needs sequence scores")
        val, mask = pred
        rv = _val(rel)
        rv = rv[..., 0] if rv.ndim == 3 else rv
        return loss_ops.lambda_rank(val[..., 0] if val.ndim == 3 else val,
                                    rv, mask, a["ndcg_num"]).mean()
    return _node("lambda_cost", run, [input, score], name=name,
                 ndcg_num=ndcg_num)


def sum_cost(input, name: Optional[str] = None):
    def run(ctx, x):
        return _val(x).sum()
    return _node("sum_cost", run, [input], name=name)


def ctc_cost(input, label, blank: int = 0, name: Optional[str] = None):
    """CTC loss over (logits, mask) vs (label_ids, label_mask)
    (ctc_layer / warp_ctc twin — ops/ctc.py is the scan-based impl)."""
    def run(ctx, logits, y, **a):
        enforce(_is_seq(logits) and _is_seq(y),
                "ctc_cost needs sequence logits and labels")
        from paddle_tpu.ops import ctc as ctc_ops
        lv, lm = logits
        yv, ym = y
        loss = ctc_ops.ctc_loss(lv, seq_ops.mask_to_lengths(lm), yv,
                                seq_ops.mask_to_lengths(ym), a["blank"])
        return loss.mean()
    return _node("ctc_cost", run, [input, label], name=name, blank=blank)


def nce_cost(input, label, num_classes: int, num_neg_samples: int = 10,
             name: Optional[str] = None):
    """Noise-contrastive estimation cost (nce_layer twin, NCELayer.cpp).
    Uniform noise distribution; owns the [num_classes, d] output table."""
    def run(ctx, x, y, **a):
        from paddle_tpu.nn.module import param, next_rng_key
        from paddle_tpu.nn import initializers as init
        import jax
        x = _val(x)
        k, n = a["num_neg_samples"], a["num_classes"]
        w = param(f"{a['_name']}/w", (n, x.shape[-1]), jnp.float32,
                  init.paddle_default(fan_in_axis=1))
        b = param(f"{a['_name']}/b", (n,), jnp.float32, init.zeros)
        noise = jax.random.randint(next_rng_key(), (x.shape[0], k), 0, n)
        logq = jnp.log(jnp.asarray(1.0 / n, x.dtype))
        return loss_ops.nce_loss(x, w, b, y, noise, logq, logq).mean()
    n_ = auto_name("nce", name)
    return _node("nce", run, [input, label], name=n_,
                 num_classes=num_classes, num_neg_samples=num_neg_samples,
                 _name=n_)


def hsigmoid_cost(input, label, num_classes: int,
                  name: Optional[str] = None):
    """Hierarchical sigmoid cost over a complete binary tree
    (hsigmoid twin, HierarchicalSigmoidLayer.cpp: the label's path codes
    are the bits of ``label + num_classes`` below its leading bit)."""
    def run(ctx, x, y, **a):
        from paddle_tpu.nn.module import param
        from paddle_tpu.nn import initializers as init
        x = _val(x)
        n = a["num_classes"]
        depth = max(1, (n - 1).bit_length())
        w = param(f"{a['_name']}/w", (n, x.shape[-1]), jnp.float32,
                  init.paddle_default(fan_in_axis=1))
        b = param(f"{a['_name']}/b", (n,), jnp.float32, init.zeros)
        code = y + n                                  # heap index of leaf
        bit = jnp.arange(depth - 1, -1, -1)
        path = code[:, None] >> (bit[None, :] + 1)    # ancestors, root..parent
        signs = jnp.where((code[:, None] >> bit[None, :]) & 1, -1.0, 1.0)
        mask = path >= 1
        nodes = jnp.clip(path - 1, 0, n - 1)
        return loss_ops.hierarchical_sigmoid(x, w, b, nodes, signs,
                                             mask).mean()
    n_ = auto_name("hsigmoid", name)
    return _node("hsigmoid", run, [input, label], name=n_,
                 num_classes=num_classes, _name=n_)


# ---- misc ------------------------------------------------------------------

def max_id(input, name: Optional[str] = None):
    def run(ctx, x):
        return jnp.argmax(_val(x), axis=-1)
    return _node("max_id", run, [input], name=name)


def sampling_id(input, name: Optional[str] = None):
    """Sample a class id from a probability row (sampling_id_layer twin)."""
    def run(ctx, x):
        from paddle_tpu.nn.module import next_rng_key
        import jax
        p = _val(x)
        return jax.random.categorical(next_rng_key(), jnp.log(p + 1e-9),
                                      axis=-1)
    return _node("sampling_id", run, [input], name=name)


def eos(input, eos_id: int, name: Optional[str] = None):
    """1.0 where the argmax id equals ``eos_id`` (eos_layer twin)."""
    def run(ctx, x, **a):
        ids = _val(x)
        if ids.ndim > 1:
            ids = jnp.argmax(ids, axis=-1)
        return (ids == a["eos_id"]).astype(jnp.float32)
    return _node("eos", run, [input], name=name, eos_id=eos_id)


def print_layer(input, label: str = "", name: Optional[str] = None):
    """Debug-print a node's value at trace/run time (print_layer twin,
    PrintLayer.cpp) via jax.debug.print; passes the value through."""
    def run(ctx, x, **a):
        import jax
        safe = a["label"].replace("{", "{{").replace("}", "}}")
        jax.debug.print(safe + " {}", _val(x))
        return x
    return _node("print", run, [input], name=name, label=label or "print")


# ---- remaining registered-layer twins (completeness sweep) -----------------

def prelu(input, init_slope: float = 0.25, name: Optional[str] = None):
    """Parametric ReLU (prelu_layer twin, PReluLayer)."""
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.PReLU(a["init_slope"], name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("prelu", name)
    return _node("prelu", run, [input], name=n, init_slope=init_slope,
                 _name=n)


def clip(input, min: float, max: float, name: Optional[str] = None):
    """Elementwise clamp (clip_layer twin, ClipLayer)."""
    def run(ctx, x, **a):
        m = _mask(x)
        y = jnp.clip(_val(x), a["min_v"], a["max_v"])
        return (y, m) if m is not None else y
    return _node("clip", run, [input], name=name, min_v=min, max_v=max)


def resize(input, size: int, name: Optional[str] = None):
    """Reshape each sample batch to rows of width ``size`` (resize_layer
    twin, ResizeLayer)."""
    def run(ctx, x, **a):
        return _val(x).reshape(-1, a["size"])
    return _node("resize", run, [input], name=name, size=size)


def scale_shift(input, bias: bool = True, name: Optional[str] = None):
    """Scalar learned scale + shift (scale_shift_layer twin)."""
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.ScaleShift(bias=a["bias"], name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("scale_shift", name)
    return _node("scale_shift", run, [input], name=n, bias=bias, _name=n)


def row_l2_norm(input, name: Optional[str] = None):
    """Row-wise L2 normalization (row_l2_norm_layer twin)."""
    def run(ctx, x):
        m = _mask(x)
        y = nn.RowL2Norm()(_val(x))
        return (y, m) if m is not None else y
    return _node("row_l2_norm", run, [input], name=name)


def cross_channel_norm(input, name: Optional[str] = None):
    """L2 normalize across channels with learned per-channel scale
    (cross_channel_norm_layer twin — SSD's Normalize)."""
    def run(ctx, x, **a):
        return nn.CrossChannelNorm(name=a["_name"])(x)
    n = auto_name("cross_channel_norm", name)
    return _node("cross_channel_norm", run, [input], name=n, _name=n)


def out_prod(input_a, input_b, name: Optional[str] = None):
    """Flattened outer product (out_prod_layer twin, OuterProdLayer)."""
    def run(ctx, x, y):
        return nn.OutProd()(_val(x), _val(y))
    return _node("out_prod", run, [input_a, input_b], name=name)


def tensor(input_a, input_b, size: int, act: str = "linear",
           bias: bool = True, name: Optional[str] = None):
    """Bilinear tensor product layer (tensor_layer twin, TensorLayer)."""
    def run(ctx, x, y, **a):
        return nn.TensorLayer(a["size"], act=a["act"], bias=a["bias"],
                              name=a["_name"])(_val(x), _val(y))
    n = auto_name("tensor", name)
    return _node("tensor", run, [input_a, input_b], name=n, size=size,
                 act=act, bias=bias, _name=n)


def gated_unit(input, size: int, act: str = "linear",
               name: Optional[str] = None):
    """act(xW) * sigmoid(xW_g) (gated_unit_layer twin)."""
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.GatedUnit(a["size"], act=a["act"], name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("gated_unit", name)
    return _node("gated_unit", run, [input], name=n, size=size, act=act,
                 _name=n)


def conv_shift(input_a, input_b, name: Optional[str] = None):
    """Circular correlation (conv_shift_layer twin, ConvShiftLayer)."""
    def run(ctx, x, y):
        return nn.ConvShift()(_val(x), _val(y))
    return _node("conv_shift", run, [input_a, input_b], name=name)


def row_conv(input, future_steps: int, name: Optional[str] = None):
    """Lookahead row convolution over a sequence (row_conv_layer twin,
    RowConvLayer — the DeepSpeech2 op)."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "row_conv needs a sequence input")
        v, m = x
        # zero padding frames FIRST: the lookahead window at positions
        # near a sequence end must not read garbage beyond the length
        # (the reference RowConvOp truncates context at the boundary)
        v = jnp.where(m[..., None], v, 0.0)
        y = nn.RowConv(a["future_steps"], name=a["_name"])(v)
        y = jnp.where(m[..., None], y, 0.0)
        return (y, m)
    n = auto_name("row_conv", name)
    return _node("row_conv", run, [input], name=n,
                 future_steps=future_steps, _name=n)


def switch_order(input, perm, name: Optional[str] = None):
    """Dimension permutation (switch_order_layer twin, SwitchOrderLayer)."""
    def run(ctx, x, **a):
        return nn.SwitchOrder(a["perm"])(_val(x))
    return _node("switch_order", run, [input], name=name, perm=tuple(perm))


def img_conv3d(input, channels: int, kernel=3, stride=1, act: str = "relu",
               padding="SAME", name: Optional[str] = None):
    """3-D convolution, NDHWC (img_conv3d_layer twin, Conv3DLayer)."""
    def run(ctx, x, **a):
        return nn.Conv3D(a["channels"], a["kernel"], stride=a["stride"],
                         padding=a["padding"], act=a["act"],
                         name=a["_name"])(x)
    n = auto_name("img_conv3d", name)
    return _node("img_conv3d", run, [input], name=n, channels=channels,
                 kernel=kernel, stride=stride, act=act, padding=padding,
                 _name=n)


def img_pool3d(input, kernel=2, stride=None, pool_type: str = "max",
               name: Optional[str] = None):
    """3-D pooling (img_pool3d_layer twin, Pool3DLayer)."""
    def run(ctx, x, **a):
        return nn.Pool3D(a["kernel"], stride=a["stride"],
                         pool_type=a["pool_type"])(x)
    return _node("img_pool3d", run, [input], name=name, kernel=kernel,
                 stride=stride, pool_type=pool_type)


def get_output(input, arg_name: str, name: Optional[str] = None):
    """Fetch a named auxiliary output of a multi-output layer
    (get_output_layer twin, GetOutputLayer): e.g. the cell state of
    ``lstm_step`` via ``arg_name="state"``."""
    def run(ctx, x, **a):
        key = f"{a['_src']}:{a['arg_name']}"
        enforce(key in ctx.aux,
                "get_output: no auxiliary output %r (have %s)", key,
                sorted(ctx.aux))
        return ctx.aux[key]
    return _node("get_output", run, [input], name=name, arg_name=arg_name,
                 _src=input.name)


def lstm_step(input, state, size: int, act: str = "tanh",
              gate_act: str = "sigmoid", name: Optional[str] = None):
    """One LSTM step for use inside ``recurrent_group`` (lstm_step_layer
    twin, LstmStepLayer): ``input`` is the pre-computed 4h gate
    projection, ``state`` the previous cell (a ``memory``).  Returns the
    hidden; fetch the new cell with ``get_output(h, "state")``."""
    from paddle_tpu.ops import activations as act_ops
    def run(ctx, gates, c_prev, **a):
        h = a["size"]
        g = _val(gates)
        enforce(g.shape[-1] == 4 * h,
                "lstm_step input must be 4*size gates, got %d", g.shape[-1])
        ga = act_ops.get(a["gate_act"])
        av = act_ops.get(a["act"])
        i, f, gg, o = jnp.split(g, 4, axis=-1)
        c = ga(f) * _val(c_prev) + ga(i) * av(gg)
        hh = ga(o) * av(c)
        ctx.aux[f"{a['_name']}:state"] = c
        return hh
    n = auto_name("lstm_step", name)
    return _node("lstm_step", run, [input, state], name=n, size=size,
                 act=act, gate_act=gate_act, _name=n)


def gru_step(input, output_mem, size: int, act: str = "tanh",
             gate_act: str = "sigmoid", name: Optional[str] = None):
    """One GRU step for ``recurrent_group`` (gru_step_layer twin,
    GruStepLayer): ``input`` is the 3h projection of x, ``output_mem``
    the previous hidden (a ``memory``)."""
    from paddle_tpu.ops import activations as act_ops
    def run(ctx, gates, h_prev, **a):
        h = a["size"]
        g = _val(gates)
        enforce(g.shape[-1] == 3 * h,
                "gru_step input must be 3*size gates, got %d", g.shape[-1])
        from paddle_tpu.core.dtypes import get_policy
        from paddle_tpu.nn.module import param
        from paddle_tpu.nn import initializers as init
        from paddle_tpu.nn.recurrent import gru_cell
        policy = get_policy()
        w_hz = param(f"{a['_name']}/w_hz", (h, 2 * h), policy.param_dtype,
                     init.paddle_default())
        w_hc = param(f"{a['_name']}/w_hc", (h, h), policy.param_dtype,
                     init.paddle_default())
        return gru_cell(g, _val(h_prev), policy.cast_to_compute(w_hz),
                        policy.cast_to_compute(w_hc),
                        act_ops.get(a["act"]), act_ops.get(a["gate_act"]),
                        policy)
    n = auto_name("gru_step", name)
    return _node("gru_step", run, [input, output_mem], name=n, size=size,
                 act=act, gate_act=gate_act, _name=n)


def gru_step_naive(input, output_mem, size: int, act: str = "tanh",
                   gate_act: str = "sigmoid", name: Optional[str] = None):
    """Unfused-reference-equivalent GRU step (gru_step_naive_layer twin)
    — numerically identical to :func:`gru_step` here, since XLA fuses
    either form the same way."""
    return gru_step(input, output_mem, size, act, gate_act, name)


# ---- projection / operator constructors for mixed() ------------------------

def full_matrix_projection(size: int):
    """x @ W projection (full_matrix_projection twin)."""
    return nn.FullMatrixProjection(size)


def trans_full_matrix_projection(size: int):
    """x @ W^T projection (trans_full_matrix_projection twin)."""
    return nn.TransposedFullMatrixProjection(size)


def identity_projection(offset: int = 0, size: Optional[int] = None):
    """Pass-through / offset projection (identity_projection twin)."""
    return nn.IdentityProjection(offset=offset, size=size)


def table_projection(size: int, vocab_size: int):
    """Embedding-lookup projection (table_projection twin)."""
    return nn.TableProjection(size, vocab_size)


def scaling_projection():
    """Learned-scalar projection (scaling_projection twin)."""
    return nn.ScalingProjection()


def dotmul_projection():
    """Learned elementwise-scale projection (dotmul_projection twin)."""
    return nn.DotMulProjection()


def slice_projection(slices):
    """Column-slice-concat projection (slice_projection twin)."""
    return nn.SliceProjection(slices)


def conv_projection(channels: int, kernel=3, stride=1, padding="SAME"):
    """Convolution-as-projection (conv_projection / conv_operator twin,
    flattened output so it sums with other projections)."""
    return nn.ConvProjection(channels, kernel, stride, padding)


def conv_operator(img, filter, channels: int, kernel: int,
                  name: Optional[str] = None):
    """Convolve an image layer with a *filter layer* (conv_operator twin,
    ConvOperator — the filter comes from the graph, not parameters).
    ``img`` is NHWC; ``filter`` is [b, kernel*kernel*in_ch*channels],
    applied per-sample."""
    def run(ctx, x, w, **a):
        import jax
        v, f = _val(x), _val(w)
        k, c = a["kernel"], a["channels"]
        in_ch = v.shape[-1]
        f = f.reshape(f.shape[0], k, k, in_ch, c)
        def one(img1, w1):
            return jax.lax.conv_general_dilated(
                img1[None], w1, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))[0]
        return jax.vmap(one)(v, f)
    return _node("conv_operator", run, [img, filter], name=name,
                 channels=channels, kernel=kernel)


def dotmul_operator(input_a, input_b, scale: float = 1.0,
                    name: Optional[str] = None):
    """scale * x .* y (dotmul_operator twin, DotMulOperator)."""
    def run(ctx, x, y, **a):
        return a["scale"] * _val(x) * _val(y)
    return _node("dotmul_operator", run, [input_a, input_b], name=name,
                 scale=scale)


# ---- remaining cost layers -------------------------------------------------

def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha:
                                float = 0.1, name: Optional[str] = None):
    """CE plus an alpha * log(Z)^2 self-normalization penalty
    (cross_entropy_with_selfnorm twin, MultiClassCrossEntropyWithSelfNorm)
    — keeps the softmax partition function near 1 so inference can skip
    normalization."""
    def run(ctx, logits, y, **a):
        import jax
        v = _val(logits)
        log_z = jax.scipy.special.logsumexp(v, axis=-1)
        ce = loss_ops.softmax_cross_entropy(v, _val(y))
        _record_label(ctx, v, _val(y))
        return (ce + a["alpha"] * jnp.square(log_z)).mean()
    return _node("ce_selfnorm", run, [input, label], name=name,
                 alpha=softmax_selfnorm_alpha)


def cross_entropy_over_beam(beams, name: Optional[str] = None):
    """Beam-level cross-entropy (cross_entropy_over_beam twin,
    CrossEntropyOverBeam.cpp): ``beams`` is a list of (scores, gold)
    node pairs — per-slot candidate scores [b, k] and the gold candidate
    index [b] (or -1 when the gold fell out of the beam; such slots are
    skipped, matching the reference's cost-of-dropped-gold = 0).  Items
    may also be ``BeamInput`` objects (candidate_scores/gold attributes;
    ``selected_candidates`` is implicit here — scores are already per
    selected candidate)."""
    flat = []
    for beam in beams:
        if hasattr(beam, "candidate_scores"):
            s, g = beam.candidate_scores, beam.gold
        else:
            s, g = beam
        flat.extend([s, g])
    def run(ctx, *vals):
        total = 0.0
        count = None
        for i in range(0, len(vals), 2):
            scores, gold = _val(vals[i]), _val(vals[i + 1])
            valid = gold >= 0
            safe_gold = jnp.where(valid, gold, 0)
            ce = loss_ops.softmax_cross_entropy(scores, safe_gold)
            ce = jnp.where(valid, ce, 0.0)
            total = total + ce.sum()
            c = valid.sum()
            count = c if count is None else count + c
        return total / jnp.maximum(count, 1).astype(jnp.float32)
    return _node("ce_over_beam", run, flat, name=name)


def warp_ctc(input, label, blank: int = 0, name: Optional[str] = None):
    """warp_ctc_layer twin — same loss as :func:`ctc_cost` (one CTC
    implementation serves both registrations on TPU)."""
    return ctc_cost(input, label, blank=blank, name=name)


# ---- detection layers ------------------------------------------------------

def priorbox(input, image_hw, min_sizes, max_sizes=(),
             aspect_ratios=(2.0,), name: Optional[str] = None):
    """Anchor grid for a feature-map node (priorbox_layer twin,
    PriorBoxLayer): emits [P, 4] prior boxes, computed from the node's
    static spatial shape."""
    def run(ctx, x, **a):
        v = _val(x)
        from paddle_tpu.ops import detection as det
        boxes = det.prior_boxes((v.shape[1], v.shape[2]), a["image_hw"],
                                a["min_sizes"], a["max_sizes"],
                                a["aspect_ratios"])
        return jnp.asarray(boxes)
    return _node("priorbox", run, [input], name=name,
                 image_hw=tuple(image_hw), min_sizes=tuple(min_sizes),
                 max_sizes=tuple(max_sizes),
                 aspect_ratios=tuple(aspect_ratios))


def multibox_loss(loc_pred, conf_logits, priors, gt_boxes, gt_labels,
                  gt_mask, neg_pos_ratio: float = 3.0,
                  threshold: float = 0.5, name: Optional[str] = None):
    """SSD MultiBox loss node (multibox_loss_layer twin)."""
    def run(ctx, loc, conf, pri, gtb, gtl, gtm, **a):
        from paddle_tpu.ops import detection as det
        return det.multibox_loss(_val(loc), _val(conf), _val(pri),
                                 _val(gtb), _val(gtl), _val(gtm),
                                 a["neg_pos_ratio"], a["threshold"])
    return _node("multibox_loss", run,
                 [loc_pred, conf_logits, priors, gt_boxes, gt_labels,
                  gt_mask], name=name, neg_pos_ratio=neg_pos_ratio,
                 threshold=threshold)


def detection_output(loc_pred, conf_logits, priors,
                     score_threshold: float = 0.01,
                     iou_threshold: float = 0.45, keep_top_k: int = 100,
                     name: Optional[str] = None):
    """Decode + per-class NMS (detection_output_layer twin)."""
    def run(ctx, loc, conf, pri, **a):
        from paddle_tpu.ops import detection as det
        import jax
        return jax.vmap(
            lambda l, c: det.detection_output(
                l, c, _val(pri), a["score_threshold"], a["iou_threshold"],
                a["keep_top_k"]))(_val(loc), _val(conf))
    return _node("detection_output", run, [loc_pred, conf_logits, priors],
                 name=name, score_threshold=score_threshold,
                 iou_threshold=iou_threshold, keep_top_k=keep_top_k)


def crf_decoding(input, num_tags: int, label=None,
                 parameter_name: Optional[str] = None,
                 name: Optional[str] = None):
    """Viterbi decode with the CRF's transition parameters
    (crf_decoding_layer twin): emits the best tag path [b, t]; with
    ``label`` emits the per-step error indicator instead.  Pass
    ``parameter_name`` equal to the ``crf_cost`` node's name to share its
    trained transitions."""
    def run(ctx, emissions, *rest, **a):
        enforce(_is_seq(emissions), "crf_decoding needs sequence emissions")
        val, mask = emissions
        from paddle_tpu.ops import crf as crf_ops
        from paddle_tpu.nn.module import param
        from paddle_tpu.nn import initializers as init
        k = a["num_tags"]
        pname = a["param_name"]
        trans = param(f"{pname}/transitions", (k, k), jnp.float32,
                      init.zeros)
        start = param(f"{pname}/start", (k,), jnp.float32, init.zeros)
        stop = param(f"{pname}/stop", (k,), jnp.float32, init.zeros)
        path = crf_ops.crf_decode(val, mask, trans, start, stop)
        if isinstance(path, tuple):
            path = path[0]
        if rest:
            y = _val(rest[0])
            err = (path != y) & mask
            return (err.astype(jnp.float32), mask)
        return (path, mask)
    n = auto_name("crf_decoding", name)
    inputs = [input] if label is None else [input, label]
    return _node("crf_decoding", run, inputs, name=n, num_tags=num_tags,
                 param_name=parameter_name or n, _name=n)


def recurrent(input, act: str = "tanh", reverse: bool = False,
              name: Optional[str] = None):
    """Full-sequence simple RNN (recurrent_layer twin, RecurrentLayer):
    the input is the pre-computed projection; only the h-recurrence
    scans."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "recurrent needs a sequence input")
        from paddle_tpu.nn.recurrent import SimpleRNN
        hs, _ = SimpleRNN(x[0].shape[-1], act=a["act"],
                          reverse=a["reverse"], project_input=False,
                          name=a["_name"])(x[0], x[1])
        return (hs, x[1])
    n = auto_name("recurrent", name)
    return _node("recurrent", run, [input], name=n, act=act,
                 reverse=reverse, _name=n)
