"""Declarative layer functions (the ``paddle.v2.layer`` /
``trainer_config_helpers/layers.py`` twin).

Each function returns a :class:`LayerOutput` node; calling conventions
mirror the v1 helper API (``layers.py:34`` — ``fc_layer``, ``embedding``,
``lstmemory``, cost layers...) while the bodies are thin closures over the
``paddle_tpu.nn`` modules and ``paddle_tpu.ops`` functions, created with
stable names so parameters live at predictable paths.

Sequence-valued nodes are (value, mask) pairs — the TPU-native stand-in for
the reference's ``Argument.sequenceStartPositions`` padding-free batches.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.core.errors import enforce
from paddle_tpu.api.graph import LayerOutput, auto_name
from paddle_tpu.ops import losses as loss_ops
from paddle_tpu.ops import sequence as seq_ops


def _node(kind, fn, inputs, name=None, **attrs):
    return LayerOutput(name=auto_name(kind, name), kind=kind, fn=fn,
                       inputs=tuple(inputs),
                       attrs=tuple(sorted(attrs.items())))


def _is_seq(v) -> bool:
    return isinstance(v, tuple) and len(v) == 2


def _val(v):
    return v[0] if _is_seq(v) else v


def _mask(v):
    return v[1] if _is_seq(v) else None


# ---- inputs ----------------------------------------------------------------

def data(name: str, dtype: str = "float32", sequence: bool = False):
    """Input node reading ``batch[name]`` (v2 ``layer.data`` twin).  With
    ``sequence=True`` the node reads ``batch[name]`` and
    ``batch[name + "_mask"]`` as a (value, mask) pair."""
    if not sequence:
        return LayerOutput(name=name, kind="data")
    base = LayerOutput(name=name, kind="data")
    mask = LayerOutput(name=f"{name}_mask", kind="data")
    return _node("seq_pair", lambda ctx, v, m: (v, m), [base, mask],
                 name=f"{name}_seq")


# ---- core layers -----------------------------------------------------------

def fc(input, size: int, act: str = "linear", bias: bool = True,
       name: Optional[str] = None):
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.Linear(a["size"], act=a["act"], bias=a["bias"],
                      name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("fc", name)
    return _node("fc", run, [input], name=n, size=size, act=act, bias=bias,
                 _name=n)


def embedding(input, size: int, vocab_size: int, name: Optional[str] = None):
    def run(ctx, ids, **a):
        m = _mask(ids)
        y = nn.Embedding(a["vocab_size"], a["size"], name=a["_name"])(_val(ids))
        return (y, m) if m is not None else y
    n = auto_name("embedding", name)
    return _node("embedding", run, [input], name=n, size=size,
                 vocab_size=vocab_size, _name=n)


def conv2d(input, channels: int, kernel: int = 3, stride: int = 1,
           act: str = "relu", padding="SAME", name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.Conv2D(a["channels"], a["kernel"], stride=a["stride"],
                         padding=a["padding"], act=a["act"],
                         name=a["_name"])(x)
    n = auto_name("conv2d", name)
    return _node("conv2d", run, [input], name=n, channels=channels,
                 kernel=kernel, stride=stride, act=act, padding=padding,
                 _name=n)


def pool2d(input, kernel: int = 2, stride: Optional[int] = None,
           pool_type: str = "max", name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.Pool2D(a["kernel"], stride=a["stride"],
                         pool_type=a["pool_type"])(x)
    return _node("pool2d", run, [input], name=name, kernel=kernel,
                 stride=stride, pool_type=pool_type)


def batch_norm(input, act: str = "linear", name: Optional[str] = None):
    def run(ctx, x, **a):
        return nn.BatchNorm(act=a["act"], name=a["_name"])(x)
    n = auto_name("batch_norm", name)
    return _node("batch_norm", run, [input], name=n, act=act, _name=n)


def dropout(input, rate: float, name: Optional[str] = None):
    def run(ctx, x, **a):
        m = _mask(x)
        y = nn.Dropout(a["rate"], name=a["_name"])(_val(x))
        return (y, m) if m is not None else y
    n = auto_name("dropout", name)
    return _node("dropout", run, [input], name=n, rate=rate, _name=n)


def concat(inputs: Sequence[LayerOutput], name: Optional[str] = None):
    def run(ctx, *xs):
        return jnp.concatenate([_val(x) for x in xs], axis=-1)
    return _node("concat", run, list(inputs), name=name)


def addto(inputs: Sequence[LayerOutput], act: str = "linear",
          name: Optional[str] = None):
    def run(ctx, *xs, **a):
        return nn.Addto(act=a["act"], name=a["_name"])(*[_val(x) for x in xs])
    n = auto_name("addto", name)
    return _node("addto", run, list(inputs), name=n, act=act, _name=n)


# ---- recurrent / sequence --------------------------------------------------

def lstmemory(input, size: int, reverse: bool = False,
              name: Optional[str] = None):
    """Full-sequence LSTM over a (value, mask) pair (lstmemory twin)."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "lstmemory needs a sequence input")
        from paddle_tpu.nn.recurrent import LSTM
        hs, _ = LSTM(a["size"], reverse=a["reverse"], name=a["_name"])(
            x[0], x[1])
        return (hs, x[1])
    n = auto_name("lstmemory", name)
    return _node("lstmemory", run, [input], name=n, size=size,
                 reverse=reverse, _name=n)


def grumemory(input, size: int, reverse: bool = False,
              name: Optional[str] = None):
    def run(ctx, x, **a):
        enforce(_is_seq(x), "grumemory needs a sequence input")
        from paddle_tpu.nn.recurrent import GRU
        hs, _ = GRU(a["size"], reverse=a["reverse"], name=a["_name"])(
            x[0], x[1])
        return (hs, x[1])
    n = auto_name("grumemory", name)
    return _node("grumemory", run, [input], name=n, size=size,
                 reverse=reverse, _name=n)


def seq_pool(input, pool_type: str = "avg", name: Optional[str] = None):
    """Sequence pooling to a fixed vector (pooling_layer twin)."""
    def run(ctx, x, **a):
        enforce(_is_seq(x), "seq_pool needs a sequence input")
        return seq_ops.sequence_pool(x[0], x[1], a["pool_type"])
    return _node("seq_pool", run, [input], name=name, pool_type=pool_type)


def last_seq(input, name: Optional[str] = None):
    def run(ctx, x):
        return seq_ops.last_seq(x[0], x[1])
    return _node("last_seq", run, [input], name=name)


def first_seq(input, name: Optional[str] = None):
    def run(ctx, x):
        return seq_ops.first_seq(x[0], x[1])
    return _node("first_seq", run, [input], name=name)


def context_projection(input, context_len: int, context_start: int,
                       name: Optional[str] = None):
    def run(ctx, x, **a):
        y = seq_ops.context_projection(x[0], x[1], a["context_len"],
                                       a["context_start"])
        return (y, x[1])
    return _node("context_projection", run, [input], name=name,
                 context_len=context_len, context_start=context_start)


# ---- costs -----------------------------------------------------------------

def _record_label(ctx, logits, label, extra=None):
    ctx.outputs["logits"] = logits
    ctx.outputs["label"] = label
    if extra:
        ctx.outputs.update(extra)


def classification_cost(input, label, name: Optional[str] = None):
    """Softmax cross-entropy against integer labels
    (classification_cost twin).  Records logits/label for evaluators."""
    def run(ctx, logits, y):
        logits = _val(logits)
        _record_label(ctx, logits, y)
        return loss_ops.softmax_cross_entropy(logits, y).mean()
    return _node("classification_cost", run, [input, label], name=name)


def square_error_cost(input, label, name: Optional[str] = None):
    def run(ctx, pred, y):
        pred = _val(pred)
        ctx.outputs["pred"] = pred
        ctx.outputs["label"] = y
        return loss_ops.square_error(pred, y).mean()
    return _node("square_error_cost", run, [input, label], name=name)


def cross_entropy_with_sequence(input, label, name: Optional[str] = None):
    """Per-step CE over a (logits, mask) sequence vs int labels [b, t]."""
    def run(ctx, logits, y):
        enforce(_is_seq(logits), "needs sequence logits")
        val, mask = logits
        ce = loss_ops.softmax_cross_entropy(val, y)
        m = mask.astype(val.dtype)
        _record_label(ctx, val, y, {"label_mask": mask})
        return (ce * m).sum() / jnp.maximum(m.sum(), 1.0)
    return _node("seq_cross_entropy", run, [input, label], name=name)


def crf_cost(input, label, num_tags: int, name: Optional[str] = None):
    """Linear-chain CRF negative log-likelihood over a sequence
    (crf_layer twin, ``LinearChainCRF.cpp``)."""
    def run(ctx, emissions, y, **a):
        enforce(_is_seq(emissions), "crf needs sequence emissions")
        val, mask = emissions
        from paddle_tpu.ops import crf as crf_ops
        from paddle_tpu.nn.module import param
        from paddle_tpu.nn import initializers as init
        k = a["num_tags"]
        trans = param(f"{a['_name']}/transitions", (k, k), jnp.float32,
                      init.zeros)
        start = param(f"{a['_name']}/start", (k,), jnp.float32, init.zeros)
        stop = param(f"{a['_name']}/stop", (k,), jnp.float32, init.zeros)
        ll = crf_ops.crf_log_likelihood(val, y, mask, trans, start, stop)
        ctx.outputs["emissions"] = val
        ctx.outputs["label"] = y
        ctx.outputs["label_mask"] = mask
        return -ll.mean()
    n = auto_name("crf", name)
    return _node("crf", run, [input, label], name=n, num_tags=num_tags,
                 _name=n)


# ---- misc ------------------------------------------------------------------

def max_id(input, name: Optional[str] = None):
    def run(ctx, x):
        return jnp.argmax(_val(x), axis=-1)
    return _node("max_id", run, [input], name=name)
