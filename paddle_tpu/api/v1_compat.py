"""v1 ``trainer_config_helpers`` name-compat surface.

The reference has two user-facing layer namespaces: the v1 helper API with
``*_layer``-suffixed names (``trainer_config_helpers/layers.py:34``) and the
v2 API that strips the suffix by reflection (``v2/layer.py``).  Our DSL
(:mod:`paddle_tpu.api.layer`) follows the v2 naming; this module republishes
every public v1 helper name so a reference user can port a v1 config by
changing only the import line:

    from paddle_tpu.api.v1_compat import *

    out = fc_layer(input=img, size=10, act="softmax")

Each alias binds the same callable — no wrapper, no behavior drift.
"""

from __future__ import annotations

from paddle_tpu.api import layer as _L
from paddle_tpu.api.graph import LayerOutput                        # noqa: F401
from paddle_tpu.core.errors import ConfigError
from paddle_tpu.api.recurrent import (GeneratedInput, StaticInput,  # noqa: F401
                                      beam_search, memory,
                                      recurrent_group)


class AggregateLevel:
    """Sequence aggregation levels (AggregateLevel twin).  Here nesting is
    carried by the mask's rank, so the level is implied by the input — the
    constants exist for config compatibility."""
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    """Sequence expansion levels (ExpandLevel twin); see AggregateLevel."""
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE


class LayerType:
    """Node-kind names (LayerType twin): our graph kinds are plain strings;
    this namespace exists for config compatibility."""
    DATA = "data"
    FC_LAYER = "fc"
    CONV_LAYER = "conv2d"
    POOL_LAYER = "pool2d"
    BATCH_NORM_LAYER = "batch_norm"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "grumemory"
    RECURRENT_LAYER = "recurrent"
    MIXED_LAYER = "mixed"
    COST = "cost"


class BaseGeneratedInput:
    """Base marker for generation-mode inputs (BaseGeneratedInput twin)."""


# A nested-sequence group input needs no wrapper here: recurrent_group
# detects nesting from the mask rank (SubsequenceInput semantics).
def SubsequenceInput(input):
    return input


class BeamInput:
    """One beam for cross_entropy_over_beam (BeamInput twin): scores over
    candidates, the selected top-k candidate ids, and the gold index.
    ``selected_candidates`` is accepted for signature compatibility; the
    loss here consumes scores-per-selected-candidate + gold directly."""

    def __init__(self, candidate_scores, selected_candidates=None,
                 gold=None):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold

    def as_pair(self):
        return (self.candidate_scores, self.gold)

# ---- v1 name → DSL callable ------------------------------------------------

def data_layer(name, size=None, type=None, dtype: str = "float32",
               sequence: bool = False, **kw):
    """v1 ``data_layer(name=..., size=...)`` twin.  ``size`` is metadata
    (shapes come from the data here).  Sequence-ness and int dtype are
    inferred from the config's ``define_py_data_sources2`` provider
    declaration when present, so a v1 config file needs no changes; a
    v2-style ``type=`` spec also works."""
    if type is not None:                       # v2-style spec
        from paddle_tpu.v2 import _DataType
        if isinstance(type, _DataType):
            sequence = type.sequence
            if "int" in type.feed_type.__class__.__name__.lower():
                dtype = "int32"
    else:
        from paddle_tpu.api import config as _cfg
        ds = _cfg._recorded.get("data_sources")
        if ds is not None:
            import importlib
            try:
                mod = (ds["module"] if not isinstance(ds["module"], str)
                       else importlib.import_module(ds["module"]))
                types = getattr(getattr(mod, ds["train_obj"]),
                                "input_types", None) or {}
            except (ImportError, AttributeError):
                # AttributeError too: a misspelled obj name in
                # define_py_data_sources2 should surface in
                # _check_data_declarations (which reports it against the
                # data source), not as a crash inside data_layer.
                types = {}
            spec = types.get(name) if isinstance(types, dict) else None
            if spec is not None:
                kind = spec.__class__.__name__
                sequence = "Sequence" in kind
                if "Int" in kind:
                    dtype = "int32"
    return _L.data(name, dtype=dtype, sequence=sequence)
fc_layer = _L.fc
embedding_layer = _L.embedding
img_conv_layer = _L.conv2d
img_conv3d_layer = _L.img_conv3d
img_pool_layer = _L.pool2d
img_pool3d_layer = _L.img_pool3d
batch_norm_layer = _L.batch_norm
dropout_layer = _L.dropout
concat_layer = _L.concat
addto_layer = _L.addto
lstmemory = _L.lstmemory
grumemory = _L.grumemory
recurrent_layer = _L.recurrent
lstm_step_layer = _L.lstm_step
gru_step_layer = _L.gru_step
gru_step_naive_layer = _L.gru_step_naive
get_output_layer = _L.get_output


class BasePoolingType:
    """v1 pooling-type object (``trainer_config_helpers/poolings.py:23``)."""

    def __init__(self, kind):
        self.kind = kind


class MaxPooling(BasePoolingType):
    def __init__(self, output_max_index=None):
        if output_max_index:
            raise ConfigError(
                "MaxPooling(output_max_index=True) is not supported: the "
                "TPU build pools values, not argmax indices")
        super().__init__("max")


class AvgPooling(BasePoolingType):
    STRATEGY_AVG = "average"
    STRATEGY_SUM = "sum"
    STRATEGY_SQROOTN = "squarerootn"

    def __init__(self, strategy=STRATEGY_AVG):
        kinds = {self.STRATEGY_AVG: "avg", self.STRATEGY_SUM: "sum",
                 self.STRATEGY_SQROOTN: "sqrt"}
        if strategy not in kinds:
            raise ConfigError(
                f"AvgPooling strategy {strategy!r} unknown "
                f"(valid: {sorted(kinds)})")
        super().__init__(kinds[strategy])


class SumPooling(AvgPooling):
    def __init__(self):
        super().__init__(AvgPooling.STRATEGY_SUM)


class SquareRootNPooling(AvgPooling):
    def __init__(self):
        super().__init__(AvgPooling.STRATEGY_SQROOTN)


def pooling_layer(input, pooling_type=None, name=None,
                  agg_level=AggregateLevel.TO_NO_SEQUENCE,
                  stride=-1, **kwargs):
    """Sequence pooling with the v1 default (MaxPooling when
    ``pooling_type`` is omitted — ``layers.py:1376``); accepts the v1
    pooling-type objects or plain strings.

    ``agg_level`` defaults to the reference's TO_NO_SEQUENCE
    (``layers.py:1347``) and is validated against the input's nesting at
    run time — a nested input with the default level pools differently in
    the reference (one vector for the whole nested sequence) than this
    build's nesting-follows-input rule, so it errors instead of silently
    training different semantics (pass EACH_SEQUENCE for per-sub-sequence
    pooling).  Sliding-window pooling (``stride > 0``, reference
    ``layers.py:1353``) has no twin and errors likewise."""
    if stride is not None and stride > 0:
        raise ConfigError(
            "pooling_layer(stride>0) sliding-window pooling is not "
            "supported in the TPU build (only whole-/sub-sequence "
            "aggregation); got stride=%r" % (stride,))
    if pooling_type is None:
        kind = "max"
    elif isinstance(pooling_type, str):
        kind = pooling_type
    else:
        kind = pooling_type.kind
    return _L.seq_pool(input, pool_type=kind, name=name,
                       agg_level=agg_level)
seq_reshape_layer = _L.seq_reshape
seq_concat_layer = _L.seq_concat
seq_slice_layer = _L.seq_slice
sub_nested_seq_layer = _L.sub_nested_seq
kmax_seq_score_layer = _L.kmax_seq_score
first_seq = _L.first_seq
last_seq = _L.last_seq
expand_layer = _L.expand
repeat_layer = _L.repeat
rotate_layer = _L.rotate
resize_layer = _L.resize
trans_layer = _L.trans
crop_layer = _L.crop
pad_layer = _L.pad
block_expand_layer = _L.block_expand
maxout_layer = _L.maxout
spp_layer = _L.spp
img_cmrnorm_layer = _L.img_cmrnorm
bilinear_interp_layer = _L.bilinear_interp
interpolation_layer = _L.interpolation
scaling_layer = _L.scaling
slope_intercept_layer = _L.slope_intercept
sum_to_one_norm_layer = _L.sum_to_one_norm
data_norm_layer = _L.data_norm
mdlstm_layer = _L.mdlstm
row_l2_norm_layer = _L.row_l2_norm
cross_channel_norm_layer = _L.cross_channel_norm
clip_layer = _L.clip
power_layer = _L.power
mixed_layer = _L.mixed
linear_comb_layer = _L.linear_comb
cos_sim = _L.cos_sim
out_prod_layer = _L.out_prod
tensor_layer = _L.tensor
gated_unit_layer = _L.gated_unit
conv_shift_layer = _L.conv_shift
row_conv_layer = _L.row_conv
switch_order_layer = _L.switch_order
multiplex_layer = _L.multiplex
selective_fc_layer = _L.selective_fc
prelu_layer = _L.prelu
scale_shift_layer = _L.scale_shift
maxid_layer = _L.max_id
sampling_id_layer = _L.sampling_id
eos_layer = _L.eos
printer_layer = _L.print_layer
print_layer = _L.print_layer
convex_comb_layer = _L.linear_comb


def layer_support(*args, **kwargs):
    """No-op decorator (layer_support twin): device/dropout attrs are
    handled by the DSL functions themselves here."""
    def deco(fn):
        return fn
    return deco

# projections / operators (same names in v1)
full_matrix_projection = _L.full_matrix_projection
trans_full_matrix_projection = _L.trans_full_matrix_projection
identity_projection = _L.identity_projection
table_projection = _L.table_projection
scaling_projection = _L.scaling_projection
dotmul_projection = _L.dotmul_projection
slice_projection = _L.slice_projection
conv_projection = _L.conv_projection
context_projection = _L.context_projection
conv_operator = _L.conv_operator
dotmul_operator = _L.dotmul_operator

# cost layers
classification_cost = _L.classification_cost
square_error_cost = _L.square_error_cost
mse_cost = _L.square_error_cost
regression_cost = _L.square_error_cost
cross_entropy = _L.cross_entropy_cost
cross_entropy_with_selfnorm = _L.cross_entropy_with_selfnorm
cross_entropy_over_beam = _L.cross_entropy_over_beam
soft_cross_entropy = _L.soft_cross_entropy_cost
multi_binary_label_cross_entropy = _L.multi_binary_label_cross_entropy_cost
huber_regression_cost = _L.huber_regression_cost
huber_classification_cost = _L.huber_classification_cost
smooth_l1_cost = _L.smooth_l1_cost
rank_cost = _L.rank_cost
lambda_cost = _L.lambda_cost
sum_cost = _L.sum_cost
ctc_layer = _L.ctc_cost
warp_ctc_layer = _L.warp_ctc
crf_layer = _L.crf_cost
crf_decoding_layer = _L.crf_decoding
nce_layer = _L.nce_cost
hsigmoid = _L.hsigmoid_cost

# detection
priorbox_layer = _L.priorbox
multibox_loss_layer = _L.multibox_loss
detection_output_layer = _L.detection_output



# ---------------------------------------------------------------------------
# The sibling trainer_config_helpers modules: activations, poolings, attrs,
# optimizers, evaluators, networks — every public name from their __all__.
# ---------------------------------------------------------------------------

# activations.py: v1 passes activation OBJECTS; our DSL takes strings.
# Each factory returns the DSL string so `act=ReluActivation()` works.
def _act(name_str):
    def factory():
        return name_str
    factory.__name__ = name_str
    return factory


BaseActivation = str
TanhActivation = _act("tanh")
SigmoidActivation = _act("sigmoid")
SoftmaxActivation = _act("softmax")
SequenceSoftmaxActivation = _act("sequence_softmax")
IdentityActivation = _act("linear")
LinearActivation = _act("linear")
ReluActivation = _act("relu")
BReluActivation = _act("brelu")
SoftReluActivation = _act("softrelu")
STanhActivation = _act("stanh")
AbsActivation = _act("abs")
SquareActivation = _act("square")
ExpActivation = _act("exp")
LogActivation = _act("log")
SqrtActivation = _act("sqrt")
ReciprocalActivation = _act("reciprocal")

# poolings.py (pooling-type classes defined above)
CudnnMaxPooling = MaxPooling        # vendor-specific impls collapse on TPU
CudnnAvgPooling = AvgPooling


# attrs.py: parameter/layer attribute bundles.  Initialization and
# regularization live in initializers/optim here; the classes accept the
# v1 kwargs so configs parse, and carry them for introspection.
class ParameterAttribute:
    """ParamAttr twin: accepted everywhere, consumed where meaningful."""

    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=None,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, **extra):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.sparse_update = sparse_update
        self.extra = extra


class ExtraLayerAttribute:
    """ExtraAttr twin (drop_rate/device placement hints)."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None, **extra):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device
        self.extra = extra


class HookAttr:
    """HookAttr twin (pruning-hook metadata carrier)."""

    def __init__(self, type="pruning", sparsity_ratio=None, **extra):
        self.type = type
        self.sparsity_ratio = sparsity_ratio
        self.extra = extra


ParamAttr = ParameterAttribute
ExtraAttr = ExtraLayerAttribute

# optimizers.py: *Optimizer class names over our api.optimizer classes.
from paddle_tpu.api import optimizer as _opt                 # noqa: E402
from paddle_tpu.api.config import (settings,                 # noqa: E402,F401
                                   define_py_data_sources2,
                                   get_config_arg)

Optimizer = _opt._Base
BaseSGDOptimizer = _opt._Base
MomentumOptimizer = _opt.Momentum
AdamOptimizer = _opt.Adam
AdamaxOptimizer = _opt.Adamax
AdaGradOptimizer = _opt.AdaGrad
DecayedAdaGradOptimizer = _opt.DecayedAdaGrad
AdaDeltaOptimizer = _opt.AdaDelta
RMSPropOptimizer = _opt.RMSProp


class BaseRegularization:
    """Marker base (BaseRegularization twin)."""

    def __init__(self, rate: float = 0.0):
        self.rate = rate


class L2Regularization(BaseRegularization):
    """L2Regularization twin: pass rate via settings(regularization=...)
    or the optimizer's l2_rate."""


class ModelAverage:
    """ModelAverage twin: carries average_window for settings()."""

    def __init__(self, average_window: float = 0,
                 max_average_window: int = 0, **extra):
        self.average_window = average_window
        self.max_average_window = max_average_window


# evaluators.py: v1 snake_case evaluator constructors over
# paddle_tpu.training.evaluators classes.
from paddle_tpu.training import evaluators as _ev            # noqa: E402

evaluator_base = _ev.Evaluator


def classification_error_evaluator(name=None, **kw):
    return _ev.ClassificationError(name=name or "classification_error")


def auc_evaluator(name=None, **kw):
    return _ev.AUC(name=name or "auc")


def pnpair_evaluator(name=None, **kw):
    return _ev.PnPair(name=name or "pnpair")


def precision_recall_evaluator(name=None, **kw):
    return _ev.PrecisionRecall(name=name or "precision_recall")


def ctc_error_evaluator(name=None, **kw):
    return _ev.CTCError(name=name or "ctc_error")


def chunk_evaluator(chunk_scheme="IOB", num_chunk_types=1, name=None,
                    pred_key="pred", label_key="label", **kw):
    if chunk_scheme != "IOB":
        raise ValueError("chunk_evaluator: only the IOB scheme (the "
                         "reference default) is wired here")
    decode = lambda tags: _ev.iob_chunks(tags, num_chunk_types)
    return _ev.ChunkEvaluator(pred_key, label_key, decode,
                              name=name or "chunk_f1")


def sum_evaluator(name=None, key="loss", **kw):
    return _ev.ValueSum(key, name=name)


def column_sum_evaluator(name=None, key="logits", **kw):
    return _ev.ColumnSum(key, name=name)


def value_printer_evaluator(input=None, name=None, keys=("logits",), **kw):
    return _ev.ValuePrinter(keys, name=name or "value_printer")


def gradient_printer_evaluator(input=None, name=None, keys=None, **kw):
    # A true gradient printer (Evaluator.cpp:1029): the Trainer computes
    # the per-batch gradient tree for it (wants_gradients hook).
    return _ev.GradientPrinter(keys, name=name or "gradient_printer")


def maxid_printer_evaluator(input=None, name=None, keys=("logits",), **kw):
    return _ev.ValuePrinter(keys, name=name or "maxid_printer")


def maxframe_printer_evaluator(input=None, name=None, keys=("logits",),
                               **kw):
    return _ev.ValuePrinter(keys, name=name or "maxframe_printer")


def seqtext_printer_evaluator(input=None, name=None, keys=("logits",),
                              **kw):
    return _ev.ValuePrinter(keys, name=name or "seqtext_printer")


def classification_error_printer_evaluator(input=None, name=None, **kw):
    return _ev.ValuePrinter(("logits",),
                            name=name or "classification_error_printer")


def detection_map_evaluator(num_classes=2, name=None,
                            overlap_threshold=0.5, **kw):
    return _ev.DetectionMAP(num_classes=num_classes,
                            iou_threshold=overlap_threshold,
                            name=name or "detection_map")


# networks.py composites
from paddle_tpu.api.networks import (                        # noqa: E402,F401
    sequence_conv_pool, simple_lstm, simple_img_conv_pool, img_conv_bn_pool,
    lstmemory_group, lstmemory_unit, small_vgg, img_conv_group,
    vgg_16_network, gru_unit, gru_group, simple_gru, simple_attention,
    simple_gru2, bidirectional_gru, text_conv_pool, bidirectional_lstm,
    inputs, outputs)

__all__ = [n for n in dir() if not n.startswith("_") and n != "annotations"]
