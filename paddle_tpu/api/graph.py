"""The declarative layer DAG and its compilation to a model_fn.

Twin of the reference's ``Topology`` (``python/paddle/v2/topology.py:26`` —
walks the layer graph behind a cost and extracts the serialized model
config) except the "serialized config" here is (a) a JSON-able topology
description for introspection/checkpoint metadata and (b) a compiled pure
``model_fn(batch) -> (loss, outputs)`` consumed by the Trainer — tracing
under jit replaces the protobuf→C++ interpreter path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.core.errors import enforce


@dataclasses.dataclass(frozen=True)
class LayerOutput:
    """A node in the declarative graph.

    ``fn(ctx, *input_values, **attrs)`` produces the node's value; data
    nodes read ``ctx.batch[name]`` instead.  Nodes are frozen/hashable so
    the graph memoizes shared sub-expressions exactly like the reference's
    name-keyed layer map.
    """
    name: str
    kind: str
    fn: Optional[Callable] = dataclasses.field(default=None, compare=False,
                                               hash=False, repr=False)
    inputs: Tuple["LayerOutput", ...] = ()
    attrs: Tuple[Tuple[str, Any], ...] = ()

    def attr_dict(self) -> Dict[str, Any]:
        return dict(self.attrs)


class _Ctx:
    def __init__(self, batch: Dict[str, Any], is_train: bool):
        self.batch = batch
        self.is_train = is_train
        self.cache: Dict[LayerOutput, Any] = {}
        self.outputs: Dict[str, Any] = {}
        # Auxiliary multi-output channel (lstm_step state → get_output):
        # NOT returned from model_fn — entries written inside a lax.scan
        # body are scan-trace-local and must not escape as model outputs.
        self.aux: Dict[str, Any] = {}


_name_counters: Dict[str, int] = {}


def auto_name(kind: str, explicit: Optional[str]) -> str:
    if explicit is not None:
        return explicit
    idx = _name_counters.get(kind, 0)
    _name_counters[kind] = idx + 1
    return f"{kind}_{idx}"


def reset_names() -> None:
    """Reset auto-naming (call between independent model builds)."""
    _name_counters.clear()


def _evaluate(node: LayerOutput, ctx: _Ctx):
    if node in ctx.cache:
        return ctx.cache[node]
    if node.kind == "data":
        enforce(node.name in ctx.batch,
                "data layer %r missing from batch (has %s)", node.name,
                sorted(ctx.batch))
        value = ctx.batch[node.name]
    else:
        args = [_evaluate(i, ctx) for i in node.inputs]
        value = node.fn(ctx, *args, **node.attr_dict())
    ctx.cache[node] = value
    return value


def _walk(nodes: Sequence[LayerOutput]) -> List[LayerOutput]:
    seen: Dict[LayerOutput, None] = {}

    def visit(n: LayerOutput):
        if n in seen:
            return
        for i in n.inputs:
            visit(i)
        seen[n] = None

    for n in nodes:
        visit(n)
    return list(seen)


def topology(*outputs: LayerOutput) -> List[Dict[str, Any]]:
    """JSON-able description of the graph behind ``outputs`` in topological
    order (the Topology.proto() twin)."""
    desc = []
    for n in _walk(outputs):
        desc.append({
            "name": n.name,
            "type": n.kind,
            "inputs": [i.name for i in n.inputs],
            "attrs": {k: v for k, v in n.attrs
                      if isinstance(v, (int, float, str, bool, type(None)))},
        })
    return desc


def compile_model(cost: LayerOutput,
                  extra_outputs: Sequence[LayerOutput] = ()):
    """Compile the DAG behind ``cost`` into ``model_fn(batch)`` for the
    Trainer: returns (loss, outputs) where outputs includes every
    ``extra_outputs`` node by name plus any label fields the cost saw."""

    def model_fn(batch: Dict[str, Any]):
        from paddle_tpu.nn.module import is_training
        ctx = _Ctx(batch, is_training())
        loss = _evaluate(cost, ctx)
        outs = dict(ctx.outputs)
        for node in extra_outputs:
            outs[node.name] = _evaluate(node, ctx)
        return loss, outs

    return model_fn
