"""Functional reader combinators.

Behavioral twin of ``python/paddle/v2/reader/decorator.py:26-233`` (and
``creator.py``): a *reader creator* is a zero-arg callable returning an
iterator over samples.  Combinators wrap reader creators.  Semantics follow
the reference (buffered shuffling over a window, chain, compose with zipped
readers, firstn, buffered prefetch via a daemon thread, multi-thread xmap).

Docstring cites are to the reference implementation being mirrored.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable, Iterator, List, Sequence

Reader = Callable[[], Iterator[Any]]


def map_readers(func: Callable, *readers: Reader) -> Reader:
    """Apply func over zipped samples of readers (decorator.py:26)."""
    def reader():
        rs = [r() for r in readers]
        for sample in zip(*rs):
            yield func(*sample)
    return reader


def shuffle(reader_creator: Reader, buf_size: int,
            seed: int = 0) -> Reader:
    """Window-shuffle with buffer buf_size (decorator.py shuffle:60)."""
    def reader():
        rng = _random.Random(seed)
        buf: List[Any] = []
        for sample in reader_creator():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf
    return reader


def chain(*readers: Reader) -> Reader:
    """Concatenate readers (decorator.py chain:90)."""
    def reader():
        for r in readers:
            yield from r()
    return reader


def mix(readers_with_ratios) -> Reader:
    """Interleave readers in sample-count proportion (MultiDataProvider
    twin, ``gserver/dataproviders/MultiDataProvider.cpp``: ratio-mixed
    sub-providers).  ``readers_with_ratios``: [(reader, ratio), ...];
    exhausted readers drop out, iteration ends when all are done."""
    pairs = list(readers_with_ratios)
    for _, w in pairs:
        if not w > 0:
            raise ValueError(f"mix: ratios must be positive, got {w!r}")

    def reader():
        its = [iter(r()) for r, _ in pairs]
        ratios = [float(w) for _, w in pairs]
        credit = [0.0] * len(its)
        alive = [True] * len(its)
        while any(alive):
            for i, it in enumerate(its):
                if not alive[i]:
                    continue
                credit[i] += ratios[i]
                while credit[i] >= 1.0 and alive[i]:
                    try:
                        yield next(it)
                    except StopIteration:
                        alive[i] = False
                        break
                    credit[i] -= 1.0
    return reader


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip readers into combined tuples (decorator.py compose:120).

    Single-item samples are flattened into the output tuple as in the
    reference.
    """
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs, fillvalue=_SENTINEL):
                if any(i is _SENTINEL for i in items):
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())
    return reader


_SENTINEL = object()


def buffered(reader_creator: Reader, size: int) -> Reader:
    """Prefetch up to `size` samples in a daemon thread — the twin of the
    DoubleBuffer async loader (``DataProvider.h:249``) and
    decorator.py buffered:169."""
    def reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        end = object()
        err: List[BaseException] = []

        def produce():
            try:
                for sample in reader_creator():
                    q.put(sample)
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is end:
                if err:
                    raise err[0]
                return
            yield sample
    return reader


def firstn(reader_creator: Reader, n: int) -> Reader:
    """First n samples (decorator.py firstn:233)."""
    def reader():
        return itertools.islice(reader_creator(), n)
    return reader


def xmap_readers(mapper: Callable, reader_creator: Reader,
                 process_num: int, buffer_size: int,
                 order: bool = False) -> Reader:
    """Parallel map over samples with worker threads
    (decorator.py xmap_readers:201).  With order=True, output order matches
    input order.
    """
    def reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        end = object()
        err: List[BaseException] = []

        def feed():
            try:
                for i, sample in enumerate(reader_creator()):
                    in_q.put((i, sample))
            except BaseException as e:
                err.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as e:
                    err.append(e)
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if err:
            raise err[0]
        if order:
            for i in sorted(pending):
                yield pending[i]
    return reader


# ---- creators (twin of v2/reader/creator.py) ----

def np_array(arr) -> Reader:
    """Reader over the first axis of a numpy array (creator.py:22)."""
    def reader():
        yield from arr
    return reader


def text_file(path: str, strip: bool = True) -> Reader:
    """Reader over lines of a text file (creator.py:39)."""
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n") if strip else line
    return reader


def batch(reader_creator: Reader, batch_size: int,
          drop_last: bool = True) -> Reader:
    """Group samples into lists of batch_size (twin of v2/minibatch.py)."""
    def reader():
        buf = []
        for sample in reader_creator():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return reader


class ComposeNotAligned(ValueError):
    """Raised by :func:`compose` when readers of different lengths are
    zipped with ``check_alignment`` (decorator.py ComposeNotAligned twin)."""


def cloud_reader(master_address, trainer: int = 0,
                 poll_interval: float = 0.2):
    """Stream records dispatched by the task master (creator.py
    ``cloud_reader`` twin — the reference pulled records through the Go
    master's etcd-discovered client; here the native master serves
    recordio shard descriptors over TCP, ``distributed/master.py``).

    Yields raw record bytes via :func:`distributed.master.task_reader`
    (which owns the pull/ack/nack + PASS_WAIT loop, so shards of a dead
    trainer really do get re-dispatched and re-read).  Each ``reader()``
    invocation consumes one pass and then asks the master to recycle for
    the next (first trainer to ask wins; the master rejects recycling
    while tasks are outstanding), so multi-pass training works like any
    other reader.
    """
    def reader():
        from paddle_tpu.distributed.master import MasterClient, task_reader
        client = MasterClient(master_address, trainer=trainer)
        try:
            yield from task_reader(client, poll_interval=poll_interval)()
            client.start_next_pass()
        finally:
            client.close()
    return reader
