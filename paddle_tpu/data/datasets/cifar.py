"""CIFAR-10/100 dataset (twin of ``python/paddle/v2/dataset/cifar.py``).

Samples are ``(image, label)`` with image float32[3072] in [0, 1] laid out
CHW-flattened like the reference.  Loads the python-pickle tarball when
cached; synthetic fallback otherwise.
"""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from paddle_tpu.data.datasets import common

CIFAR10 = "cifar-10-python.tar.gz"


def _synthetic(n, num_classes, seed):
    rng = common.synthetic_rng("cifar", seed)
    protos = rng.rand(num_classes, 3072).astype(np.float32)
    labels = rng.randint(0, num_classes, n)
    imgs = np.clip(protos[labels]
                   + 0.25 * rng.randn(n, 3072).astype(np.float32), 0, 1)
    return imgs, labels


def _reader(sub_names, num_classes, n_synth, seed):
    path = common.fetch(CIFAR10)

    def reader():
        if path:
            with tarfile.open(path, mode="r") as tf:
                for member in tf.getmembers():
                    if any(s in member.name for s in sub_names):
                        batch = pickle.load(tf.extractfile(member),
                                            encoding="latin1")
                        for img, lbl in zip(batch["data"], batch["labels"]):
                            yield (img.astype(np.float32) / 255.0, int(lbl))
        else:
            imgs, labels = _synthetic(n_synth, num_classes, seed)
            for img, lbl in zip(imgs, labels):
                yield img, int(lbl)
    return reader


def train10(n_synthetic: int = 2048):
    return _reader(["data_batch"], 10, n_synthetic, seed=0)


def test10(n_synthetic: int = 512):
    return _reader(["test_batch"], 10, n_synthetic, seed=1)
