"""UCI housing regression dataset (twin of
``python/paddle/v2/dataset/uci_housing.py``): samples ``(features[13], price)``
with feature normalization.  Synthetic linear-model fallback.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

FEATURE_DIM = 13


def _synthetic(n, seed):
    rng = common.synthetic_rng("uci_housing", seed)
    w = rng.randn(FEATURE_DIM)
    x = rng.randn(n, FEATURE_DIM).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n)).astype(np.float32)
    return x, y


def train(n_synthetic: int = 404):
    def reader():
        x, y = _synthetic(n_synthetic, 0)
        for xi, yi in zip(x, y):
            yield xi, float(yi)
    return reader


def test(n_synthetic: int = 102):
    def reader():
        x, y = _synthetic(n_synthetic, 1)
        for xi, yi in zip(x, y):
            yield xi, float(yi)
    return reader
