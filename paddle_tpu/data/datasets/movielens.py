"""MovieLens-1M ratings dataset (twin of
``python/paddle/v2/dataset/movielens.py``).

Samples are ``(user_id, gender, age, occupation, movie_id, category_ids,
title_ids, rating)`` — the feature layout the reference's recommender demo
consumes.  Synthetic fallback: latent-factor users/movies so a
matrix-factorization or wide&deep model actually has signal to fit.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

NUM_USERS = 6040
NUM_MOVIES = 3952
NUM_AGES = 7
NUM_OCCUPATIONS = 21
NUM_CATEGORIES = 18
TITLE_VOCAB = 5174
MAX_CATEGORIES = 3
TITLE_LEN = 4


def max_user_id() -> int:
    return NUM_USERS


def max_movie_id() -> int:
    return NUM_MOVIES


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def _factors(rng, n, d=8):
    return rng.randn(n, d).astype(np.float32) * 0.5


def _synthetic(n, seed):
    rng = common.synthetic_rng("movielens", seed)
    uf = _factors(rng, NUM_USERS)
    mf = _factors(rng, NUM_MOVIES)
    genders = rng.randint(0, 2, NUM_USERS)
    ages = rng.randint(0, NUM_AGES, NUM_USERS)
    occs = rng.randint(0, NUM_OCCUPATIONS, NUM_USERS)
    movie_cats = rng.randint(0, NUM_CATEGORIES, (NUM_MOVIES, MAX_CATEGORIES))
    movie_titles = rng.randint(0, TITLE_VOCAB, (NUM_MOVIES, TITLE_LEN))
    for _ in range(n):
        u = int(rng.randint(0, NUM_USERS))
        m = int(rng.randint(0, NUM_MOVIES))
        score = float(uf[u] @ mf[m]) + 0.3 * float(rng.randn())
        rating = int(np.clip(np.round(3.0 + score), 1, 5))
        yield (u, int(genders[u]), int(ages[u]), int(occs[u]),
               m, movie_cats[m].astype(np.int32),
               movie_titles[m].astype(np.int32), rating)


def train(n_synthetic: int = 4096):
    def reader():
        yield from _synthetic(n_synthetic, 0)
    return reader


def test(n_synthetic: int = 512):
    def reader():
        yield from _synthetic(n_synthetic, 1)
    return reader
