"""MNIST dataset (twin of ``python/paddle/v2/dataset/mnist.py``).

Yields ``(image, label)`` with image a flat float32[784] in [-1, 1] and
label int — the exact sample contract of the reference.  Reads the standard
idx-format files from the cache dir when present; otherwise generates a
deterministic synthetic set with class-dependent structure (each digit class
has a distinct mean pattern) so models can actually learn from it in tests.
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from paddle_tpu.data.datasets import common

TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
TEST_LABELS = "t10k-labels-idx1-ubyte.gz"


def _read_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad magic {magic}"
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows * cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad magic {magic}"
        return np.frombuffer(f.read(n), np.uint8)


def _synthetic(n: int, seed: int):
    rng = common.synthetic_rng("mnist", seed)
    protos = rng.rand(10, 784).astype(np.float32)
    labels = rng.randint(0, 10, n)
    imgs = protos[labels] + 0.3 * rng.randn(n, 784).astype(np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    return imgs, labels


def _reader(images_file, labels_file, n_synth, seed):
    img_path = common.fetch(images_file)
    lbl_path = common.fetch(labels_file)

    def reader():
        if img_path and lbl_path:
            imgs = _read_idx_images(img_path).astype(np.float32) / 255.0
            labels = _read_idx_labels(lbl_path)
        else:
            imgs, labels = _synthetic(n_synth, seed)
        # reference normalizes to [-1, 1] (mnist.py reader_creator)
        for img, lbl in zip(imgs, labels):
            yield img * 2.0 - 1.0, int(lbl)
    return reader


def train(n_synthetic: int = 2048):
    return _reader(TRAIN_IMAGES, TRAIN_LABELS, n_synthetic, seed=0)


def test(n_synthetic: int = 512):
    return _reader(TEST_IMAGES, TEST_LABELS, n_synthetic, seed=1)
