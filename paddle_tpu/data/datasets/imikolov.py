"""PTB-style n-gram language-model dataset (twin of
``python/paddle/v2/dataset/imikolov.py``): samples are n-gram tuples of word
ids.  Synthetic Markov-chain fallback so LM perplexity actually improves
during tests.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common


def build_dict(vocab_size: int = 2074):
    return {f"w{i}": i for i in range(vocab_size)}


def _chain(n_tokens, vocab_size, seed):
    rng = common.synthetic_rng("imikolov", seed)
    # sparse row-stochastic transition matrix -> learnable bigram structure
    trans = rng.rand(vocab_size, vocab_size) ** 8 + 1e-4
    trans /= trans.sum(1, keepdims=True)
    tok = int(rng.randint(vocab_size))
    for _ in range(n_tokens):
        tok = int(rng.choice(vocab_size, p=trans[tok]))
        yield tok


def ngram(n: int = 5, vocab_size: int = 2074, n_tokens: int = 20000,
          seed: int = 0):
    def reader():
        window = []
        for tok in _chain(n_tokens, vocab_size, seed):
            window.append(tok)
            if len(window) == n:
                yield tuple(window)
                window.pop(0)
    return reader


def train(n: int = 5, vocab_size: int = 2074, n_tokens: int = 20000):
    return ngram(n, vocab_size, n_tokens, seed=0)


def test(n: int = 5, vocab_size: int = 2074, n_tokens: int = 4000):
    return ngram(n, vocab_size, n_tokens, seed=1)
