"""CoNLL-2005 semantic-role-labeling dataset (twin of
``python/paddle/v2/dataset/conll05.py``).

Samples are ``(word_ids, predicate_id, ctx_n2/n1/0/p1/p2, mark, label_ids)``
— the 8-slot feature layout of the reference's SRL demo (sequence tagging
with B/I/O argument labels).  Synthetic fallback: template-generated
sentences where argument spans correlate with distance to the predicate.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

WORD_VOCAB = 44068
PREDICATE_VOCAB = 3162
# 0 = O; odd = B-type k, even = I-type k (iob_decode scheme)
NUM_LABEL_TYPES = 10
NUM_LABELS = 2 * NUM_LABEL_TYPES + 1


def word_dict_len() -> int:
    return WORD_VOCAB


def predicate_dict_len() -> int:
    return PREDICATE_VOCAB


def label_dict_len() -> int:
    return NUM_LABELS


def _synthetic(n, seed, min_len=8, max_len=40):
    rng = common.synthetic_rng("conll05", seed)
    for _ in range(n):
        length = int(rng.randint(min_len, max_len + 1))
        words = rng.randint(0, WORD_VOCAB, length).astype(np.int32)
        pred_pos = int(rng.randint(0, length))
        predicate = int(rng.randint(0, PREDICATE_VOCAB))
        labels = np.zeros(length, np.int32)
        # one argument span on each side of the predicate when room allows
        for lo, hi in ((0, pred_pos), (pred_pos + 1, length)):
            if hi - lo >= 2:
                s = int(rng.randint(lo, hi - 1))
                e = min(hi, s + int(rng.randint(1, 4)))
                t = int(rng.randint(0, NUM_LABEL_TYPES))
                labels[s] = 2 * t + 1          # B-t
                labels[s + 1:e] = 2 * t + 2    # I-t
        mark = np.zeros(length, np.int32)
        mark[pred_pos] = 1
        ctx = [words[np.clip(pred_pos + d, 0, length - 1)]
               for d in (-2, -1, 0, 1, 2)]
        yield (words, predicate, *map(int, ctx), mark, labels)


def train(n_synthetic: int = 1024):
    def reader():
        yield from _synthetic(n_synthetic, 0)
    return reader


def test(n_synthetic: int = 128):
    def reader():
        yield from _synthetic(n_synthetic, 1)
    return reader
