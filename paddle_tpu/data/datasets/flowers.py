"""Oxford-102 flowers dataset (twin of
``python/paddle/v2/dataset/flowers.py``): ``(image_hwc_float, label)`` with
102 classes.  Synthetic fallback: class-colored noise images so a CNN can
separate them.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

NUM_CLASSES = 102
IMAGE_SIZE = 64  # reference pipeline resizes/crops; synthetic uses 64²


def _synthetic(n, seed, size=IMAGE_SIZE):
    rng = common.synthetic_rng("flowers", seed)
    palette = rng.rand(NUM_CLASSES, 3).astype(np.float32)
    for _ in range(n):
        label = int(rng.randint(0, NUM_CLASSES))
        img = (palette[label][None, None, :]
               + 0.25 * rng.randn(size, size, 3)).astype(np.float32)
        yield np.clip(img, 0.0, 1.0), label


def train(n_synthetic: int = 1024):
    def reader():
        yield from _synthetic(n_synthetic, 0)
    return reader


def valid(n_synthetic: int = 128):
    def reader():
        yield from _synthetic(n_synthetic, 1)
    return reader


def test(n_synthetic: int = 128):
    def reader():
        yield from _synthetic(n_synthetic, 2)
    return reader
