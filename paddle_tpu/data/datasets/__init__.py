from paddle_tpu.data.datasets import mnist, cifar, imdb, uci_housing, imikolov

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "imikolov"]
