from paddle_tpu.data.datasets import (mnist, cifar, imdb, uci_housing,
                                      imikolov, ctr, movielens, conll05,
                                      wmt14, sentiment, mq2007, flowers,
                                      voc2012)

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "imikolov", "ctr",
           "movielens", "conll05", "wmt14", "sentiment", "mq2007", "flowers",
           "voc2012"]
