"""NLTK movie-review sentiment dataset (twin of
``python/paddle/v2/dataset/sentiment.py``) — same sample contract as imdb
(``(word_ids, label)``), smaller vocabulary.
"""

from __future__ import annotations

from paddle_tpu.data.datasets import imdb

VOCAB = 2000


def get_word_dict():
    return imdb.word_dict(VOCAB)


def train(n_synthetic: int = 800):
    return imdb.train(VOCAB, n_synthetic, min_len=5, max_len=60)


def test(n_synthetic: int = 200):
    return imdb.test(VOCAB, n_synthetic, min_len=5, max_len=60)
