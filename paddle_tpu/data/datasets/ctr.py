"""Synthetic CTR dataset with sparse multi-hot fields.

Stand-in for the reference ``quick_start``/Avazu-style CTR data (the sparse
pserver workload, BASELINE.json config 5): each sample has several sparse
id-list fields and a click label generated from a hidden per-id weight
vector, so AUC genuinely improves during training.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from paddle_tpu.data.datasets import common


def make_fields(num_fields: int = 3,
                vocab_sizes: Sequence[int] = (1000, 500, 100),
                max_ids: int = 5):
    return list(vocab_sizes[:num_fields]), max_ids


def train(vocab_sizes: Sequence[int] = (1000, 500, 100), max_ids: int = 5,
          n: int = 4096, seed: int = 0):
    # hidden model fixed across train/test splits; only samples vary by seed
    rng = common.synthetic_rng("ctr_weights", 0)
    hidden_w = [rng.randn(v) * 1.5 for v in vocab_sizes]

    def reader():
        r = common.synthetic_rng("ctr_samples", seed)
        for _ in range(n):
            sample = []
            score = 0.0
            for fi, v in enumerate(vocab_sizes):
                k = int(r.randint(1, max_ids + 1))
                ids = r.randint(0, v, k).astype(np.int32)
                score += hidden_w[fi][ids].sum()
                sample.append(ids)
            p = 1.0 / (1.0 + np.exp(-score / np.sqrt(len(vocab_sizes)
                                                     * max_ids)))
            label = int(r.rand() < p)
            yield (*sample, label)
    return reader


def test(vocab_sizes: Sequence[int] = (1000, 500, 100), max_ids: int = 5,
         n: int = 1024):
    return train(vocab_sizes, max_ids, n, seed=1)
