"""Dataset common utilities.

Twin of ``python/paddle/v2/dataset/common.py`` (download cache + split).
This build environment has no network egress, so ``fetch`` only resolves
files already present in the cache directory (``~/.cache/paddle_tpu`` or
``$PADDLE_TPU_DATA``); every dataset module falls back to a deterministic
synthetic generator when real files are absent — the test-fixture strategy
of the reference (``paddle/testing/TestUtil.*`` random fake inputs).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def data_home() -> str:
    return os.environ.get(
        "PADDLE_TPU_DATA",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))


def fetch(filename: str) -> Optional[str]:
    """Return the cached path for filename if it exists, else None."""
    path = os.path.join(data_home(), filename)
    return path if os.path.exists(path) else None


def synthetic_rng(name: str, seed: int = 0) -> np.random.RandomState:
    """Deterministic per-dataset RNG for synthetic fallbacks.

    Uses crc32, not hash(): Python's str hash is salted per process, which
    would silently give every process a different 'deterministic' dataset.
    """
    import zlib
    return np.random.RandomState(
        zlib.crc32(f"{name}:{seed}".encode()) % (2 ** 31))
