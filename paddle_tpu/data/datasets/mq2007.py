"""LETOR MQ2007 learning-to-rank dataset (twin of
``python/paddle/v2/dataset/mq2007.py``).

Modes match the reference: ``pointwise`` yields (features, relevance),
``pairwise`` yields (features_hi, features_lo) with rel(hi) > rel(lo),
``listwise`` yields (query_features [n, 46], relevances [n]).  Synthetic
fallback: relevance is a noisy linear function of the 46 features.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

NUM_FEATURES = 46


def _queries(n_queries, seed, docs_per_query=(5, 20)):
    rng = common.synthetic_rng("mq2007", seed)
    w = rng.randn(NUM_FEATURES).astype(np.float32)
    for _ in range(n_queries):
        n_docs = int(rng.randint(*docs_per_query))
        feats = rng.randn(n_docs, NUM_FEATURES).astype(np.float32)
        score = feats @ w + 0.5 * rng.randn(n_docs).astype(np.float32)
        rel = np.digitize(score, np.quantile(score, [0.5, 0.8])) \
            .astype(np.int32)  # 0/1/2 relevance grades
        yield feats, rel


def train(mode: str = "pairwise", n_queries: int = 200):
    return _reader(mode, n_queries, seed=0)


def test(mode: str = "pairwise", n_queries: int = 40):
    return _reader(mode, n_queries, seed=1)


def _reader(mode, n_queries, seed):
    def reader():
        for feats, rel in _queries(n_queries, seed):
            if mode == "listwise":
                yield feats, rel
            elif mode == "pointwise":
                for f, r in zip(feats, rel):
                    yield f, int(r)
            elif mode == "pairwise":
                hi = np.argsort(-rel)
                for i in hi:
                    for j in hi[::-1]:
                        if rel[i] > rel[j]:
                            yield feats[i], feats[j]
                            break
            else:
                raise ValueError(f"unknown mq2007 mode {mode!r}")
    return reader
