"""IMDB sentiment dataset (twin of ``python/paddle/v2/dataset/imdb.py``).

Samples are ``(word_id_sequence, label)`` with label in {0, 1}.  Synthetic
fallback: two vocab distributions (positive/negative skew) generate
variable-length sequences a text classifier can actually separate — keeping
the learning-dynamics realism of the real dataset for tests and benchmarks.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common


def word_dict(vocab_size: int = 5148):
    """Synthetic stand-in for imdb.word_dict() — id -> id mapping size."""
    return {f"w{i}": i for i in range(vocab_size)}


def _synthetic(n, vocab_size, min_len, max_len, seed):
    rng = common.synthetic_rng("imdb", seed)
    # class-dependent unigram distributions over the vocabulary
    base = rng.rand(vocab_size) + 0.1
    tilt = rng.rand(vocab_size)
    pos = base * (1 + tilt)
    neg = base * (2 - tilt)
    pos /= pos.sum()
    neg /= neg.sum()
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(min_len, max_len + 1))
        dist = pos if label == 1 else neg
        seq = rng.choice(vocab_size, size=length, p=dist)
        yield seq.astype(np.int32), label


def train(vocab_size: int = 5148, n_synthetic: int = 1024,
          min_len: int = 10, max_len: int = 100):
    def reader():
        yield from _synthetic(n_synthetic, vocab_size, min_len, max_len, 0)
    return reader


def test(vocab_size: int = 5148, n_synthetic: int = 256,
         min_len: int = 10, max_len: int = 100):
    def reader():
        yield from _synthetic(n_synthetic, vocab_size, min_len, max_len, 1)
    return reader
