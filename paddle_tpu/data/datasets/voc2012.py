"""Pascal VOC-2012 detection/segmentation dataset (twin of
``python/paddle/v2/dataset/voc2012.py``, extended with the detection sample
layout the SSD stack consumes).

``train/val`` yield ``(image_hwc_float, gt_boxes [G,4] normalized,
gt_labels [G] in 1..20)``.  Synthetic fallback: colored rectangles on noise
backgrounds — detectable objects with exact ground truth.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

NUM_CLASSES = 21  # 20 object classes + background (0)
IMAGE_SIZE = 96


def _synthetic(n, seed, size=IMAGE_SIZE, max_objects=3):
    rng = common.synthetic_rng("voc2012", seed)
    palette = rng.rand(NUM_CLASSES, 3).astype(np.float32)
    for _ in range(n):
        img = 0.1 * rng.rand(size, size, 3).astype(np.float32)
        g = int(rng.randint(1, max_objects + 1))
        boxes, labels = [], []
        for _ in range(g):
            w, h = rng.uniform(0.15, 0.5, 2)
            x0 = rng.uniform(0, 1 - w)
            y0 = rng.uniform(0, 1 - h)
            cls = int(rng.randint(1, NUM_CLASSES))
            xi0, yi0 = int(x0 * size), int(y0 * size)
            xi1, yi1 = int((x0 + w) * size), int((y0 + h) * size)
            img[yi0:yi1, xi0:xi1] = palette[cls]
            boxes.append([x0, y0, x0 + w, y0 + h])
            labels.append(cls)
        yield (img, np.asarray(boxes, np.float32),
               np.asarray(labels, np.int32))


def train(n_synthetic: int = 512):
    def reader():
        yield from _synthetic(n_synthetic, 0)
    return reader


def val(n_synthetic: int = 64):
    def reader():
        yield from _synthetic(n_synthetic, 1)
    return reader


def test(n_synthetic: int = 64):
    def reader():
        yield from _synthetic(n_synthetic, 2)
    return reader
