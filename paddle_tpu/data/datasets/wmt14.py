"""WMT-14 French→English translation dataset (twin of
``python/paddle/v2/dataset/wmt14.py``).

Samples are ``(src_ids, trg_ids_in, trg_ids_out)`` with <s>/<e>/<unk>
conventions matching the reference (ids 0/1/2).  Synthetic fallback: an
invertible toy "translation" (digit-reversal language pair) so a seq2seq
model can reach near-zero loss — exercising attention and beam search the
way the real corpus would.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

START_ID, END_ID, UNK_ID = 0, 1, 2
RESERVED = 3
DEFAULT_DICT_SIZE = 30000


def _synthetic(n, dict_size, seed, min_len=4, max_len=20):
    rng = common.synthetic_rng("wmt14", seed)
    for _ in range(n):
        length = int(rng.randint(min_len, max_len + 1))
        src = rng.randint(RESERVED, dict_size, length).astype(np.int32)
        # toy alignment: target = reversed source with a fixed offset map
        trg = ((src[::-1] - RESERVED + 7) % (dict_size - RESERVED)
               + RESERVED).astype(np.int32)
        trg_in = np.concatenate([[START_ID], trg]).astype(np.int32)
        trg_out = np.concatenate([trg, [END_ID]]).astype(np.int32)
        yield src, trg_in, trg_out


def train(dict_size: int = DEFAULT_DICT_SIZE, n_synthetic: int = 2048):
    def reader():
        yield from _synthetic(n_synthetic, dict_size, 0)
    return reader


def test(dict_size: int = DEFAULT_DICT_SIZE, n_synthetic: int = 256):
    def reader():
        yield from _synthetic(n_synthetic, dict_size, 1)
    return reader
