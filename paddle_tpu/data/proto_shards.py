"""Reader for the reference ProtoDataProvider binary shard format.

A shard is a stream of varint-length-prefixed proto2 messages
(``gserver/dataproviders/ProtoReader.h:95-109``): one ``DataHeader``
followed by ``DataSample`` records (``proto/DataFormat.proto``).  The
header declares the slot schema — dense vectors, sparse id (non-value)
vectors, sparse value vectors, integer indices, variable-multi-dim
tensors, strings — and each sample carries one entry per slot, with
INDEX-typed slots drawn from ``id_slots`` in declaration order after the
vector slots (``ProtoDataProvider.cpp:240-351`` fillSlots).

Reference users' existing data files (e.g. the checked-in
``paddle/trainer/tests/mnist_bin_part``) read here without conversion:

    from paddle_tpu.data import proto_shards
    slots, samples = proto_shards.read_shard("mnist_bin_part")
    reader = proto_shards.shard_reader(["mnist_bin_part"])  # -> dict rows

The wire walk is a from-scratch minimal proto2 decoder (the pattern of
``v2.py``'s ParameterConfig walker) — no protobuf runtime dependency.
Gzip-compressed shards (``DataConfig.data_compression``) are
auto-detected by magic bytes.
"""

from __future__ import annotations

import gzip
import io
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Sequence, Tuple

import numpy as np

from paddle_tpu.core.errors import enforce

# SlotDef.SlotType (DataFormat.proto:49-57)
VECTOR_DENSE = 0
VECTOR_SPARSE_NON_VALUE = 1
VECTOR_SPARSE_VALUE = 2
INDEX = 3
VAR_MDIM_DENSE = 4
VAR_MDIM_INDEX = 5
STRING = 6

_SLOT_NAMES = {
    VECTOR_DENSE: "dense", VECTOR_SPARSE_NON_VALUE: "sparse_non_value",
    VECTOR_SPARSE_VALUE: "sparse_value", INDEX: "index",
    VAR_MDIM_DENSE: "var_mdim_dense", VAR_MDIM_INDEX: "var_mdim_index",
    STRING: "string",
}

_VECTOR_TYPES = (VECTOR_DENSE, VECTOR_SPARSE_NON_VALUE,
                 VECTOR_SPARSE_VALUE, VAR_MDIM_DENSE, STRING)


@dataclass
class SlotDef:
    type: int
    dim: int

    @property
    def type_name(self) -> str:
        return _SLOT_NAMES.get(self.type, str(self.type))


def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    v = s = 0
    while i < len(buf):
        b = buf[i]
        v |= (b & 0x7F) << s
        s += 7
        i += 1
        if not b & 0x80:
            return v, i
    raise ValueError("proto shard: truncated varint")


def _skip(buf: bytes, i: int, wire: int) -> int:
    if wire == 0:
        _, i = _varint(buf, i)
        return i
    if wire == 1:
        return i + 8
    if wire == 2:
        n, i = _varint(buf, i)
        return i + n
    if wire == 5:
        return i + 4
    raise ValueError(f"proto shard: unsupported wire type {wire}")


def _packed_varints(buf: bytes) -> List[int]:
    out, i = [], 0
    while i < len(buf):
        v, i = _varint(buf, i)
        out.append(v)
    return out


def _parse_vector_slot(buf: bytes) -> Dict[str, Any]:
    """VectorSlot: 1=values (packed float), 2=ids (packed uint32),
    3=dims (packed uint32), 4=strs.  Packed numeric fields may also
    appear unpacked (one wire-0/5 entry per element)."""
    values: List[bytes] = []
    ids: List[int] = []
    dims: List[int] = []
    strs: List[bytes] = []
    i = 0
    while i < len(buf):
        key, i = _varint(buf, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            n, i = _varint(buf, i)
            values.append(buf[i:i + n])
            i += n
        elif field == 1 and wire == 5:
            values.append(buf[i:i + 4])
            i += 4
        elif field == 2 and wire == 2:
            n, i = _varint(buf, i)
            ids.extend(_packed_varints(buf[i:i + n]))
            i += n
        elif field == 2 and wire == 0:
            v, i = _varint(buf, i)
            ids.append(v)
        elif field == 3 and wire == 2:
            n, i = _varint(buf, i)
            dims.extend(_packed_varints(buf[i:i + n]))
            i += n
        elif field == 3 and wire == 0:
            v, i = _varint(buf, i)
            dims.append(v)
        elif field == 4 and wire == 2:
            n, i = _varint(buf, i)
            strs.append(buf[i:i + n])
            i += n
        else:
            i = _skip(buf, i, wire)
    return {
        "values": np.frombuffer(b"".join(values), "<f4")
        if values else np.zeros(0, np.float32),
        "ids": np.asarray(ids, np.int32),
        "dims": tuple(dims),
        "strs": [s.decode("utf-8", "replace") for s in strs],
    }


def _parse_header(buf: bytes) -> List[SlotDef]:
    """DataHeader: 1=slot_defs (SlotDef: 1=type, 2=dim)."""
    slots: List[SlotDef] = []
    i = 0
    while i < len(buf):
        key, i = _varint(buf, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            n, i = _varint(buf, i)
            sub, j = buf[i:i + n], 0
            stype = sdim = 0
            while j < len(sub):
                k2, j = _varint(sub, j)
                f2, w2 = k2 >> 3, k2 & 7
                if f2 == 1 and w2 == 0:
                    stype, j = _varint(sub, j)
                elif f2 == 2 and w2 == 0:
                    sdim, j = _varint(sub, j)
                else:
                    j = _skip(sub, j, w2)
            slots.append(SlotDef(stype, sdim))
            i += n
        else:
            i = _skip(buf, i, wire)
    enforce(slots, "proto shard: DataHeader has no slot_defs")
    return slots


def _parse_sample(buf: bytes) -> Dict[str, Any]:
    """DataSample: 1=is_beginning, 2=vector_slots, 3=id_slots (packed),
    4=var_id_slots, 5=subseq_slots (1=slot_id, 2=lens)."""
    out: Dict[str, Any] = {"is_beginning": True, "vector_slots": [],
                           "id_slots": [], "var_id_slots": [],
                           "subseq_slots": {}}
    i = 0
    while i < len(buf):
        key, i = _varint(buf, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            v, i = _varint(buf, i)
            out["is_beginning"] = bool(v)
        elif field == 2 and wire == 2:
            n, i = _varint(buf, i)
            out["vector_slots"].append(_parse_vector_slot(buf[i:i + n]))
            i += n
        elif field == 3 and wire == 2:
            n, i = _varint(buf, i)
            out["id_slots"].extend(_packed_varints(buf[i:i + n]))
            i += n
        elif field == 3 and wire == 0:
            v, i = _varint(buf, i)
            out["id_slots"].append(v)
        elif field == 4 and wire == 2:
            n, i = _varint(buf, i)
            out["var_id_slots"].append(_parse_vector_slot(buf[i:i + n]))
            i += n
        elif field == 5 and wire == 2:
            n, i = _varint(buf, i)
            sub, j = buf[i:i + n], 0
            slot_id, lens = 0, []
            while j < len(sub):
                k2, j = _varint(sub, j)
                f2, w2 = k2 >> 3, k2 & 7
                if f2 == 1 and w2 == 0:
                    slot_id, j = _varint(sub, j)
                elif f2 == 2 and w2 == 2:
                    m, j = _varint(sub, j)
                    lens = _packed_varints(sub[j:j + m])
                    j += m
                elif f2 == 2 and w2 == 0:
                    v, j = _varint(sub, j)
                    lens.append(v)
                else:
                    j = _skip(sub, j, w2)
            out["subseq_slots"][slot_id] = lens
            i += n
        else:
            i = _skip(buf, i, wire)
    return out


def _open_shard(path: str) -> bytes:
    with open(path, "rb") as f:
        head = f.read(2)
        f.seek(0)
        if head == b"\x1f\x8b":  # DataConfig.data_compression artifact
            return gzip.open(f).read()
        return f.read()


def _messages(buf: bytes) -> Iterator[bytes]:
    i = 0
    while i < len(buf):
        n, i = _varint(buf, i)
        enforce(i + n <= len(buf),
                "proto shard: truncated message (%d bytes declared, %d "
                "remain)", n, len(buf) - i)
        yield buf[i:i + n]
        i += n


def _slot_value(slot: SlotDef, slot_idx: int, num_vec: int,
                sample: Dict[str, Any]):
    """One slot's value for one sample, mirroring fillSlots
    (``ProtoDataProvider.cpp:240-351``)."""
    if slot.type == VECTOR_DENSE:
        vs = sample["vector_slots"][slot_idx]
        enforce(vs["values"].size == slot.dim,
                "dense slot %d: sample has %d values, header dim is %d",
                slot_idx, vs["values"].size, slot.dim)
        return vs["values"]
    if slot.type == VECTOR_SPARSE_NON_VALUE:
        return sample["vector_slots"][slot_idx]["ids"]
    if slot.type == VECTOR_SPARSE_VALUE:
        vs = sample["vector_slots"][slot_idx]
        return (vs["ids"], vs["values"])
    if slot.type == INDEX:
        return int(sample["id_slots"][slot_idx - num_vec])
    if slot.type == VAR_MDIM_DENSE:
        vs = sample["vector_slots"][slot_idx]
        vals = vs["values"]
        return vals.reshape(vs["dims"]) if vs["dims"] else vals
    if slot.type == VAR_MDIM_INDEX:
        return sample["var_id_slots"][slot_idx - num_vec]["ids"]
    if slot.type == STRING:
        vs = sample["vector_slots"][slot_idx]
        enforce(vs["strs"], "string slot %d: sample has no strs", slot_idx)
        return vs["strs"][0]
    raise ValueError(f"unsupported slot type {slot.type}")


def read_shard(path: str) -> Tuple[List[SlotDef], Iterator[List[Any]]]:
    """Parse one shard file.  Returns the slot schema and an iterator of
    per-sample slot-value lists (dense -> float32 [dim], sparse-id ->
    int32 ids, sparse-value -> (ids, values), index -> int, ...)."""
    buf = _open_shard(path)
    msgs = _messages(buf)
    try:
        slots = _parse_header(next(msgs))
    except StopIteration:
        raise ValueError(f"proto shard {path}: empty file")
    num_vec = sum(1 for s in slots if s.type in _VECTOR_TYPES)
    # The reference hard-rejects INDEX slots before vector slots
    # (checkDataHeader, DataFormat.proto's "INDEX slot should be always
    # after VECTOR slots") — without this, the id_slots offset arithmetic
    # below would silently mis-index.
    for i, s in enumerate(slots):
        enforce(s.type in _VECTOR_TYPES or i >= num_vec,
                "proto shard %s: %s slot at position %d precedes a "
                "vector slot (INDEX slots must come last)",
                path, s.type_name, i)

    def rows() -> Iterator[List[Any]]:
        for raw in msgs:
            sample = _parse_sample(raw)
            yield [_slot_value(s, i, num_vec, sample)
                   for i, s in enumerate(slots)]

    return slots, rows()


def shard_reader(paths: Sequence[str]):
    """Reader factory over shard files: ``reader()`` yields one TUPLE per
    sample, feeder-compatible (``data/feeder.py`` column specs line up
    with the header's slot order).  Samples with ``is_beginning=False``
    belong to the previous sample's sequence; this flat reader yields
    them as-is — sequence grouping is the consumer's (value, mask)
    batching concern."""
    paths = list(paths)
    enforce(paths, "shard_reader: no shard paths given")

    def reader():
        for p in paths:
            _, rows = read_shard(p)
            for row in rows:
                yield tuple(row)

    return reader
