from paddle_tpu.data import reader, datasets
from paddle_tpu.data.feeder import (DataFeeder, Dense, Integer, IntSequence,
                                    DenseSequence)

__all__ = ["reader", "datasets", "DataFeeder", "Dense", "Integer",
           "IntSequence", "DenseSequence"]
