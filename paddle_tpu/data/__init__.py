"""Readers, providers, feeders, datasets (the py_paddle
DataProvider stack twin)."""
from paddle_tpu.data import reader, datasets, proto_shards, provider
from paddle_tpu.data.feeder import (DataFeeder, Dense, Integer, IntSequence,
                                    DenseSequence, SparseBinary, SparseFloat)

__all__ = ["reader", "datasets", "proto_shards", "provider", "DataFeeder",
           "Dense", "Integer", "IntSequence", "DenseSequence",
           "SparseBinary", "SparseFloat"]
