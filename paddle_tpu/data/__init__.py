from paddle_tpu.data import reader, datasets, provider
from paddle_tpu.data.feeder import (DataFeeder, Dense, Integer, IntSequence,
                                    DenseSequence, SparseBinary, SparseFloat)

__all__ = ["reader", "datasets", "provider", "DataFeeder", "Dense",
           "Integer", "IntSequence", "DenseSequence", "SparseBinary",
           "SparseFloat"]
