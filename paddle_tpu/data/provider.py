"""``@provider`` data-provider protocol (PyDataProvider2 twin).

Re-creation of the reference's Python data-provider surface —
``python/paddle/trainer/PyDataProvider2.py:365`` (the ``@provider``
decorator, ``input_types``, ``init_hook``, ``pool_size`` shuffle pool,
``cache`` modes) and the C++ host that pulls from it
(``gserver/dataproviders/PyDataProvider2.cpp:195,334``) — except the host
here is pure Python: a provider instance *is* a reader over file names,
composable with ``paddle_tpu.data.reader`` combinators and fed through
:class:`~paddle_tpu.data.feeder.DataFeeder`.

Input-type constructors carry the reference's exact names
(``dense_vector``, ``integer_value_sequence``, ...) and map onto the
feeder's slot types; sparse slots densify to multi-hot rows (static shapes
for XLA — the capability delta vs CSR is documented in the feeder).

Example, mirroring the reference's mnist_provider.py idiom::

    from paddle_tpu.data import provider as pv

    @pv.provider(input_types={"pixel": pv.dense_vector(784),
                              "label": pv.integer_value(10)},
                 cache=pv.CacheType.CACHE_PASS_IN_MEM)
    def process(settings, filename):
        for img, lab in read_file(filename):
            yield {"pixel": img, "label": lab}

    reader = process(["train.list.1", "train.list.2"])   # a reader()
    feeder = reader.feeder()                              # DataFeeder
"""

from __future__ import annotations

import enum
import logging
import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from paddle_tpu.core.errors import enforce
from paddle_tpu.data import feeder as feeder_mod


# ---- input types (names match PyDataProvider2.py) ---------------------------

def dense_vector(dim: int):
    return feeder_mod.Dense((dim,))


def dense_array(shape: Sequence[int]):
    return feeder_mod.Dense(tuple(shape))


def integer_value(value_range: int = 0):
    # value_range is metadata only (the reference used it for checks).
    return feeder_mod.Integer()


def dense_vector_sequence(dim: int,
                          buckets: Optional[Sequence[int]] = None):
    return feeder_mod.DenseSequence(dim, buckets=buckets)


def integer_value_sequence(value_range: int = 0,
                           buckets: Optional[Sequence[int]] = None):
    return feeder_mod.IntSequence(buckets=buckets)


def sparse_binary_vector(dim: int):
    return feeder_mod.SparseBinary(dim)


def sparse_float_vector(dim: int):
    return feeder_mod.SparseFloat(dim)


class CacheType(enum.Enum):
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class Settings:
    """The ``settings`` object handed to init_hook and the process fn
    (PyDataProvider2's DataProviderWrapper settings twin): carries
    ``input_types``, a logger, and any attributes the init_hook sets."""

    def __init__(self, input_types: Dict[str, Any], **kwargs):
        self.input_types = input_types
        self.logger = logging.getLogger("paddle_tpu.provider")
        for k, v in kwargs.items():
            setattr(self, k, v)


class DataProvider:
    """A bound provider: iterate samples from a file list.

    Calling it returns a fresh sample iterator (the reader protocol), so it
    plugs into ``reader.shuffle``/``reader.batch``/... directly.
    """

    def __init__(self, process: Callable, files: Sequence[str],
                 settings: Settings, pool_size: int, cache: CacheType,
                 should_shuffle: bool, seed: Optional[int]):
        self._process = process
        self.files = list(files)
        self.settings = settings
        self.pool_size = pool_size
        self.cache = cache
        self.should_shuffle = should_shuffle
        self._rng = random.Random(seed)
        self._pass_cache: Optional[List[Any]] = None

    @property
    def input_types(self) -> Dict[str, Any]:
        return self.settings.input_types

    def feeder(self) -> feeder_mod.DataFeeder:
        """A DataFeeder matching this provider's input_types (dict samples
        are converted to tuples in declaration order)."""
        names = list(self.settings.input_types)
        types = [self.settings.input_types[n] for n in names]
        return feeder_mod.DataFeeder(types, names)

    def _iter_raw(self):
        files = list(self.files)
        if self.should_shuffle:
            self._rng.shuffle(files)
        for fname in files:
            for sample in self._process(self.settings, fname):
                if isinstance(sample, dict):
                    sample = tuple(sample[k]
                                   for k in self.settings.input_types)
                yield sample

    def __call__(self):
        if self.cache == CacheType.CACHE_PASS_IN_MEM:
            if self._pass_cache is None:
                self._pass_cache = list(self._iter_raw())
            data: Any = list(self._pass_cache)
            if self.should_shuffle:
                self._rng.shuffle(data)
            return iter(data)
        if self.should_shuffle:
            if self.pool_size > 0:
                return self._pooled_iter()
            # pool_size 0 = unlimited pool (the reference's default):
            # full-pass in-memory shuffle.
            data = list(self._iter_raw())
            self._rng.shuffle(data)
            return iter(data)
        return self._iter_raw()

    def _pooled_iter(self):
        """Reservoir-pool shuffle (the reference's pool_size semantics:
        fill a pool, emit randomly, refill — bounded memory)."""
        pool: List[Any] = []
        for sample in self._iter_raw():
            pool.append(sample)
            if len(pool) >= self.pool_size:
                self._rng.shuffle(pool)
                half = len(pool) // 2
                for s in pool[:half]:
                    yield s
                pool = pool[half:]
        self._rng.shuffle(pool)
        yield from pool


def provider(input_types: Union[Dict[str, Any], Sequence[Any], None] = None,
             cache: CacheType = CacheType.NO_CACHE,
             pool_size: int = 0,
             should_shuffle: bool = True,
             init_hook: Optional[Callable] = None,
             calc_batch_size: Optional[Callable] = None,
             seed: Optional[int] = 0,
             **extra_settings):
    """Decorator turning ``process(settings, filename)`` generators into
    :class:`DataProvider` factories (``@provider`` twin,
    ``PyDataProvider2.py:365``).

    The decorated function becomes ``factory(files, **hook_kwargs) ->
    DataProvider``.  ``input_types`` may be a name→type dict (preferred
    here; samples may then be dicts) or a positional list.  ``init_hook``
    runs once per construction: ``init_hook(settings, files=files,
    **hook_kwargs)`` and may set/replace ``settings.input_types``.
    ``calc_batch_size`` is accepted for signature parity (batch sizing
    lives in ``reader.batch`` here).
    """

    def wrap(process: Callable) -> Callable:
        def factory(files: Union[str, Sequence[str]],
                    **hook_kwargs) -> DataProvider:
            if isinstance(files, str):
                files = [files]
            types = input_types
            if isinstance(types, (list, tuple)):
                types = {f"slot{i}": t for i, t in enumerate(types)}
            settings = Settings(dict(types or {}), **extra_settings)
            if init_hook is not None:
                init_hook(settings, files=list(files), **hook_kwargs)
            enforce(settings.input_types,
                    "provider %r has no input_types (pass input_types= or "
                    "set settings.input_types in init_hook)",
                    getattr(process, "__name__", "?"))
            return DataProvider(process, files, settings, pool_size, cache,
                                should_shuffle, seed)

        factory.__name__ = getattr(process, "__name__", "provider")
        factory.origin = process
        # Declared types, introspectable without constructing a provider
        # (v1 data_layer uses this to infer sequence-ness by slot name).
        factory.input_types = input_types
        return factory

    return wrap
