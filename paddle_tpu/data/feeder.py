"""Batch feeding: python samples -> device-ready arrays.

Twin of the reference's DataFeeder/DataProviderConverter
(``paddle/py_paddle/dataprovider_converter.py``, ``v2/data_feeder.py``) and
of ``Argument.sequenceStartPositions``: declared input types map each sample
slot to a dense array; variable-length sequence slots are padded to the
batch max (or a bucket boundary) and paired with a boolean mask, which is
the TPU-native replacement for the reference's packed offset vectors (static
shapes for XLA; bucketing bounds recompilation).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Dense:
    """Fixed-shape float slot (twin of dense_vector input type)."""
    shape: Tuple[int, ...]
    dtype: Any = np.float32


@dataclasses.dataclass(frozen=True)
class Integer:
    """Scalar int slot (twin of integer_value)."""
    dtype: Any = np.int32


@dataclasses.dataclass(frozen=True)
class IntSequence:
    """Variable-length int sequence slot (twin of integer_value_sequence).

    Produces (padded_ids [b, t], mask [b, t]).
    """
    pad_value: int = 0
    buckets: Optional[Sequence[int]] = None
    dtype: Any = np.int32


@dataclasses.dataclass(frozen=True)
class DenseSequence:
    """Variable-length sequence of fixed-dim vectors
    (twin of dense_vector_sequence).  Produces (padded [b, t, d], mask)."""
    dim: int
    pad_value: float = 0.0
    buckets: Optional[Sequence[int]] = None
    dtype: Any = np.float32


@dataclasses.dataclass(frozen=True)
class SparseBinary:
    """Sparse 0/1 vector slot given as active indices (twin of
    sparse_binary_vector).  Densified to a multi-hot [dim] float row — the
    TPU-native layout (static shapes; XLA has no CSR) of the reference's
    binary CSR rows (``Matrix.h:66`` CpuSparseMatrix NO_VALUE)."""
    dim: int
    dtype: Any = np.float32


@dataclasses.dataclass(frozen=True)
class SparseFloat:
    """Sparse float vector slot given as (index, value) pairs (twin of
    sparse_float_vector); densified to a [dim] float row."""
    dim: int
    dtype: Any = np.float32


@dataclasses.dataclass(frozen=True)
class SparseBinarySequence:
    """Variable-length sequence of sparse 0/1 vectors, each given as
    active indices (twin of sparse_binary_vector_sequence); densified to
    (multi-hot [b, t, dim], mask [b, t])."""
    dim: int
    buckets: Optional[Sequence[int]] = None
    dtype: Any = np.float32


def _bucket_len(n: int, buckets: Optional[Sequence[int]]) -> int:
    if not buckets:
        return n
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class DataFeeder:
    """Convert a list of samples (tuples aligned with feed_types) into a
    dict of numpy arrays keyed by the given names."""

    def __init__(self, feed_types: Sequence[Any], names: Sequence[str]):
        assert len(feed_types) == len(names)
        self.feed_types = list(feed_types)
        self.names = list(names)

    def __call__(self, samples: List[Tuple]) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        cols = list(zip(*samples))
        for ftype, name, col in zip(self.feed_types, self.names, cols):
            if isinstance(ftype, Dense):
                out[name] = np.stack(
                    [np.asarray(x, ftype.dtype).reshape(ftype.shape)
                     for x in col])
            elif isinstance(ftype, Integer):
                out[name] = np.asarray(col, ftype.dtype)
            elif isinstance(ftype, IntSequence):
                max_len = _bucket_len(max(len(x) for x in col), ftype.buckets)
                b = len(col)
                ids = np.full((b, max_len), ftype.pad_value, ftype.dtype)
                mask = np.zeros((b, max_len), bool)
                for i, x in enumerate(col):
                    n = min(len(x), max_len)
                    ids[i, :n] = np.asarray(x[:n], ftype.dtype)
                    mask[i, :n] = True
                out[name] = ids
                out[name + "_mask"] = mask
            elif isinstance(ftype, DenseSequence):
                max_len = _bucket_len(max(len(x) for x in col), ftype.buckets)
                b = len(col)
                arr = np.full((b, max_len, ftype.dim), ftype.pad_value,
                              ftype.dtype)
                mask = np.zeros((b, max_len), bool)
                for i, x in enumerate(col):
                    n = min(len(x), max_len)
                    arr[i, :n] = np.asarray(x[:n], ftype.dtype)
                    mask[i, :n] = True
                out[name] = arr
                out[name + "_mask"] = mask
            elif isinstance(ftype, SparseBinary):
                arr = np.zeros((len(col), ftype.dim), ftype.dtype)
                for i, idxs in enumerate(col):
                    arr[i, np.asarray(list(idxs), np.int64)] = 1.0
                out[name] = arr
            elif isinstance(ftype, SparseFloat):
                arr = np.zeros((len(col), ftype.dim), ftype.dtype)
                for i, pairs in enumerate(col):
                    for j, v in pairs:
                        arr[i, j] = v
                out[name] = arr
            elif isinstance(ftype, SparseBinarySequence):
                max_len = _bucket_len(max(len(x) for x in col), ftype.buckets)
                b = len(col)
                arr = np.zeros((b, max_len, ftype.dim), ftype.dtype)
                mask = np.zeros((b, max_len), bool)
                for i, steps in enumerate(col):
                    n = min(len(steps), max_len)
                    for t, idxs in enumerate(list(steps)[:n]):
                        arr[i, t, np.asarray(list(idxs), np.int64)] = 1.0
                    mask[i, :n] = True
                out[name] = arr
                out[name + "_mask"] = mask
            else:
                raise TypeError(f"Unknown feed type {ftype!r}")
        return out
