"""Image preprocessing utilities (``python/paddle/v2/image.py`` twin).

The reference wraps PIL/cv2 for load/resize/crop/flip/normalize used by the
image demos and the ImageNet input pipeline.  Pure-numpy implementations
here (bilinear resize included) so the pipeline has no extra dependencies;
layouts are HWC uint8/float like the reference's, with ``to_chw`` for
converting to its CHW convention (our conv layers take NHWC).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.core.errors import enforce

__all__ = ["resize_short", "resize", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "to_chw", "batch_images"]


def resize(im: np.ndarray, size: Tuple[int, int]) -> np.ndarray:
    """Bilinear resize of an HWC (or HW) image to (h, w)."""
    h, w = im.shape[:2]
    oh, ow = size
    if (h, w) == (oh, ow):
        return im
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    fim = im.astype(np.float32)
    r0, r1 = fim[y0], fim[y1]
    top = r0[:, x0] * (1 - wx) + r0[:, x1] * wx
    bot = r1[:, x0] * (1 - wx) + r1[:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return np.rint(out).astype(im.dtype) \
        if np.issubdtype(im.dtype, np.integer) else out


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the shorter edge equals ``size`` (resize_short twin)."""
    h, w = im.shape[:2]
    if h < w:
        return resize(im, (size, int(round(w * size / h))))
    return resize(im, (int(round(h * size / w)), size))


def center_crop(im: np.ndarray, size: int) -> np.ndarray:
    h, w = im.shape[:2]
    enforce(h >= size and w >= size,
            "center_crop: image %dx%d smaller than crop %d", h, w, size)
    y = (h - size) // 2
    x = (w - size) // 2
    return im[y:y + size, x:x + size]


def random_crop(im: np.ndarray, size: int,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    enforce(h >= size and w >= size,
            "random_crop: image %dx%d smaller than crop %d", h, w, size)
    y = rng.randint(0, h - size + 1)
    x = rng.randint(0, w - size + 1)
    return im[y:y + size, x:x + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def to_chw(im: np.ndarray) -> np.ndarray:
    """HWC -> CHW (the reference's layout; our conv layers take NHWC)."""
    return np.transpose(im, (2, 0, 1))


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool,
                     mean: Optional[Sequence[float]] = None,
                     scale: float = 1.0,
                     rng: Optional[np.random.RandomState] = None
                     ) -> np.ndarray:
    """resize-short + crop (+ random flip when training) + normalize
    (simple_transform twin) — returns float32 HWC."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng)
        if (rng or np.random).randint(0, 2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = im.astype(np.float32) * scale
    if mean is not None:
        im = im - np.asarray(mean, np.float32)
    return im


def batch_images(images: Sequence[np.ndarray]) -> np.ndarray:
    """Stack HWC images into an NHWC batch."""
    return np.stack([np.asarray(im, np.float32) for im in images])
