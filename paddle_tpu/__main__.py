"""``python -m paddle_tpu`` — the `paddle` CLI twin (see cli.py)."""

from paddle_tpu.cli import main

if __name__ == "__main__":
    main()
