"""Recurrent layers: LSTM, GRU, simple RNN — built on ``lax.scan``.

TPU-native replacement for the reference's recurrent stack:

* ``LstmLayer``/``GruLayer`` + the fused per-frame CUDA kernels
  (``paddle/gserver/layers/LstmLayer.h:73``, ``hl_lstm_ops.cuh``,
  ``hl_gru_ops.cuh``) become a single ``lax.scan`` whose body XLA fuses —
  the input-to-hidden projection for *all* timesteps is one big MXU matmul
  hoisted out of the scan, which is exactly the trick the reference's
  ``SequenceToBatch`` scheme (``SequenceToBatch.h:23-46``) approximates with
  batch reordering.
* Variable-length sequences use a ``[batch, time]`` boolean mask instead of
  ``sequenceStartPositions`` (``parameter/Argument.h:84``): masked steps
  carry the previous state forward, so padded batches compute identical
  results to the reference's padding-free scheme while keeping shapes static
  for XLA.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dtypes import get_policy
from paddle_tpu.core.errors import enforce
from paddle_tpu.nn import initializers as init
from paddle_tpu.nn.module import Module, param
from paddle_tpu.ops import activations, pallas_kernels


def _mask_state(new, old, mask_t):
    # mask_t: [batch] bool; keep old state where this step is padding.
    m = mask_t[:, None]
    return jnp.where(m, new, old)


def gru_cell(gates_x, h_prev, w_hz, w_hc, act, gate_act, policy):
    """One GRU step shared by the scan layer and the api gru_step node:
    ``gates_x`` is the 3h input projection [batch, 3h] (z, r, candidate),
    ``w_hz``/``w_hc`` already in compute dtype."""
    h = h_prev.shape[-1]
    # MXU accumulates the bf16 recurrence matmuls in f32 (tpu-lint:
    # accum-dtype); cast_to_output then narrows once, after the sum.
    zr = gates_x[:, :2 * h] + policy.cast_to_output(
        jnp.matmul(policy.cast_to_compute(h_prev), w_hz,
                   preferred_element_type=jnp.float32))
    z, r = jnp.split(gate_act(zr), 2, axis=-1)
    cand = gates_x[:, 2 * h:] + policy.cast_to_output(
        jnp.matmul(policy.cast_to_compute(r * h_prev), w_hc,
                   preferred_element_type=jnp.float32))
    cand = act(cand)
    return (1.0 - z) * h_prev + z * cand


class LSTM(Module):
    """Unidirectional LSTM over [batch, time, dim] (twin of LstmLayer).

    Gate order follows the reference (input, forget, cell, output).  Returns
    the full hidden-state sequence and the final (h, c).
    """

    def __init__(self, hidden: int, act="tanh", gate_act="sigmoid",
                 reverse: bool = False, name: Optional[str] = None,
                 use_pallas: Optional[bool] = None):
        super().__init__(name)
        self.hidden = hidden
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)
        # With the default activations (tanh/sigmoid — the reference's
        # hl_lstm_ops.cuh config) the recurrence routes through
        # ops/pallas_kernels.lstm_scan; the LIVE (h, c) carry and gate
        # math are f32 on every backend, while the fused kernels' xw/hs
        # HBM streams follow the policy dtype (bf16 under mixed
        # precision) — so mixed-policy results are bf16-tier and can
        # differ from the always-f32 lax.scan fallback.  Custom
        # activations use the policy-dtype scan below.  ``use_pallas``
        # forces the kernel choice (tests).
        self._fusable = act == "tanh" and gate_act == "sigmoid"
        self.use_pallas = use_pallas
        self.reverse = reverse

    def forward(self, x, mask=None, initial_state=None):
        policy = get_policy()
        b, t, d = x.shape
        h = self.hidden
        w_x = param("w_x", (d, 4 * h), policy.param_dtype,
                    init.paddle_default())
        w_h = param("w_h", (h, 4 * h), policy.param_dtype,
                    init.paddle_default())
        bias = param("b", (4 * h,), policy.param_dtype, init.zeros)

        # One big MXU matmul for all timesteps; only the h-recurrence scans.
        xw = jnp.einsum("btd,dk->btk", policy.cast_to_compute(x),
                        policy.cast_to_compute(w_x),
                        preferred_element_type=jnp.float32)
        xw = policy.cast_to_output(xw) + bias.astype(policy.output_dtype)

        if initial_state is None:
            h0 = jnp.zeros((b, h), x.dtype)
            c0 = jnp.zeros((b, h), x.dtype)
        else:
            h0, c0 = initial_state

        if mask is None:
            mask = jnp.ones((b, t), bool)

        xw_t = jnp.swapaxes(xw, 0, 1)          # [time, batch, 4h]
        mask_t = jnp.swapaxes(mask, 0, 1)      # [time, batch]
        if self.reverse:
            xw_t = xw_t[::-1]
            mask_t = mask_t[::-1]

        if self._fusable:
            out_dtype = xw_t.dtype
            # xw streams to the kernel in the policy dtype (bf16 under
            # mixed precision — half the HBM traffic of the dominant
            # stream and no boundary casts); the kernel's live (h, c)
            # carry and gate math stay f32 regardless.
            hs, h_last, c_last = pallas_kernels.lstm_scan(
                xw_t, w_h.astype(jnp.float32),
                h0.astype(jnp.float32), c0.astype(jnp.float32), mask_t,
                use_pallas=self.use_pallas)
            hs = hs.astype(out_dtype)
            h_last = h_last.astype(out_dtype)
            c_last = c_last.astype(out_dtype)
        else:
            w_h_c = policy.cast_to_compute(w_h)

            def step(carry, inp):
                h_prev, c_prev = carry
                gates_x, m = inp
                gates = gates_x + policy.cast_to_output(
                    jnp.matmul(policy.cast_to_compute(h_prev), w_h_c,
                               preferred_element_type=jnp.float32))
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i = self.gate_act(i)
                f = self.gate_act(f)
                o = self.gate_act(o)
                g = self.act(g)
                c = f * c_prev + i * g
                hh = o * self.act(c)
                c = _mask_state(c, c_prev, m)
                hh = _mask_state(hh, h_prev, m)
                return (hh, c), hh

            (h_last, c_last), hs = lax.scan(step, (h0, c0), (xw_t, mask_t))
        if self.reverse:
            hs = hs[::-1]
        return jnp.swapaxes(hs, 0, 1), (h_last, c_last)


class GRU(Module):
    """GRU over [batch, time, dim] (twin of GruLayer / hl_gru_ops.cuh).

    Gate order: update (z), reset (r), candidate.  With the default
    tanh/sigmoid activations the recurrence routes through
    ``ops/pallas_kernels.gru_scan`` (fused VMEM-resident kernel on TPU,
    ``lax.scan`` elsewhere) carried in f32, like the LSTM.
    """

    def __init__(self, hidden: int, act="tanh", gate_act="sigmoid",
                 reverse: bool = False, name: Optional[str] = None,
                 use_pallas: Optional[bool] = None):
        super().__init__(name)
        self.hidden = hidden
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)
        self._fusable = act == "tanh" and gate_act == "sigmoid"
        self.use_pallas = use_pallas
        self.reverse = reverse

    def forward(self, x, mask=None, initial_state=None):
        policy = get_policy()
        b, t, d = x.shape
        h = self.hidden
        w_x = param("w_x", (d, 3 * h), policy.param_dtype,
                    init.paddle_default())
        w_hz = param("w_hz", (h, 2 * h), policy.param_dtype,
                     init.paddle_default())
        w_hc = param("w_hc", (h, h), policy.param_dtype,
                     init.paddle_default())
        bias = param("b", (3 * h,), policy.param_dtype, init.zeros)

        xw = jnp.einsum("btd,dk->btk", policy.cast_to_compute(x),
                        policy.cast_to_compute(w_x),
                        preferred_element_type=jnp.float32)
        xw = policy.cast_to_output(xw) + bias.astype(policy.output_dtype)

        h0 = jnp.zeros((b, h), x.dtype) if initial_state is None else initial_state
        if mask is None:
            mask = jnp.ones((b, t), bool)

        xw_t = jnp.swapaxes(xw, 0, 1)
        mask_t = jnp.swapaxes(mask, 0, 1)
        if self.reverse:
            xw_t = xw_t[::-1]
            mask_t = mask_t[::-1]

        if self._fusable:
            out_dtype = xw_t.dtype
            hs, h_last = pallas_kernels.gru_scan(
                xw_t.astype(jnp.float32), w_hz.astype(jnp.float32),
                w_hc.astype(jnp.float32), h0.astype(jnp.float32), mask_t,
                use_pallas=self.use_pallas)
            hs = hs.astype(out_dtype)
            h_last = h_last.astype(out_dtype)
        else:
            w_hz_c = policy.cast_to_compute(w_hz)
            w_hc_c = policy.cast_to_compute(w_hc)

            def step(h_prev, inp):
                gates_x, m = inp
                hh = gru_cell(gates_x, h_prev, w_hz_c, w_hc_c, self.act,
                              self.gate_act, policy)
                hh = _mask_state(hh, h_prev, m)
                return hh, hh

            h_last, hs = lax.scan(step, h0, (xw_t, mask_t))
        if self.reverse:
            hs = hs[::-1]
        return jnp.swapaxes(hs, 0, 1), h_last


class SimpleRNN(Module):
    """Plain recurrent layer (twin of RecurrentLayer.cpp).

    With ``project_input=False`` the input IS the pre-computed projection
    (must already be ``hidden`` wide) and only ``w_h`` + bias are learned —
    the reference RecurrentLayer's exact contract (its only weight is the
    hidden-hidden ``getSize() x getSize()`` matrix)."""

    def __init__(self, hidden: int, act="tanh", reverse: bool = False,
                 project_input: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.hidden = hidden
        self.act = activations.get(act)
        self.reverse = reverse
        self.project_input = project_input

    def forward(self, x, mask=None, initial_state=None):
        policy = get_policy()
        b, t, d = x.shape
        h = self.hidden
        w_h = param("w_h", (h, h), policy.param_dtype, init.paddle_default())
        bias = param("b", (h,), policy.param_dtype, init.zeros)

        if self.project_input:
            w_x = param("w_x", (d, h), policy.param_dtype,
                        init.paddle_default())
            xw = jnp.einsum("btd,dk->btk", policy.cast_to_compute(x),
                            policy.cast_to_compute(w_x),
                            preferred_element_type=jnp.float32)
            xw = policy.cast_to_output(xw) + bias.astype(policy.output_dtype)
        else:
            enforce(d == h, "SimpleRNN(project_input=False): input width "
                    "%d must equal hidden %d", d, h)
            xw = x + bias.astype(x.dtype)
        h0 = jnp.zeros((b, h), x.dtype) if initial_state is None else initial_state
        if mask is None:
            mask = jnp.ones((b, t), bool)
        xw_t = jnp.swapaxes(xw, 0, 1)
        mask_t = jnp.swapaxes(mask, 0, 1)
        if self.reverse:
            xw_t = xw_t[::-1]
            mask_t = mask_t[::-1]
        w_h_c = policy.cast_to_compute(w_h)

        def step(h_prev, inp):
            gx, m = inp
            hh = self.act(gx + policy.cast_to_output(
                jnp.matmul(policy.cast_to_compute(h_prev), w_h_c,
                           preferred_element_type=jnp.float32)))
            hh = _mask_state(hh, h_prev, m)
            return hh, hh

        h_last, hs = lax.scan(step, h0, (xw_t, mask_t))
        if self.reverse:
            hs = hs[::-1]
        return jnp.swapaxes(hs, 0, 1), h_last


class BiLSTM(Module):
    """Bidirectional LSTM (twin of bidirectional_lstm in networks.py)."""

    def __init__(self, hidden: int, name: Optional[str] = None, **kwargs):
        super().__init__(name)
        self.fwd = LSTM(hidden, name="fw", **kwargs)
        self.bwd = LSTM(hidden, reverse=True, name="bw", **kwargs)

    def forward(self, x, mask=None):
        hf, _ = self.fwd(x, mask)
        hb, _ = self.bwd(x, mask)
        return jnp.concatenate([hf, hb], axis=-1)
