from paddle_tpu.nn.module import (Module, Transformed, transform, param, state,
                                  set_state, is_training, next_rng_key,
                                  flatten_names, unflatten_names)
from paddle_tpu.nn import initializers
from paddle_tpu.nn.layers import (Linear, Embedding, Conv2D, Pool2D,
                                  GlobalPool2D, BatchNorm, LayerNorm, Dropout,
                                  Maxout, CrossChannelNorm, Sequential)

__all__ = [
    "Module", "Transformed", "transform", "param", "state", "set_state",
    "is_training", "next_rng_key", "flatten_names", "unflatten_names",
    "initializers", "Linear", "Embedding", "Conv2D", "Pool2D", "GlobalPool2D",
    "BatchNorm", "LayerNorm", "Dropout", "Maxout", "CrossChannelNorm",
    "Sequential",
]
