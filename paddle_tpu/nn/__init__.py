"""Module system and layer zoo (functional, transform-based — the
Layer-registry twin of ref:paddle/gserver/layers)."""
from paddle_tpu.nn.module import (Module, Transformed, transform, param, state,
                                  set_state, is_training, next_rng_key,
                                  flatten_names, unflatten_names, remat,
                                  escape_name, unescape_name)
from paddle_tpu.nn import initializers
from paddle_tpu.nn.layers import (Linear, Embedding, Conv2D, Pool2D,
                                  GlobalPool2D, BatchNorm, LayerNorm, Dropout,
                                  Maxout, CrossChannelNorm, Sequential)
from paddle_tpu.nn.layers_extra import (
    Conv2DTranspose, Conv3D, Pool3D, SpatialPyramidPool, RowConv, BlockExpand,
    BilinearInterp, Interpolation, Crop, Pad, Rotate, SwitchOrder,
    FeatureMapExpand, Multiplex, SelectiveFC, DataNorm, DataNormTable,
    SumToOneNorm, Scaling,
    SlopeIntercept, Addto, DotMulProjection, ScalingProjection,
    IdentityProjection, TransposedFullMatrixProjection, Mixed,
    FullMatrixProjection, TableProjection, SliceProjection, ConvProjection,
    PReLU, TensorLayer, GatedUnit, ConvShift, OutProd, RowL2Norm, ScaleShift,
    MDLstm2D)

__all__ = [
    "Module", "Transformed", "transform", "param", "state", "set_state",
    "is_training", "next_rng_key", "flatten_names", "unflatten_names",
    "escape_name", "unescape_name",
    "remat", "initializers", "Linear", "Embedding", "Conv2D", "Pool2D",
    "GlobalPool2D", "BatchNorm", "LayerNorm", "Dropout", "Maxout",
    "CrossChannelNorm", "Sequential",
    "Conv2DTranspose", "Conv3D", "Pool3D", "SpatialPyramidPool", "RowConv",
    "BlockExpand", "BilinearInterp", "Interpolation", "Crop", "Pad", "Rotate",
    "SwitchOrder", "FeatureMapExpand", "Multiplex", "SelectiveFC", "DataNorm",
    "DataNormTable",
    "SumToOneNorm", "Scaling", "SlopeIntercept", "Addto", "DotMulProjection",
    "ScalingProjection", "IdentityProjection",
    "TransposedFullMatrixProjection", "Mixed",
    "FullMatrixProjection", "TableProjection", "SliceProjection",
    "ConvProjection", "PReLU", "TensorLayer", "GatedUnit", "ConvShift",
    "OutProd", "RowL2Norm", "ScaleShift", "MDLstm2D",
]
