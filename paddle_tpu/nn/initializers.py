"""Parameter initializers.

Twin of the reference's ``Parameter::randomize`` modes
(``paddle/parameter/Parameter.cpp``): the default v1 scheme is
uniform(-sqrt(3/dim), +sqrt(3/dim)) on the input dim ("initial_strategy=0"),
with explicit normal/uniform overrides — plus the modern Xavier/He variants
the layer zoo effectively assumed for convs.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def zeros(key, shape, dtype):
    del key
    return jnp.zeros(shape, dtype)


def ones(key, shape, dtype):
    del key
    return jnp.ones(shape, dtype)


def constant(value: float):
    def init(key, shape, dtype):
        del key
        return jnp.full(shape, value, dtype)
    return init


def normal(std: float = 0.01, mean: float = 0.0):
    def init(key, shape, dtype):
        return mean + std * jax.random.normal(key, shape, dtype)
    return init


def uniform(scale: float):
    def init(key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


def paddle_default(fan_in_axis: int = 0):
    """v1 default: uniform with scale sqrt(3/fan_in) (Parameter.cpp randomize)."""
    def init(key, shape, dtype):
        fan_in = shape[fan_in_axis] if shape else 1
        scale = np.sqrt(3.0 / max(1, fan_in))
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


def xavier_uniform(fan_in: int = None, fan_out: int = None):
    def init(key, shape, dtype):
        fin = fan_in if fan_in is not None else _fan(shape)[0]
        fout = fan_out if fan_out is not None else _fan(shape)[1]
        scale = np.sqrt(6.0 / (fin + fout))
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


def he_normal():
    def init(key, shape, dtype):
        fin = _fan(shape)[0]
        return jax.random.normal(key, shape, dtype) * np.sqrt(2.0 / fin)
    return init


def _fan(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels HWIO
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive
