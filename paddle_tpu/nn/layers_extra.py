"""Extended layer zoo — the rest of the reference's 92 registered layers.

TPU-native twins of the remaining ``paddle/gserver/layers/*`` families
(SURVEY.md §2.2): transposed/3-D convolution, spatial-pyramid pooling,
row (lookahead) convolution, block-expand (im2col-as-layer), interpolation
and bilinear upsampling, crop/pad/rotate/switch-order, feature-map expand,
multiplex, selective FC, data normalization, and the MixedLayer
projection/operator family (``MixedLayer.{h,cpp}``, ``Projection.h``,
``Operator.h``).

Everything is a thin composition of jnp/lax ops: XLA fuses what the
reference hand-wrote as CUDA kernels (``hl_cnn.h``: ``hl_maxout_forward``,
``hl_expand_feature`` etc.), and convolution variants lower straight onto
the MXU without im2col.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dtypes import get_policy
from paddle_tpu.core.errors import enforce, enforce_in
from paddle_tpu.nn import initializers as init
from paddle_tpu.nn.layers import Conv2D, IntOrPair, _pair
from paddle_tpu.nn.module import Module, param, next_rng_key


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]), int(v[2]))
    return (int(v),) * 3


class Conv2DTranspose(Module):
    """Transposed (fractionally-strided) conv, NHWC/HWIO — twin of the
    backward-as-forward conv layers (ExpandConvTransLayer,
    ``gserver/layers/ConvTransBaseLayer.h``)."""

    def __init__(self, channels: int, kernel: IntOrPair,
                 stride: IntOrPair = 1, padding: Union[str, IntOrPair] = "SAME",
                 act="linear", bias: bool = True, w_init=None,
                 name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.ops import activations
        self.channels = channels
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        if isinstance(padding, str):
            self.padding = padding.upper()
        else:
            p = _pair(padding)
            self.padding = [(p[0], p[0]), (p[1], p[1])]
        self.act = activations.get(act)
        self.bias = bias
        self.w_init = w_init or init.he_normal()

    def forward(self, x):
        policy = get_policy()
        in_ch = x.shape[-1]
        kshape = (*self.kernel, in_ch, self.channels)
        w = param("w", kshape, policy.param_dtype, self.w_init)
        y = lax.conv_transpose(
            policy.cast_to_compute(x), policy.cast_to_compute(w),
            strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = policy.cast_to_output(y)
        if self.bias:
            b = param("b", (self.channels,), policy.param_dtype, init.zeros)
            y = y + b.astype(y.dtype)
        return self.act(y)


class Conv3D(Module):
    """3-D convolution, NDHWC/DHWIO (twin of Conv3DLayer.cpp)."""

    def __init__(self, channels: int, kernel, stride=1,
                 padding: Union[str, Sequence[int]] = "SAME", act="linear",
                 bias: bool = True, w_init=None, name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.ops import activations
        self.channels = channels
        self.kernel = _triple(kernel)
        self.stride = _triple(stride)
        if isinstance(padding, str):
            self.padding = padding.upper()
        else:
            p = _triple(padding)
            self.padding = [(pi, pi) for pi in p]
        self.act = activations.get(act)
        self.bias = bias
        self.w_init = w_init or init.he_normal()

    def forward(self, x):
        policy = get_policy()
        in_ch = x.shape[-1]
        kshape = (*self.kernel, in_ch, self.channels)
        w = param("w", kshape, policy.param_dtype, self.w_init)
        y = lax.conv_general_dilated(
            policy.cast_to_compute(x), policy.cast_to_compute(w),
            window_strides=self.stride, padding=self.padding,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        y = policy.cast_to_output(y)
        if self.bias:
            b = param("b", (self.channels,), policy.param_dtype, init.zeros)
            y = y + b.astype(y.dtype)
        return self.act(y)


class Pool3D(Module):
    """3-D max/avg pooling over NDHWC (twin of Pool3DLayer.cpp)."""

    def __init__(self, kernel, stride=None, pool_type: str = "max",
                 name: Optional[str] = None):
        super().__init__(name)
        enforce_in(pool_type, ("max", "avg"))
        self.kernel = _triple(kernel)
        self.stride = _triple(stride) if stride is not None else self.kernel
        self.pool_type = pool_type

    def forward(self, x):
        window = (1, *self.kernel, 1)
        strides = (1, *self.stride, 1)
        if self.pool_type == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                     "VALID")
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, "VALID")
        return summed / (self.kernel[0] * self.kernel[1] * self.kernel[2])


def _adaptive_pool2d(x, bins: int, pool_type: str):
    """Adaptive pooling to a bins×bins grid: output cell (i, j) reduces the
    input window [floor(i*h/bins), ceil((i+1)*h/bins)) × (same for w) —
    every window is non-empty and windows tile the valid region exactly, so
    no padding values ever enter the reduction (the reference's ceil-mode
    pooling semantics).  Bin edges are static Python ints; the loop unrolls
    into ``2*bins`` static slices XLA fuses."""
    _, h, w, _ = x.shape

    def edges(size):
        return [((i * size) // bins, -(-((i + 1) * size) // bins))
                for i in range(bins)]

    red = jnp.max if pool_type == "max" else jnp.mean
    rows = jnp.stack([red(x[:, s:e], axis=1) for s, e in edges(h)], axis=1)
    cols = jnp.stack([red(rows[:, :, s:e], axis=2) for s, e in edges(w)],
                     axis=2)
    return cols  # [n, bins, bins, c]


class SpatialPyramidPool(Module):
    """SPP layer (twin of SpatialPyramidPoolLayer.cpp): pools the feature
    map at ``levels`` pyramid scales (1x1, 2x2, 4x4, ...) and concatenates
    the flattened bins — output size is input-size independent."""

    def __init__(self, levels: int = 3, pool_type: str = "max",
                 name: Optional[str] = None):
        super().__init__(name)
        enforce_in(pool_type, ("max", "avg"))
        self.levels = levels
        self.pool_type = pool_type

    def forward(self, x):
        n = x.shape[0]
        outs = []
        for lvl in range(self.levels):
            pooled = _adaptive_pool2d(x, 2 ** lvl, self.pool_type)
            outs.append(pooled.reshape(n, -1))
        return jnp.concatenate(outs, axis=-1)


class RowConv(Module):
    """Row (lookahead) convolution over [batch, time, dim] — twin of
    RowConvLayer / ``paddle/function/RowConvOp.cpp``: each timestep mixes
    the next ``future_steps`` frames with a per-dim learned window
    (DeepSpeech2-style streaming context)."""

    def __init__(self, future_steps: int, name: Optional[str] = None):
        super().__init__(name)
        self.future_steps = future_steps

    def forward(self, x):
        policy = get_policy()
        d = x.shape[-1]
        k = self.future_steps + 1
        w = param("w", (k, d), policy.param_dtype, init.paddle_default())
        # depthwise 1-D conv looking forward: pad the time axis on the right.
        xp = jnp.pad(x, ((0, 0), (0, self.future_steps), (0, 0)))
        y = jnp.zeros_like(x)
        for i in range(k):  # k is small and static; XLA unrolls+fuses.
            y = y + xp[:, i:i + x.shape[1], :] * w[i]
        return y


class BlockExpand(Module):
    """im2col as a layer (twin of BlockExpandLayer.cpp): cuts NHWC feature
    maps into (block_h × block_w) patches and returns [batch, n_blocks,
    block_h*block_w*c] — the sequence form used by OCR/CTC pipelines."""

    def __init__(self, block: IntOrPair, stride: IntOrPair,
                 padding: IntOrPair = 0, name: Optional[str] = None):
        super().__init__(name)
        self.block = _pair(block)
        self.stride = _pair(stride)
        self.padding = _pair(padding)

    def forward(self, x):
        n, hh, ww, c = x.shape
        ph, pw = self.padding
        xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        bh, bw = self.block
        patches = lax.conv_general_dilated_patches(
            jnp.moveaxis(xp, -1, 1), (bh, bw), self.stride, "VALID")
        # [n, c*bh*bw, oh, ow] -> [n, oh*ow, bh*bw*c]
        n_, cb, oh, ow = patches.shape
        return jnp.moveaxis(patches.reshape(n_, cb, oh * ow), 1, 2)


class BilinearInterp(Module):
    """Bilinear upsampling to a fixed output size (twin of
    BilinearInterpLayer.cpp / ``hl_cnn.h`` bilinear kernels)."""

    def __init__(self, out_h: int, out_w: int, name: Optional[str] = None):
        super().__init__(name)
        self.out_h = out_h
        self.out_w = out_w

    def forward(self, x):
        n, hh, ww, c = x.shape
        return jax.image.resize(x, (n, self.out_h, self.out_w, c),
                                method="bilinear")


class Interpolation(Module):
    """Learned-free lerp of two inputs by a per-sample weight (twin of
    InterpolationLayer.cpp): ``out = w*x + (1-w)*y``."""

    def forward(self, w, x, y):
        w = w.reshape(w.shape[0], *([1] * (x.ndim - 1)))
        return w * x + (1.0 - w) * y


class Crop(Module):
    """Static crop of NHWC maps (twin of CropLayer / crop_op)."""

    def __init__(self, offsets: Sequence[int], shape: Sequence[int],
                 name: Optional[str] = None):
        super().__init__(name)
        self.offsets = tuple(offsets)
        self.shape = tuple(shape)

    def forward(self, x):
        starts = (0,) + self.offsets + (0,)
        sizes = (x.shape[0],) + self.shape + (x.shape[-1],)
        return lax.dynamic_slice(x, starts, sizes)


class Pad(Module):
    """Zero-pad NHWC maps (twin of PadLayer / pad_op)."""

    def __init__(self, pad_h: Tuple[int, int], pad_w: Tuple[int, int],
                 pad_c: Tuple[int, int] = (0, 0), name: Optional[str] = None):
        super().__init__(name)
        self.pads = ((0, 0), tuple(pad_h), tuple(pad_w), tuple(pad_c))

    def forward(self, x):
        return jnp.pad(x, self.pads)


class Rotate(Module):
    """90° CCW rotation of the spatial dims (twin of RotateLayer.cpp)."""

    def forward(self, x):
        return jnp.rot90(x, k=1, axes=(1, 2))


class SwitchOrder(Module):
    """Axis permutation (twin of SwitchOrderLayer / transpose_op)."""

    def __init__(self, perm: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.perm = tuple(perm)

    def forward(self, x):
        return jnp.transpose(x, self.perm)


class FeatureMapExpand(Module):
    """Broadcast a [batch, dim] vector across ``num_filters`` feature maps
    (twin of FeatureMapExpandLayer.cpp)."""

    def __init__(self, num_filters: int, as_row: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.num_filters = num_filters
        self.as_row = as_row

    def forward(self, x):
        if self.as_row:
            return jnp.repeat(x[:, None, :], self.num_filters, axis=1)
        return jnp.repeat(x[:, :, None], self.num_filters, axis=2)


class Multiplex(Module):
    """Row-wise select among K inputs by index (twin of MultiplexLayer.cpp)."""

    def forward(self, index, *inputs):
        stacked = jnp.stack(inputs, axis=0)          # [K, batch, ...]
        return jnp.take_along_axis(
            stacked, index.reshape(1, -1, *([1] * (stacked.ndim - 2))),
            axis=0)[0]


class SelectiveFC(Module):
    """Fully-connected layer that only computes selected output columns
    (twin of SelectiveFullyConnectedLayer.cpp, used for large-vocab softmax
    shortlists).  ``sel`` is [batch, k] int32 column ids; TPU-style this is
    a gather of weight columns + a batched matmul — dense, static-shape,
    MXU-friendly."""

    def __init__(self, size: int, act="linear",
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.ops import activations
        self.size = size
        self.act = activations.get(act)
        self.bias = bias

    def forward(self, x, sel=None):
        policy = get_policy()
        in_dim = x.shape[-1]
        w = param("w", (in_dim, self.size), policy.param_dtype,
                  init.paddle_default(fan_in_axis=0))
        b = (param("b", (self.size,), policy.param_dtype, init.zeros)
             if self.bias else None)
        if sel is None:
            y = policy.cast_to_output(
                policy.cast_to_compute(x) @ policy.cast_to_compute(w))
            if b is not None:
                y = y + b.astype(y.dtype)
            return self.act(y)
        w_sel = jnp.take(w, sel, axis=1)             # [in, batch, k]
        w_sel = jnp.moveaxis(w_sel, 1, 0)            # [batch, in, k]
        y = jnp.einsum("bi,bik->bk", policy.cast_to_compute(x),
                       policy.cast_to_compute(w_sel))
        y = policy.cast_to_output(y)
        if b is not None:
            y = y + jnp.take(b, sel)
        return self.act(y)


class DataNorm(Module):
    """Input feature normalization from precomputed dataset statistics
    (twin of DataNormLayer.cpp: z-score / min-max / decimal-scaling)."""

    def __init__(self, mean, std=None, min_=None, max_=None,
                 strategy: str = "z-score", name: Optional[str] = None):
        super().__init__(name)
        enforce_in(strategy, ("z-score", "min-max", "decimal-scaling"))
        self.strategy = strategy
        self.mean = jnp.asarray(mean)
        self.std = None if std is None else jnp.asarray(std)
        self.min = None if min_ is None else jnp.asarray(min_)
        self.max = None if max_ is None else jnp.asarray(max_)

    def forward(self, x):
        if self.strategy == "z-score":
            enforce(self.std is not None, "z-score needs std")
            return (x - self.mean) / (self.std + 1e-8)
        if self.strategy == "min-max":
            enforce(self.min is not None and self.max is not None,
                    "min-max needs min_/max_")
            return (x - self.min) / (self.max - self.min + 1e-8)
        enforce(self.max is not None, "decimal-scaling needs max_")
        digits = jnp.ceil(jnp.log10(jnp.maximum(jnp.abs(self.max), 1e-8)))
        return x / jnp.power(10.0, digits)


class DataNormTable(Module):
    """Stats-table data normalization — the loadable form of
    :class:`DataNorm` (twin of ``gserver/layers/DataNormLayer.cpp:84-112``
    with the 5×size static input parameter of ``config_parser.py``'s
    ``DataNormLayer``, ref ``:2014``).

    The ``stats`` rows are ``[min, 1/(max-min), mean, 1/std, 1/10^j]``
    — computed in preprocessing (:meth:`compute_table`) or imported from
    a reference checkpoint; the default init is the identity transform.
    The table is *static* (the reference enforces ``isStatic()``), so it
    lives in the non-trainable STATE collection like BatchNorm's moving
    statistics — out of reach of optimizers AND the L1/L2 decay
    transforms, which would silently shrink a stop-gradient parameter
    every step.  Import from a reference artifact goes through
    ``checkpoint.apply_v1_state`` with a ``name_map`` (the BN ``.w1``/
    ``.w2`` route).  The input gradient is the same column scale the
    reference's ``backward`` applies (``addColScale`` by the reciprocal
    row).
    """

    def __init__(self, strategy: str = "z-score",
                 name: Optional[str] = None):
        super().__init__(name)
        enforce_in(strategy, ("z-score", "min-max", "decimal-scaling"))
        self.strategy = strategy

    def forward(self, x):
        from paddle_tpu.nn.module import state

        size = x.shape[-1]

        def identity_init(shape, dtype):
            # reciprocal rows (1/range, 1/std, 1/10^j) default to 1,
            # offset rows (min, mean) to 0 -> identity transform.
            return jnp.zeros(shape, dtype).at[jnp.array([1, 3, 4])].set(1.0)

        table = lax.stop_gradient(
            state("stats", (5, size), jnp.float32, identity_init))
        if self.strategy == "z-score":
            return (x - table[2]) * table[3]
        if self.strategy == "min-max":
            return (x - table[0]) * table[1]
        return x * table[4]

    @staticmethod
    def compute_table(data, eps: float = 1e-8):
        """Build the 5×size stats table from a [n, size] dataset array —
        the preprocessing stage the reference delegates to external tools
        (its config docstring: "calculated in the preprocessing stage,
        initialized by --init_model_path")."""
        data = jnp.asarray(data, jnp.float32)
        mn, mx = data.min(axis=0), data.max(axis=0)
        mean, std = data.mean(axis=0), data.std(axis=0)
        j = jnp.ceil(jnp.log10(jnp.maximum(jnp.abs(data).max(axis=0), eps)))
        return jnp.stack([mn, 1.0 / (mx - mn + eps), mean,
                          1.0 / (std + eps), jnp.power(10.0, -j)])


class SumToOneNorm(Module):
    """Row-normalize to sum 1 (twin of SumToOneNormLayer.cpp)."""

    def forward(self, x):
        return x / (jnp.sum(x, axis=-1, keepdims=True) + 1e-12)


class Scaling(Module):
    """Scale each row of y by scalar x (twin of ScalingLayer.cpp)."""

    def forward(self, scale, y):
        return scale.reshape(-1, *([1] * (y.ndim - 1))) * y


class SlopeIntercept(Module):
    """``out = slope * x + intercept`` (twin of SlopeInterceptLayer.cpp)."""

    def __init__(self, slope: float = 1.0, intercept: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.slope = slope
        self.intercept = intercept

    def forward(self, x):
        return self.slope * x + self.intercept


class Addto(Module):
    """Sum of inputs + optional bias, then activation (twin of
    AddtoLayer.cpp)."""

    def __init__(self, act="linear", bias: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.ops import activations
        self.act = activations.get(act)
        self.bias = bias

    def forward(self, *inputs):
        policy = get_policy()
        y = inputs[0]
        for v in inputs[1:]:
            y = y + v
        if self.bias:
            b = param("b", (y.shape[-1],), policy.param_dtype, init.zeros)
            y = y + b.astype(y.dtype)
        return self.act(y)


# ---------------------------------------------------------------------------
# MixedLayer projection/operator family.
# ---------------------------------------------------------------------------

class DotMulProjection(Module):
    """Learned elementwise scale (twin of DotMulProjection)."""

    def forward(self, x):
        policy = get_policy()
        w = param("w", (x.shape[-1],), policy.param_dtype, init.ones)
        return x * w


class ScalingProjection(Module):
    """Single learned scalar multiplier (twin of ScalingProjection)."""

    def forward(self, x):
        policy = get_policy()
        w = param("w", (1,), policy.param_dtype, init.ones)
        return x * w[0]


class IdentityProjection(Module):
    """Pass-through, optionally offset into the output (twin of
    IdentityProjection / IdentityOffsetProjection)."""

    def __init__(self, offset: int = 0, size: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.offset = offset
        self.size = size

    def forward(self, x):
        if self.size is None:
            return x
        pad_right = self.size - self.offset - x.shape[-1]
        enforce(pad_right >= 0, "identity projection overflows output")
        return jnp.pad(x, ((0, 0),) * (x.ndim - 1)
                       + ((self.offset, pad_right),))


class TransposedFullMatrixProjection(Module):
    """x @ W^T (twin of TransposedFullMatrixProjection)."""

    def __init__(self, size: int, name: Optional[str] = None):
        super().__init__(name)
        self.size = size

    def forward(self, x):
        policy = get_policy()
        w = param("w", (self.size, x.shape[-1]), policy.param_dtype,
                  init.paddle_default(fan_in_axis=1))
        return policy.cast_to_output(
            policy.cast_to_compute(x) @ policy.cast_to_compute(w).T)


class FullMatrixProjection(Module):
    """x @ W (twin of FullMatrixProjection — the workhorse of MixedLayer)."""

    def __init__(self, size: int, name: Optional[str] = None):
        super().__init__(name)
        self.size = size

    def forward(self, x):
        policy = get_policy()
        w = param("w", (x.shape[-1], self.size), policy.param_dtype,
                  init.paddle_default())
        return policy.cast_to_output(
            policy.cast_to_compute(x) @ policy.cast_to_compute(w))


class TableProjection(Module):
    """Embedding-table lookup projection (twin of TableProjection):
    input is an id array, output rows of a learned table."""

    def __init__(self, size: int, vocab_size: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.vocab_size = vocab_size

    def forward(self, ids):
        policy = get_policy()
        table = param("w", (self.vocab_size, self.size), policy.param_dtype,
                      init.paddle_default())
        return jnp.take(table, ids.astype(jnp.int32), axis=0, mode="clip")


class SliceProjection(Module):
    """Concatenation of column slices of the input (twin of
    SliceProjection): ``slices`` is a list of (start, end) pairs."""

    def __init__(self, slices: Sequence[Tuple[int, int]],
                 name: Optional[str] = None):
        super().__init__(name)
        self.slices = [(int(s), int(e)) for s, e in slices]

    def forward(self, x):
        return jnp.concatenate([x[..., s:e] for s, e in self.slices],
                               axis=-1)


class ConvProjection(Module):
    """2-D convolution as a Mixed projection (twin of ConvProjection /
    conv_operator): input is NHWC, output flattened to [batch, -1] so it
    can be summed with other projections of the same output size."""

    def __init__(self, channels: int, kernel: IntOrPair, stride: IntOrPair = 1,
                 padding: str = "SAME", flatten: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.conv = Conv2D(channels, kernel, stride, padding, name="conv")
        self.flatten = flatten

    def forward(self, x):
        y = self.conv(x)
        return y.reshape(y.shape[0], -1) if self.flatten else y


class Mixed(Module):
    """Sum of projection outputs + bias + activation (twin of
    MixedLayer.cpp): ``Mixed([proj1, proj2], act="relu")(x1, x2)``."""

    def __init__(self, projections: Sequence[Module], act="linear",
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.ops import activations
        self.projections = list(projections)
        self.act = activations.get(act)
        self.bias = bias

    def forward(self, *inputs):
        policy = get_policy()
        enforce(len(inputs) == len(self.projections),
                "Mixed: %d inputs for %d projections", len(inputs),
                len(self.projections))
        y = None
        for proj, x in zip(self.projections, inputs):
            out = proj(x)
            y = out if y is None else y + out
        if self.bias:
            b = param("b", (y.shape[-1],), policy.param_dtype, init.zeros)
            y = y + b.astype(y.dtype)
        return self.act(y)


# ---------------------------------------------------------------------------
# Remaining registered-layer twins.
# ---------------------------------------------------------------------------

class PReLU(Module):
    """Parametric ReLU with a learned per-channel slope (twin of
    PReluLayer; ``partial_sum`` channel grouping collapses to the
    per-channel case, the only one the demos use)."""

    def __init__(self, init_slope: float = 0.25,
                 name: Optional[str] = None):
        super().__init__(name)
        self.init_slope = init_slope

    def forward(self, x):
        policy = get_policy()
        a = param("a", (x.shape[-1],), policy.param_dtype,
                  init.constant(self.init_slope))
        return jnp.where(x > 0, x, a * x)


class TensorLayer(Module):
    """Bilinear tensor product (twin of TensorLayer):
    ``out[b, k] = x1[b] @ W[k] @ x2[b]`` with ``W: [size, d1, d2]``."""

    def __init__(self, size: int, act="linear", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.ops import activations
        self.size = size
        self.act = activations.get(act)
        self.bias = bias

    def forward(self, x1, x2):
        policy = get_policy()
        w = param("w", (self.size, x1.shape[-1], x2.shape[-1]),
                  policy.param_dtype, init.paddle_default(fan_in_axis=1))
        y = jnp.einsum("bi,kij,bj->bk", policy.cast_to_compute(x1),
                       policy.cast_to_compute(w),
                       policy.cast_to_compute(x2))
        y = policy.cast_to_output(y)
        if self.bias:
            b = param("b", (self.size,), policy.param_dtype, init.zeros)
            y = y + b.astype(y.dtype)
        return self.act(y)


class GatedUnit(Module):
    """Gated linear unit (twin of gated_unit_layer):
    ``act(x W) * sigmoid(x W_g)`` — the GLU of the conv-seq2seq line."""

    def __init__(self, size: int, act="linear", name: Optional[str] = None):
        super().__init__(name)
        from paddle_tpu.nn.layers import Linear
        self.value = Linear(size, act=act, name="value")
        self.gate = Linear(size, act="sigmoid", name="gate")

    def forward(self, x):
        return self.value(x) * self.gate(x)


class ConvShift(Module):
    """Circular correlation of two layers (twin of ConvShiftLayer — the
    NTM attention-shift op): ``out[b, i] = sum_j b[b, j] *
    a[b, (i + j - (N-1)//2) mod M]`` with ``N`` odd and static, so the
    gather indices are compile-time constants."""

    def forward(self, a, b):
        m, n = a.shape[-1], b.shape[-1]
        enforce(n % 2 == 1, "conv_shift filter width must be odd, got %d", n)
        idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :]
               - (n - 1) // 2) % m          # [M, N]
        return jnp.einsum("bmn,bn->bm", a[:, idx], b)


class OutProd(Module):
    """Flattened outer product of two vectors (twin of OuterProdLayer)."""

    def forward(self, x, y):
        out = jnp.einsum("bi,bj->bij", x, y)
        return out.reshape(out.shape[0], -1)


class RowL2Norm(Module):
    """Row-wise L2 normalization (twin of RowL2NormLayer)."""

    def __init__(self, epsilon: float = 1e-6, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def forward(self, x):
        sq = jnp.sum(jnp.square(x), axis=-1, keepdims=True)
        return x * lax.rsqrt(sq + self.epsilon)


class ScaleShift(Module):
    """``w * x + b`` with scalar learned w and b (twin of
    ScaleShiftLayer)."""

    def __init__(self, bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.bias = bias

    def forward(self, x):
        policy = get_policy()
        w = param("w", (1,), policy.param_dtype, init.ones)
        y = x * w[0]
        if self.bias:
            b = param("b", (1,), policy.param_dtype, init.zeros)
            y = y + b[0]
        return y


class MDLstm2D(Module):
    """2-D multi-dimensional LSTM (twin of ``MDLstmLayer.cpp:180``, the
    ``mdlstmemory`` kind — which the reference only ever ran on CPU; its
    GPU path never shipped).  Input is the PRE-PROJECTED grid
    ``[b, H, W, 5*size]`` (the reference requires the input layer to be
    ``(3+D)*size`` wide, gate layout ``[inode, ig, fg_h, fg_w, og]``);
    parameters are the shared recurrent weight ``[size, 5*size]``, the
    local bias, and the ig/fg/og peepholes — the same shapes the
    reference packs into its weight + bias parameters.  The recurrence
    runs as a skewed anti-diagonal wavefront ``lax.scan``
    (``ops/mdlstm.py``) instead of a per-cell walk."""

    def __init__(self, size: int, directions=(True, True),
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.directions = tuple(directions)

    def forward(self, x):
        from paddle_tpu.ops.mdlstm import mdlstm2d

        n = self.size
        enforce(x.shape[-1] == 5 * n,
                "MDLstm2D(size=%d): input must be pre-projected to "
                "5*size=%d channels, got %d", n, 5 * n, x.shape[-1])
        policy = get_policy()
        w_r = param("w", (n, 5 * n), policy.param_dtype,
                    init.paddle_default(fan_in_axis=0))
        bias = param("b", (5 * n,), policy.param_dtype, init.zeros)
        check_ig = param("check_ig", (n,), policy.param_dtype, init.zeros)
        check_fg = param("check_fg", (2, n), policy.param_dtype, init.zeros)
        check_og = param("check_og", (n,), policy.param_dtype, init.zeros)
        out, _ = mdlstm2d(x, w_r, bias, check_ig, check_fg, check_og,
                          directions=self.directions)
        return out
