"""Core layers.

TPU-native twins of the reference layer zoo (``paddle/gserver/layers/*``,
82 REGISTER_LAYER registrations — see SURVEY.md §2.2).  Layers here are thin
:class:`~paddle_tpu.nn.module.Module` wrappers over jnp/lax ops; XLA does the
kernel fusion the reference hand-wrote in ``paddle/cuda``.

Conventions (TPU-first, not reference-translated):

* images are NHWC (XLA's preferred TPU conv layout), conv kernels HWIO —
  the reference's NCHW/``im2col`` path (``paddle/function/GemmConvOp.cpp``)
  is irrelevant on TPU where XLA lowers convs straight onto the MXU;
* matmuls run in the active dtype-policy compute dtype (bf16 on TPU);
* every layer takes ``act=`` by name, mirroring the v1 helper API
  (``trainer_config_helpers/layers.py``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from paddle_tpu.core.dtypes import get_policy
from paddle_tpu.core.errors import enforce, enforce_in
from paddle_tpu.nn import initializers as init
from paddle_tpu.nn.module import Module, param, state, is_training, next_rng_key
from paddle_tpu.ops import activations

IntOrPair = Union[int, Tuple[int, int]]


def _pair(v: IntOrPair) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


class Linear(Module):
    """Fully-connected layer (twin of FullyConnectedLayer.cpp / fc_layer)."""

    def __init__(self, size: int, act="linear", bias: bool = True,
                 w_init=None, b_init=None, name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.act = activations.get(act)
        self.bias = bias
        self.w_init = w_init
        self.b_init = b_init or init.zeros

    def forward(self, x):
        policy = get_policy()
        in_dim = x.shape[-1]
        w_init = self.w_init or init.paddle_default(fan_in_axis=0)
        w = param("w", (in_dim, self.size), policy.param_dtype, w_init)
        # Under MIXED_BF16 this matmul accumulates in bf16 on purpose: the
        # policy boundary is the layer output, and the bf16-tier tolerance
        # is part of the mixed-precision contract (docs/design/analysis.md).
        # tpu-lint: disable=accum-dtype
        y = jnp.matmul(policy.cast_to_compute(x), policy.cast_to_compute(w))
        y = policy.cast_to_output(y)
        if self.bias:
            b = param("b", (self.size,), policy.param_dtype, self.b_init)
            y = y + b.astype(y.dtype)
        return self.act(y)


class Embedding(Module):
    """Embedding lookup (twin of TableProjection / lookup_table op).

    Row-sparse gradients (the reference's ``SparseRowCpuMatrix``) arrive for
    free: ``jnp.take`` differentiates to a scatter-add, which XLA keeps sparse.
    """

    def __init__(self, vocab_size: int, dim: int, w_init=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.vocab_size = vocab_size
        self.dim = dim
        self.w_init = w_init or init.normal(0.01)

    def forward(self, ids):
        policy = get_policy()
        table = param("w", (self.vocab_size, self.dim), policy.param_dtype,
                      self.w_init)
        # mode="clip": out-of-vocab ids clamp to the last row (XLA's
        # native gather semantics) instead of jnp.take's default NaN
        # fill, which silently poisons the whole forward pass.
        # tpu-lint: disable=gather-in-decode — embedding lookup of the carried token IS the decode step; one row per iteration
        return policy.cast_to_output(jnp.take(table, ids, axis=0,
                                              mode="clip"))


class Conv2D(Module):
    """2-D convolution, NHWC/HWIO (twin of ExpandConvLayer / conv2d op).

    XLA lowers this directly to MXU systolic matmuls; no im2col
    (``paddle/function/Im2Col.h``) is needed on TPU.
    """

    def __init__(self, channels: int, kernel: IntOrPair, stride: IntOrPair = 1,
                 padding: Union[str, IntOrPair] = "SAME", act="linear",
                 bias: bool = True, groups: int = 1, dilation: IntOrPair = 1,
                 w_init=None, name: Optional[str] = None):
        super().__init__(name)
        self.channels = channels
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        self.dilation = _pair(dilation)
        self.groups = groups
        if isinstance(padding, str):
            self.padding = padding.upper()
        else:
            p = _pair(padding)
            self.padding = [(p[0], p[0]), (p[1], p[1])]
        self.act = activations.get(act)
        self.bias = bias
        self.w_init = w_init or init.he_normal()

    def forward(self, x):
        policy = get_policy()
        in_ch = x.shape[-1]
        enforce(in_ch % self.groups == 0, "channels %d not divisible by groups",
                in_ch)
        kshape = (*self.kernel, in_ch // self.groups, self.channels)
        w = param("w", kshape, policy.param_dtype, self.w_init)
        y = lax.conv_general_dilated(
            policy.cast_to_compute(x), policy.cast_to_compute(w),
            window_strides=self.stride, padding=self.padding,
            rhs_dilation=self.dilation, feature_group_count=self.groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # Tag for remat policies: "conv_out" saves exactly these tensors
        # and recomputes the cheap elementwise chains in backward (a
        # no-op unless the model runs under nn.remat with that policy).
        y = checkpoint_name(y, "conv_out")
        y = policy.cast_to_output(y)
        if self.bias:
            b = param("b", (self.channels,), policy.param_dtype, init.zeros)
            y = y + b.astype(y.dtype)
        return self.act(y)


class Pool2D(Module):
    """Max/avg pooling (twin of PoolLayer / pool2d op)."""

    def __init__(self, kernel: IntOrPair, stride: Optional[IntOrPair] = None,
                 padding: Union[str, IntOrPair] = "VALID",
                 pool_type: str = "max", name: Optional[str] = None):
        super().__init__(name)
        enforce_in(pool_type, ("max", "avg"))
        self.kernel = _pair(kernel)
        self.stride = _pair(stride) if stride is not None else self.kernel
        self.pool_type = pool_type
        if isinstance(padding, str):
            self.padding = padding.upper()
        else:
            p = _pair(padding)
            self.padding = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))

    def forward(self, x):
        window = (1, *self.kernel, 1)
        strides = (1, *self.stride, 1)
        if self.pool_type == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                     self.padding)
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                   self.padding)
        if isinstance(self.padding, str) and self.padding == "VALID":
            count = self.kernel[0] * self.kernel[1]
            return summed / count
        ones = jnp.ones_like(x)
        count = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                  self.padding)
        return summed / count


class GlobalPool2D(Module):
    """Global spatial pooling over NHWC."""

    def __init__(self, pool_type: str = "avg", name=None):
        super().__init__(name)
        enforce_in(pool_type, ("max", "avg"))
        self.pool_type = pool_type

    def forward(self, x):
        if self.pool_type == "avg":
            return jnp.mean(x, axis=(1, 2))
        return jnp.max(x, axis=(1, 2))


class BatchNorm(Module):
    """Batch normalization (twin of BatchNormalizationLayer /
    CudnnBatchNormLayer — ``gserver/layers/BatchNormBaseLayer.h``).

    Running stats live in the mutable ``state`` collection; training updates
    them with ``moving_average_fraction`` semantics from the reference.
    """

    def __init__(self, momentum: float = 0.9, epsilon: float = 1e-5,
                 act="linear", axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.momentum = momentum
        self.epsilon = epsilon
        self.act = activations.get(act)
        self.axis = axis

    def forward(self, x):
        policy = get_policy()
        dim = x.shape[self.axis]
        reduce_axes = tuple(i for i in range(x.ndim)
                            if i != (self.axis % x.ndim))
        gamma = param("scale", (dim,), policy.param_dtype, init.ones)
        beta = param("bias", (dim,), policy.param_dtype, init.zeros)
        mean_s = state("moving_mean", (dim,), jnp.float32,
                       lambda s, d: jnp.zeros(s, d))
        var_s = state("moving_var", (dim,), jnp.float32,
                      lambda s, d: jnp.ones(s, d))
        shape = [1] * x.ndim
        shape[self.axis % x.ndim] = dim
        if is_training():
            xf = x.astype(jnp.float32)
            # Single-pass SHIFTED variance: both statistics come from ONE
            # read of the conv output (with jnp.var the mean-centered pass
            # forces a second full HBM read of every activation — measured
            # ~8% of the ResNet-50 step).  Shifting by the running mean
            # first (a constant, so still one fused pass) keeps the
            # E[d^2]-E[d]^2 cancellation benign even for large-mean /
            # small-spread channels, where the unshifted form loses all
            # precision in f32; the clamp then only absorbs last-ulp
            # negatives and epsilon dominates harmlessly.
            shift = lax.stop_gradient(mean_s).reshape(shape)
            d = xf - shift
            dmean = jnp.mean(d, axis=reduce_axes)
            mean = dmean + mean_s
            var = jnp.maximum(
                jnp.mean(jnp.square(d), axis=reduce_axes)
                - jnp.square(dmean), 0.0)
            from paddle_tpu.nn.module import set_state
            m = self.momentum
            set_state("moving_mean", m * mean_s + (1 - m) * mean)
            set_state("moving_var", m * var_s + (1 - m) * var)
        else:
            mean, var = mean_s, var_s
        # Statistics stay f32; the normalization itself applies in the
        # activation dtype — under bf16 compute an f32 apply would double
        # the VPU + HBM cost of the hottest elementwise op in conv nets
        # (and its backward).
        inv = (lax.rsqrt(var + self.epsilon)
               * gamma.astype(jnp.float32)).astype(x.dtype)
        y = ((x - mean.astype(x.dtype).reshape(shape))
             * inv.reshape(shape) + beta.astype(x.dtype).reshape(shape))
        return self.act(y)


class LayerNorm(Module):
    """Layer normalization over the last axis."""

    def __init__(self, epsilon: float = 1e-6, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def forward(self, x):
        policy = get_policy()
        dim = x.shape[-1]
        gamma = param("scale", (dim,), policy.param_dtype, init.ones)
        beta = param("bias", (dim,), policy.param_dtype, init.zeros)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.epsilon)
        return (y * gamma + beta).astype(x.dtype)


class Dropout(Module):
    """Inverted dropout (twin of Layer::forwardDropOut, ``Layer.cpp:334``)."""

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = rate

    def forward(self, x):
        if self.rate <= 0.0 or not is_training():
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(next_rng_key(), keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class Maxout(Module):
    """Maxout over channel groups (twin of MaxOutLayer.cpp)."""

    def __init__(self, groups: int, name: Optional[str] = None):
        super().__init__(name)
        self.groups = groups

    def forward(self, x):
        ch = x.shape[-1]
        enforce(ch % self.groups == 0, "maxout channels %% groups != 0")
        new_shape = x.shape[:-1] + (ch // self.groups, self.groups)
        return jnp.max(x.reshape(new_shape), axis=-1)


class CrossChannelNorm(Module):
    """L2 normalization across channels with learned per-channel scale
    (twin of CrossChannelNormLayer / NormLayer in SSD)."""

    def __init__(self, epsilon: float = 1e-10, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def forward(self, x):
        policy = get_policy()
        dim = x.shape[-1]
        scale = param("scale", (dim,), policy.param_dtype, init.ones)
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True)
                        + self.epsilon)
        return x / norm * scale


class Sequential(Module):
    """Chain of callables/modules."""

    def __init__(self, *layers, name: Optional[str] = None):
        super().__init__(name)
        self.layers = layers

    def forward(self, x, *args, **kwargs):
        for layer in self.layers:
            x = layer(x)
        return x
