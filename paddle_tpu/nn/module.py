"""Minimal functional module system.

This is the TPU-native replacement for the reference's ``Layer`` base class
and registry (``paddle/gserver/layers/Layer.h:62``, ``REGISTER_LAYER``
``Layer.h:31``): instead of config-constructed C++ nodes mutating ``Argument``
buffers, a model is a pure Python function that calls :class:`Module` objects;
:func:`transform` turns it into an ``(init, apply)`` pair of pure functions
over an explicit parameter pytree, which is what ``jax.jit``/``pjit``/
``jax.grad`` consume.

Design points:

* **Named parameters.** Every parameter lives at a path
  ``("scope", ..., "name")`` in a nested dict — the twin of the reference's
  ``parameterMap_`` (``NeuralNetwork.cpp:74``) — so checkpoints, sharding
  rules, and per-parameter optimizer attributes can address parameters by
  name, as the reference's ``ParameterConfig`` does.
* **Deterministic auto-naming.** Modules are named ``<class>_<k>`` in call
  order within their parent scope (explicit ``name=`` overrides), so ``init``
  and ``apply`` agree without a registry.  Calling the *same instance* twice
  reuses its scope → weight sharing, the twin of the reference's shared
  ``Weight`` objects.
* **Separate state collection.** Non-trained buffers (batch-norm running
  stats — ``Parameter``'s extra ``ParameterType`` buffers in the reference)
  live in a parallel ``state`` tree; ``apply`` returns ``(out, new_state)``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.errors import enforce
from paddle_tpu.core.rng import KeySeq

Params = Dict[str, Any]  # nested dict of str -> (dict | jax.Array)
State = Dict[str, Any]

_local = threading.local()


def _frames():
    if not hasattr(_local, "frames"):
        _local.frames = []
    return _local.frames


class _Frame:
    def __init__(self, mode: str, params: Params, state: State,
                 rng: Optional[KeySeq], train: bool):
        self.mode = mode  # "init" | "apply"
        self.params = params
        self.state = state
        self.new_state: State = {}
        self.rng = rng
        self.train = train
        self.scope: list[str] = []
        self.counters: Dict[Tuple[str, ...], Dict[str, int]] = {}
        # Keyed by module *object* (identity hash) rather than id(): holding a
        # strong reference prevents CPython id reuse from aliasing the scopes
        # of two short-lived module instances.
        self.module_names: Dict["Module", str] = {}


def current_frame() -> _Frame:
    frames = _frames()
    enforce(frames, "Module/param used outside of transform().init/apply")
    return frames[-1]


def in_transform() -> bool:
    return bool(_frames())


def is_training() -> bool:
    return current_frame().train


def next_rng_key() -> jax.Array:
    frame = current_frame()
    enforce(frame.rng is not None,
            "An RNG key is required (dropout/init) but none was passed")
    return frame.rng.next()


def _tree_get(tree: Dict[str, Any], path: Sequence[str]):
    node: Any = tree
    for p in path:
        if not isinstance(node, dict) or p not in node:
            return None
        node = node[p]
    return node


def _tree_set(tree: Dict[str, Any], path: Sequence[str], value) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def param(name: str, shape: Sequence[int], dtype,
          init: Callable[[jax.Array, Sequence[int], Any], jax.Array]) -> jax.Array:
    """Fetch (apply) or create (init) a trainable parameter at current scope."""
    frame = current_frame()
    path = tuple(frame.scope) + (name,)
    value = _tree_get(frame.params, path)
    if value is None:
        enforce(frame.mode == "init",
                "Unknown parameter %s during apply", "/".join(path))
        value = init(next_rng_key(), tuple(shape), dtype)
        _tree_set(frame.params, path, value)
    return value


def state(name: str, shape: Sequence[int], dtype,
          init: Callable[..., jax.Array]) -> jax.Array:
    """Fetch or create a non-trainable buffer (e.g. BN running stats)."""
    frame = current_frame()
    path = tuple(frame.scope) + (name,)
    value = _tree_get(frame.new_state, path)
    if value is None:
        value = _tree_get(frame.state, path)
    if value is None:
        enforce(frame.mode == "init",
                "Unknown state %s during apply", "/".join(path))
        value = init(tuple(shape), dtype)
    _tree_set(frame.new_state, path, value)
    return value


def set_state(name: str, value: jax.Array) -> None:
    frame = current_frame()
    path = tuple(frame.scope) + (name,)
    _tree_set(frame.new_state, path, value)


AUX_LOSS_KEY = "__aux_loss__"


def add_aux_loss(value) -> None:
    """Record an auxiliary loss (e.g. MoE load-balance) at the current scope.

    Stored in the state tree under ``__aux_loss__``; the Trainer adds
    :func:`collect_aux_losses` of the post-apply state to the main loss.
    """
    set_state(AUX_LOSS_KEY, jnp.asarray(value, jnp.float32))


def collect_aux_losses(state_tree: State):
    """Sum every ``__aux_loss__`` leaf in a state tree (0.0 if none)."""
    total = jnp.zeros((), jnp.float32)
    if not state_tree:
        return total
    stack = [state_tree]
    while stack:
        node = stack.pop()
        for k, v in node.items():
            if isinstance(v, dict):
                stack.append(v)
            elif k == AUX_LOSS_KEY:
                total = total + v
    return total


def _deep_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v


def _clone_dicts(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Copy every dict node (leaves shared) so merges never alias the
    caller's tree."""
    return {k: _clone_dicts(v) if isinstance(v, dict) else v
            for k, v in tree.items()}


def _resolve_remat_policy(policy):
    """String shorthands for common jax.checkpoint policies; None means
    full recompute (save only the boundary), jax's default."""
    if policy is None or not isinstance(policy, str):
        return policy
    import jax.ad_checkpoint as adck
    if policy == "nothing":
        return adck.checkpoint_policies.nothing_saveable
    if policy == "dots":
        return adck.checkpoint_policies.dots_saveable
    if policy == "conv_out":
        return adck.checkpoint_policies.save_only_these_names("conv_out")
    raise ValueError(f"unknown remat policy {policy!r}")


def remat(fn: Callable, *args, policy=None):
    """``jax.checkpoint`` for stateful module calls.

    Plain ``jax.checkpoint`` cannot wrap a module call directly: ``param()``
    reads and ``state`` writes would leak checkpoint tracers into the ambient
    transform frame.  This lifts the frame through the checkpoint boundary —
    params/state/rng enter as explicit operands of a pure function that runs
    ``fn`` under a nested frame (same scope and naming counters, so ``init``
    and the rematerialised ``apply`` agree on parameter names), and state
    writes flow back out as returns.

    The TPU twin of trading FLOPs for HBM that the reference gets from
    keeping only per-frame activations in RecurrentGradientMachine
    (``RecurrentGradientMachine.cpp:293``): activations inside ``fn`` are
    recomputed during backward instead of stored.

    Usage: ``x = nn.remat(block, x, mask)`` instead of ``x = block(x, mask)``.

    ``policy`` is a ``jax.checkpoint`` rematerialization policy (e.g.
    ``jax.checkpoint_policies.save_only_these_names("conv_out")`` to keep
    conv outputs and recompute the cheap elementwise chains in backward —
    the HBM-traffic shape ResNet wants) or one of the string shorthands
    "nothing" / "dots" / "conv_out".
    """
    policy = _resolve_remat_policy(policy)
    if not in_transform():
        return jax.checkpoint(fn, policy=policy)(*args)
    frame = current_frame()
    if frame.mode == "init":
        # Params are being created; no gradient pass happens at init.
        return fn(*args)

    rng_key = frame.rng.next() if frame.rng is not None else None
    scope = list(frame.scope)
    counters_in = {k: dict(v) for k, v in frame.counters.items()}
    names_in = dict(frame.module_names)
    captured: Dict[str, Any] = {}

    def pure(params, st, key, *inner_args):
        inner = _Frame("apply", params, st,
                       KeySeq(key) if key is not None else None,
                       train=frame.train)
        inner.scope = list(scope)
        inner.counters = {k: dict(v) for k, v in counters_in.items()}
        inner.module_names = dict(names_in)
        _frames().append(inner)
        try:
            out = fn(*inner_args)
        finally:
            _frames().pop()
        # Naming side effects are replay-invariant; keep the last trace's.
        captured["counters"] = inner.counters
        captured["module_names"] = inner.module_names
        return out, inner.new_state

    # State written earlier in this apply must be visible inside the
    # checkpointed segment, exactly as in inline execution.  Dict nodes are
    # cloned so the merge cannot mutate the caller's state tree.
    merged_state = _clone_dicts(frame.state)
    _deep_merge(merged_state, _clone_dicts(frame.new_state))
    out, new_state = jax.checkpoint(pure, policy=policy)(
        frame.params, merged_state, rng_key, *args)
    if captured:
        frame.counters = captured["counters"]
        frame.module_names = captured["module_names"]
    _deep_merge(frame.new_state, new_state)
    return out


class Module:
    """Base class for layers.  Subclasses implement ``forward``."""

    def __init__(self, name: Optional[str] = None):
        self._requested_name = name

    def _scope_name(self, frame: _Frame) -> str:
        if self in frame.module_names:
            return frame.module_names[self]
        if self._requested_name is not None:
            name = self._requested_name
        else:
            base = type(self).__name__.lower()
            scope_key = tuple(frame.scope)
            counters = frame.counters.setdefault(scope_key, {})
            idx = counters.get(base, 0)
            counters[base] = idx + 1
            name = f"{base}_{idx}"
        frame.module_names[self] = name
        return name

    def __call__(self, *args, **kwargs):
        return self.scoped("forward", *args, **kwargs)

    def scoped(self, method: str, *args, **kwargs):
        """Invoke a non-``forward`` method under this module's name scope.

        ``__call__`` pushes the module's scope before ``forward``; alternate
        entry points (``generate``, ``decode``...) invoked directly would
        create/look up parameters at the WRONG paths and silently not share
        weights with the trained model.  ``net.scoped("generate", ...)``
        gives them the same scope as training.
        """
        frame = current_frame()
        name = self._scope_name(frame)
        frame.scope.append(name)
        try:
            return getattr(self, method)(*args, **kwargs)
        finally:
            frame.scope.pop()

    def forward(self, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError


class Transformed:
    """``(init, apply)`` pair produced by :func:`transform`."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def init(self, rng, *args, **kwargs) -> Tuple[Params, State]:
        frame = _Frame("init", {}, {}, KeySeq(rng), train=False)
        _frames().append(frame)
        try:
            self._fn(*args, **kwargs)
        finally:
            _frames().pop()
        return frame.params, frame.new_state

    def apply(self, params: Params, state: State, rng, *args,
              train: bool = False, **kwargs):
        frame = _Frame("apply", params or {}, state or {},
                       KeySeq(rng) if rng is not None else None, train=train)
        _frames().append(frame)
        try:
            out = self._fn(*args, **kwargs)
        finally:
            _frames().pop()
        return out, frame.new_state


def transform(fn: Callable) -> Transformed:
    return Transformed(fn)


def flatten_names(params: Params, prefix: str = "") -> Dict[str, jax.Array]:
    """Flatten a nested param tree to {'a/b/c': array} (for printing/saving)."""
    out: Dict[str, jax.Array] = {}
    for k, v in params.items():
        path = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(flatten_names(v, path))
        else:
            out[path] = v
    return out


def unflatten_names(flat: Dict[str, jax.Array]) -> Params:
    tree: Params = {}
    for k, v in flat.items():
        _tree_set(tree, k.split("/"), v)
    return tree


def escape_name(name: str) -> str:
    """Parameter path -> file/tar-member-safe name.  Our names are module
    paths ('fc_0/w'); '/' cannot appear in a file name, so artifact
    writers (Parameters.to_tar, v1 pass dirs) escape with this shared
    convention and loaders invert with :func:`unescape_name`.  '%' is
    escaped first so the mapping is injective: a name containing a
    literal '%2F' round-trips instead of unescaping to a bogus '/'."""
    return name.replace("%", "%25").replace("/", "%2F")


def unescape_name(name: str) -> str:
    return name.replace("%2F", "/").replace("%25", "%")
