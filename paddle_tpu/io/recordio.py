"""Python binding for the native recordio library (ctypes).

Twin of the reference's record streaming path: the Go master partitions
recordio chunks into tasks (``go/master/service.go:106``) and the v2 master
client streams records (``go/master/client.go:119-239`` NextRecord); here a
C++ reader with a prefetch thread feeds Python, and the index block gives
O(1) seek for data-cursor resume (the master's checkpointed cursor).

The .so is built on demand from ``csrc/recordio.cc`` with g++ (no pybind11
in this environment — plain C ABI via ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, Optional

_LIB_PATH = os.path.join(os.path.dirname(__file__), "librecordio.so")
_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    from paddle_tpu.utils.native import load_library
    lib = load_library("recordio.cc", _LIB_PATH)
    lib.recordio_writer_open.restype = ctypes.c_void_p
    lib.recordio_writer_open.argtypes = [ctypes.c_char_p]
    lib.recordio_writer_put.restype = ctypes.c_int
    lib.recordio_writer_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint32]
    lib.recordio_writer_close.restype = ctypes.c_int
    lib.recordio_writer_close.argtypes = [ctypes.c_void_p]
    lib.recordio_reader_open.restype = ctypes.c_void_p
    lib.recordio_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    lib.recordio_reader_count.restype = ctypes.c_int64
    lib.recordio_reader_count.argtypes = [ctypes.c_void_p]
    lib.recordio_reader_next.restype = ctypes.c_int
    lib.recordio_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.recordio_reader_get.restype = ctypes.c_int
    lib.recordio_reader_get.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.recordio_reader_error.restype = ctypes.c_char_p
    lib.recordio_reader_error.argtypes = [ctypes.c_void_p]
    lib.recordio_reader_close.restype = None
    lib.recordio_reader_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class Writer:
    def __init__(self, path: str):
        self._lib = _load()
        self._h = self._lib.recordio_writer_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, data: bytes) -> None:
        rc = self._lib.recordio_writer_put(self._h, data, len(data))
        if rc != 0:
            raise IOError("recordio write failed")

    def close(self) -> None:
        if self._h:
            rc = self._lib.recordio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio close failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Reader:
    """Sequential (prefetching) + random-access record reader."""

    def __init__(self, path: str, prefetch: int = 64,
                 buf_size: int = 1 << 20):
        self._lib = _load()
        self._h = self._lib.recordio_reader_open(path.encode(), prefetch)
        if not self._h:
            raise IOError(f"cannot open recordio file {path}")
        self._buf = ctypes.create_string_buffer(buf_size)

    def __len__(self) -> int:
        return self._lib.recordio_reader_count(self._h)

    def _grow(self, needed: int) -> None:
        self._buf = ctypes.create_string_buffer(needed)

    def __iter__(self) -> Iterator[bytes]:
        length = ctypes.c_uint64()
        while True:
            status = self._lib.recordio_reader_next(
                self._h, self._buf, len(self._buf), ctypes.byref(length))
            if status == 1:
                return
            if status == -1:
                err = self._lib.recordio_reader_error(self._h).decode()
                raise IOError(f"recordio read failed: {err}")
            if status == -2:
                self._grow(length.value)
                continue
            yield self._buf.raw[:length.value]

    def get(self, idx: int) -> bytes:
        length = ctypes.c_uint64()
        status = self._lib.recordio_reader_get(
            self._h, idx, self._buf, len(self._buf), ctypes.byref(length))
        if status == -2:
            self._grow(length.value)
            return self.get(idx)
        if status != 0:
            err = self._lib.recordio_reader_error(self._h).decode()
            raise IOError(f"recordio get failed: {err}")
        return self._buf.raw[:length.value]

    def close(self) -> None:
        if self._h:
            self._lib.recordio_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def reader_creator(path: str, prefetch: int = 64):
    """Reader-combinator-compatible creator over raw record bytes."""
    def reader():
        with Reader(path, prefetch) as r:
            yield from r
    return reader


def num_records(path: str) -> int:
    with Reader(path, prefetch=1) as r:
        return len(r)


def read_range(path: str, start: int, count: int) -> Iterator[bytes]:
    """Stream ``count`` records starting at ``start`` (O(1) index seek) —
    the shard-read primitive the master's task dispatch hands to trainers."""
    with Reader(path, prefetch=1) as r:
        n = len(r)
        for i in range(start, min(start + count, n)):
            yield r.get(i)
