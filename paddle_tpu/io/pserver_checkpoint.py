"""Reader for the reference Go pserver's checkpoint shard files.

The third of the reference's three trained-model artifact formats
(SURVEY hard-part #5; the other two — v1 ``pass-%05d/`` dirs and v2
parameter tars — load via ``training/checkpoint.py`` and ``v2.py``): a
pserver shard persists as a gob-encoded ``[]parameterCheckpoint``
(``go/pserver/service.go:272-305``) with an md5 recorded in etcd
metadata (``checkpointMeta``) — one file per pserver index, each
holding the slice of parameters that shard owned.

``load_shards`` merges any number of shard files back into one
``name -> array`` dict, with optional md5 verification against the
saved meta JSON (the etcd values, if the operator exported them).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from paddle_tpu.core.errors import enforce
from paddle_tpu.io.gob import GobDecoder

# go/pserver/service.go:52-60 — ElementType iota order.
ELEMENT_DTYPES = {
    0: np.int32, 1: np.uint32, 2: np.int64, 3: np.uint64,
    4: np.float32, 5: np.float64,
}


def load_shard(path: str, expect_md5: Optional[str] = None
               ) -> List[Dict[str, Any]]:
    """Decode one shard file into its parameterCheckpoint records
    (``Param`` name/dtype/array, raw ``Config``/``State`` blobs)."""
    with open(path, "rb") as f:
        raw = f.read()
    if expect_md5 is not None:
        got = hashlib.md5(raw).hexdigest()
        enforce(got == expect_md5,
                "pserver shard %s: md5 %s != recorded %s (WrongChecksum)",
                path, got, expect_md5)
    values = GobDecoder(raw).decode()
    enforce(len(values) == 1 and isinstance(values[0], list),
            "pserver shard %s: expected one []parameterCheckpoint, got %d "
            "top-level values", path, len(values))
    out = []
    for rec in values[0]:
        # parameterCheckpoint embeds ParameterWithConfig; gob transmits
        # the embedded struct as a field named by its type.
        pwc = rec.get("ParameterWithConfig", rec)
        param = pwc.get("Param", {})
        # gob omits zero-valued fields: an absent ElementType IS the Go
        # zero value Int32 (iota 0), not a "default" of our choosing.
        etype = param.get("ElementType", 0)
        dtype = ELEMENT_DTYPES.get(etype)
        enforce(dtype is not None,
                "pserver shard %s: unknown ElementType %d", path, etype)
        content = param.get("Content", b"")
        out.append({
            "name": param.get("Name", ""),
            "dtype": np.dtype(dtype),
            "value": np.frombuffer(content, dtype=dtype).copy(),
            "config": pwc.get("Config", b""),
            "state": rec.get("State", b""),
        })
    return out


def load_shards(paths: Iterable[str],
                meta_dir: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Merge pserver shard files into one flat ``name -> vector`` dict
    (the model the trainer fleet sharded across pservers).  Vectors are
    1-D — dims live in the model config, exactly like v1 pass-dir files;
    feed the result to ``training.checkpoint.apply_v1_params``.

    ``meta_dir``: optional directory of ``<shard-file-name>.meta.json``
    files carrying the etcd ``checkpointMeta`` (uuid/path/md5); when
    present, each shard's md5 is verified (the reference's
    ``WrongChecksum`` guard)."""
    merged: Dict[str, np.ndarray] = {}
    for path in paths:
        md5 = None
        if meta_dir is not None:
            # The caller asked for verification: a missing meta file
            # must fail, not silently skip the WrongChecksum guard.
            mp = os.path.join(meta_dir,
                              os.path.basename(path) + ".meta.json")
            enforce(os.path.exists(mp),
                    "pserver shards: meta_dir given but %s is missing",
                    mp)
            with open(mp) as f:
                md5 = json.load(f).get("md5")
        for rec in load_shard(path, expect_md5=md5):
            enforce(rec["name"] not in merged,
                    "pserver shards: parameter %r in two shards",
                    rec["name"])
            merged[rec["name"]] = rec["value"]
    enforce(merged, "pserver shards: no parameters found")
    return merged
