"""Minimal Go ``encoding/gob`` stream codec — enough to read (and, for
tests, write) the reference Go pserver's checkpoint shard files.

The reference persists each pserver shard as a gob-encoded
``[]parameterCheckpoint`` (``go/pserver/service.go:272-305``):

    type Parameter struct { Name string; ElementType int; Content []byte }
    type ParameterWithConfig struct { Param Parameter; Config []byte }
    type parameterCheckpoint struct { ParameterWithConfig; State []byte }

This module implements the documented gob wire format (the
``encoding/gob`` package spec): the uint/int scalar encodings, the
length-prefixed message framing, type-descriptor messages (negative
type ids carrying ``wireType`` values built from the predefined meta
types), and struct/slice/bytes/string value encoding.  The decoder is
GENERIC over transmitted struct descriptors — it reconstructs whatever
schema the stream declares, so renamed or re-ordered fields in a future
reference build still decode.

Validation: scalar encodings are pinned against the byte examples in
the gob specification; the full checkpoint path round-trips through the
encoder here.  No Go toolchain exists in this build environment, so in
addition to the spec's byte vectors the test suite pins a HAND-ASSEMBLED
stream replicating Go's exact emission for ``[]parameterCheckpoint``
(outermost-first descriptors, bottom-up type ids, zero-field omission,
singleton framing — byte provenance documented in
``tests/test_gob_pserver.py``), plus truncated/corrupt streams that must
fail with clean errors.
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.core.errors import enforce

# Predefined type ids (gob spec).
BOOL, INT, UINT, FLOAT, BYTES, STRING, COMPLEX, INTERFACE = range(1, 9)
WIRE_TYPE, ARRAY_TYPE, COMMON_TYPE, SLICE_TYPE, STRUCT_TYPE, FIELD_TYPE = (
    16, 17, 18, 19, 20, 21)
MAP_TYPE = 23
_FIRST_USER_ID = 65


# ---------------------------------------------------------------------------
# Scalar encodings.
# ---------------------------------------------------------------------------

def encode_uint(n: int) -> bytes:
    """Gob uint: <128 one byte; else a count byte (256 - len) then
    big-endian bytes (spec: "254 01 00" hmm — the count byte holds the
    NEGATIVE byte count)."""
    enforce(n >= 0, "encode_uint: negative %d", n)
    if n < 128:
        return bytes([n])
    payload = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([256 - len(payload)]) + payload


def decode_uint(buf: memoryview, i: int) -> Tuple[int, int]:
    enforce(i < len(buf), "gob: truncated stream (uint expected)")
    b = buf[i]
    if b < 128:
        return b, i + 1
    n = 256 - b
    enforce(0 < n <= 8, "gob: bad uint count byte %d", b)
    enforce(i + 1 + n <= len(buf), "gob: truncated %d-byte uint", n)
    return int.from_bytes(bytes(buf[i + 1:i + 1 + n]), "big"), i + 1 + n


def encode_int(v: int) -> bytes:
    u = (~v << 1) | 1 if v < 0 else v << 1
    return encode_uint(u)


def decode_int(buf: memoryview, i: int) -> Tuple[int, int]:
    u, i = decode_uint(buf, i)
    return (~(u >> 1) if u & 1 else u >> 1), i


# ---------------------------------------------------------------------------
# Wire-type model (what type-descriptor messages transmit).
# ---------------------------------------------------------------------------

@dataclass
class FieldT:
    name: str
    type_id: int


@dataclass
class TypeT:
    name: str
    id: int
    kind: str                       # "struct" | "slice" | "array" | "map"
    fields: List[FieldT] = field(default_factory=list)
    elem: int = 0                   # slice/array elem type id
    length: int = 0                 # array length
    key: int = 0                    # map key type id


class GobDecoder:
    """Decode one gob stream (all values must share the stream)."""

    def __init__(self, data: bytes):
        self.buf = memoryview(data)
        self.types: Dict[int, TypeT] = {}

    # -- message framing --
    def _messages(self):
        i = 0
        while i < len(self.buf):
            n, i = decode_uint(self.buf, i)
            enforce(i + n <= len(self.buf), "gob: truncated message")
            yield self.buf[i:i + n]
            i += n

    # -- type descriptors --
    def _decode_common(self, buf, i) -> Tuple[Tuple[str, int], int]:
        name, tid = "", 0
        prev = 0
        while True:
            delta, i = decode_uint(buf, i)
            if delta == 0:
                return (name, tid), i
            prev += delta
            if prev == 1:       # Name string
                ln, i = decode_uint(buf, i)
                enforce(i + ln <= len(buf), "gob: truncated type name")
                name = bytes(buf[i:i + ln]).decode()
                i += ln
            elif prev == 2:     # Id int
                tid, i = decode_int(buf, i)
            else:
                raise ValueError(f"gob commonType: field {prev}")

    def _decode_wire_type(self, buf, i) -> Tuple[TypeT, int]:
        """wireType is a struct whose single set field says which kind."""
        prev = 0
        out: Optional[TypeT] = None
        while True:
            delta, i = decode_uint(buf, i)
            if delta == 0:
                enforce(out is not None, "gob: empty wireType")
                return out, i
            prev += delta
            # wireType fields: 1 ArrayT, 2 SliceT, 3 StructT, 4 MapT
            # (5/6 GobEncoderT/BinaryMarshalerT unsupported here)
            if prev == 1:
                out, i = self._decode_array_type(buf, i)
            elif prev == 2:
                out, i = self._decode_slice_type(buf, i)
            elif prev == 3:
                out, i = self._decode_struct_type(buf, i)
            elif prev == 4:
                out, i = self._decode_map_type(buf, i)
            else:
                raise ValueError(f"gob wireType: field {prev} unsupported")

    def _decode_slice_type(self, buf, i) -> Tuple[TypeT, int]:
        prev = 0
        common: Tuple[str, int] = ("", 0)
        elem = 0
        while True:
            delta, i = decode_uint(buf, i)
            if delta == 0:
                return TypeT(common[0], common[1], "slice", elem=elem), i
            prev += delta
            if prev == 1:       # CommonType
                common, i = self._decode_common(buf, i)
            elif prev == 2:     # Elem typeId
                elem, i = decode_int(buf, i)
            else:
                raise ValueError(f"gob sliceType: field {prev}")

    def _decode_array_type(self, buf, i) -> Tuple[TypeT, int]:
        prev = 0
        common: Tuple[str, int] = ("", 0)
        elem = length = 0
        while True:
            delta, i = decode_uint(buf, i)
            if delta == 0:
                return TypeT(common[0], common[1], "array", elem=elem,
                             length=length), i
            prev += delta
            if prev == 1:
                common, i = self._decode_common(buf, i)
            elif prev == 2:
                elem, i = decode_int(buf, i)
            elif prev == 3:
                length, i = decode_int(buf, i)
            else:
                raise ValueError(f"gob arrayType: field {prev}")

    def _decode_map_type(self, buf, i) -> Tuple[TypeT, int]:
        prev = 0
        common: Tuple[str, int] = ("", 0)
        key = elem = 0
        while True:
            delta, i = decode_uint(buf, i)
            if delta == 0:
                return TypeT(common[0], common[1], "map", key=key,
                             elem=elem), i
            prev += delta
            if prev == 1:
                common, i = self._decode_common(buf, i)
            elif prev == 2:
                key, i = decode_int(buf, i)
            elif prev == 3:
                elem, i = decode_int(buf, i)
            else:
                raise ValueError(f"gob mapType: field {prev}")

    def _decode_struct_type(self, buf, i) -> Tuple[TypeT, int]:
        prev = 0
        common: Tuple[str, int] = ("", 0)
        fields: List[FieldT] = []
        while True:
            delta, i = decode_uint(buf, i)
            if delta == 0:
                return TypeT(common[0], common[1], "struct",
                             fields=fields), i
            prev += delta
            if prev == 1:
                common, i = self._decode_common(buf, i)
            elif prev == 2:     # []fieldType
                count, i = decode_uint(buf, i)
                for _ in range(count):
                    fprev = 0
                    fname, ftid = "", 0
                    while True:
                        fd, i = decode_uint(buf, i)
                        if fd == 0:
                            break
                        fprev += fd
                        if fprev == 1:
                            ln, i = decode_uint(buf, i)
                            enforce(i + ln <= len(buf),
                                    "gob: truncated field name")
                            fname = bytes(buf[i:i + ln]).decode()
                            i += ln
                        elif fprev == 2:
                            ftid, i = decode_int(buf, i)
                        else:
                            raise ValueError("gob fieldType")
                    fields.append(FieldT(fname, ftid))
            else:
                raise ValueError(f"gob structType: field {prev}")

    # -- values --
    def _decode_value(self, buf, i, tid: int):
        if tid == BOOL:
            u, i = decode_uint(buf, i)
            return bool(u), i
        if tid == INT:
            return decode_int(buf, i)
        if tid == UINT:
            return decode_uint(buf, i)
        if tid == FLOAT:
            # floats travel as the float64 bit pattern with bytes
            # reversed (so small-exponent values compress)
            u, i = decode_uint(buf, i)
            import struct as _s
            return _s.unpack("<d", u.to_bytes(8, "big"))[0], i
        if tid in (BYTES, STRING):
            n, i = decode_uint(buf, i)
            enforce(i + n <= len(buf),
                    "gob: %s length %d overruns its message",
                    "bytes" if tid == BYTES else "string", n)
            raw = bytes(buf[i:i + n])
            return (raw if tid == BYTES else raw.decode()), i + n
        t = self.types.get(tid)
        enforce(t is not None, "gob: value of unknown type id %d", tid)
        if t.kind == "struct":
            out: Dict[str, Any] = {}
            prev = -1
            while True:
                delta, i = decode_uint(buf, i)
                if delta == 0:
                    return out, i
                prev += delta
                enforce(prev < len(t.fields),
                        "gob: field %d beyond %s", prev, t.name)
                f = t.fields[prev]
                out[f.name], i = self._decode_value(buf, i, f.type_id)
        if t.kind in ("slice", "array"):
            n, i = decode_uint(buf, i)
            items = []
            for _ in range(n):
                v, i = self._decode_value(buf, i, t.elem)
                items.append(v)
            return items, i
        if t.kind == "map":
            n, i = decode_uint(buf, i)
            m = {}
            for _ in range(n):
                k, i = self._decode_value(buf, i, t.key)
                v, i = self._decode_value(buf, i, t.elem)
                m[k] = v
            return m, i
        raise ValueError(f"gob: kind {t.kind}")

    def decode(self):
        """Decode the stream's top-level values (usually one).  Each
        framed message carries either ONE type descriptor (negative id)
        or one value (positive id)."""
        values = []
        for msg in self._messages():
            i = 0
            tid, i = decode_int(msg, i)
            if tid < 0:
                t, i = self._decode_wire_type(msg, i)
                t.id = -tid
                self.types[-tid] = t
                enforce(i == len(msg),
                        "gob: %d trailing bytes after type descriptor",
                        len(msg) - i)
                continue
            t = self.types.get(tid)
            if t is None or t.kind != "struct":
                # non-struct top level: preceded by a zero "delta" byte
                delta, i = decode_uint(msg, i)
                enforce(delta == 0, "gob: expected 0 before value")
            v, i = self._decode_value(msg, i, tid)
            enforce(i == len(msg),
                    "gob: %d trailing bytes after value (Go's decoder "
                    "rejects extra data too)", len(msg) - i)
            values.append(v)
        return values


# ---------------------------------------------------------------------------
# Encoder — enough to produce streams the decoder (and Go) accept; used
# by tests to synthesize reference-shaped checkpoint files.
# ---------------------------------------------------------------------------

class GobEncoder:
    def __init__(self):
        self.out = io.BytesIO()
        self.next_id = _FIRST_USER_ID

    def _message(self, payload: bytes) -> None:
        self.out.write(encode_uint(len(payload)) + payload)

    def _common(self, name: str, tid: int) -> bytes:
        """CommonType{Name string, Id typeId}.  Go's gob omits
        zero-valued fields, so an UNNAMED type (e.g. the top-level
        ``[]parameterCheckpoint`` slice) skips the Name field and the Id
        arrives with delta 2 — matching Go's emission byte for byte."""
        out = b""
        prev = -1
        if name:
            out += (encode_uint(0 - prev) + encode_uint(len(name))
                    + name.encode())
            prev = 0
        out += encode_uint(1 - prev) + encode_int(tid)
        return out + encode_uint(0)

    def define_struct(self, name: str,
                      fields: List[Tuple[str, int]]) -> int:
        tid = self.next_id
        self.next_id += 1
        body = encode_uint(1) + encode_uint(len(fields))
        for fname, ftid in fields:
            body += (encode_uint(1) + encode_uint(len(fname))
                     + fname.encode() + encode_uint(1) + encode_int(ftid)
                     + encode_uint(0))
        struct_t = (encode_uint(1) + self._common(name, tid)
                    + body + encode_uint(0))
        # wireType with field 3 (StructT) set
        wire = encode_uint(3) + struct_t + encode_uint(0)
        self._message(encode_int(-tid) + wire)
        return tid

    def define_slice(self, name: str, elem: int) -> int:
        tid = self.next_id
        self.next_id += 1
        slice_t = (encode_uint(1) + self._common(name, tid)
                   + encode_uint(1) + encode_int(elem) + encode_uint(0))
        wire = encode_uint(2) + slice_t + encode_uint(0)
        self._message(encode_int(-tid) + wire)
        return tid

    @staticmethod
    def struct_value(fields: List[Tuple[int, bytes]]) -> bytes:
        """fields: (field_number, encoded value) — zero values omitted by
        the caller, exactly as gob omits them."""
        out = b""
        prev = -1
        for num, payload in fields:
            out += encode_uint(num - prev) + payload
            prev = num
        return out + encode_uint(0)

    @staticmethod
    def bytes_value(raw: bytes) -> bytes:
        return encode_uint(len(raw)) + raw

    def top_level(self, tid: int, payload: bytes,
                  is_struct: bool = False) -> None:
        if is_struct:
            self._message(encode_int(tid) + payload)
        else:
            self._message(encode_int(tid) + encode_uint(0) + payload)

    def getvalue(self) -> bytes:
        return self.out.getvalue()
