from paddle_tpu.io import gob, pserver_checkpoint, recordio

__all__ = ["gob", "pserver_checkpoint", "recordio"]
