from paddle_tpu.io import recordio

__all__ = ["recordio"]
