"""Speculative decoding for the paged serving engine.

Decode is latency-bound: every token costs one full serving round trip
(dispatch + kernel launch + host sync) for one token of progress.
Speculative decoding buys multiple tokens per round trip without
changing the output distribution: a cheap DRAFT model proposes ``k``
tokens per slot, the target model scores all ``k + 1`` positions in
ONE batched step over the paged cache (the multi-token verify form —
``paged_chunked_attention``'s per-query causal bounds, the same op
that serves tail prefill), and host-side rejection sampling accepts a
prefix of the proposals.  Rejected tokens roll back by truncating the
slot's block-table cursor (:func:`~paddle_tpu.ops.paged_attention.
paged_rollback`) — on a paged cache, undo is a pointer truncation, not
a copy, which is why the ROADMAP calls this the block table's second
payoff.

The module is the engine-independent core:

* :class:`SpecConfig` — the engine knob (``PagedServingEngine(spec=
  SpecConfig(k=4, draft_layers=1))``).
* :class:`DraftModel` — the proposer protocol: anything exposing a
  ``TransformerConfig`` + params the engine can build its draft
  programs from.  First implementation :class:`TruncatedDraft`: the
  target's own bottom ``N`` layers (plus embeddings / final norm /
  output head), built by PARAMETER SLICING — ``nn.transform``'s apply
  ignores unused param subtrees, so the truncated twin shares the
  target's weights with zero extra memory and zero training.
* :func:`greedy_accept` / :func:`rejection_sample` — the host-side
  accept rules.  Greedy is longest-prefix match against the target's
  argmax chain, which makes the speculative stream BIT-IDENTICAL to
  target-only greedy decode by induction: the correction token after
  the matched prefix is exactly the argmax the direct engine would
  have emitted.  Sampled decode is standard speculative rejection
  sampling [Leviathan et al.; Chen et al.]: accept draft ``d_j`` with
  probability ``min(1, p_j(d_j) / q_j(d_j))``, on the first rejection
  emit a correction from ``normalize(max(p_j - q_j, 0))``, and when
  every draft survives emit a BONUS token from the target's ``k``-th
  distribution — the classical argument gives output marginals exactly
  equal to target-only sampling, for ANY draft (a bad draft costs
  speed, never correctness).  Both ``p`` and ``q`` must be
  ``softmax(restrict(logits / temp))`` with the target's own
  ``_restrict_logits`` masks — the engine builds them from the same
  helper the direct sampler uses, so the corrected distribution is the
  direct engine's distribution to the bit.

The serving integration (draft/verify programs, per-slot accept
windows, the rollback ledger, telemetry) lives in
``paddle_tpu/serving.py``; ``docs/design/serving.md`` works the
correctness argument and the compile contract.
"""

from __future__ import annotations

import dataclasses
from typing import (List, Protocol, Sequence, Tuple,
                    runtime_checkable)

import numpy as np

from paddle_tpu.core.errors import enforce
from paddle_tpu.models.transformer import TransformerConfig

__all__ = ["SpecConfig", "DraftModel", "TruncatedDraft",
           "truncate_lm_params", "greedy_accept", "rejection_sample"]


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Engine-facing speculative-decoding knob.

    ``k``: draft tokens proposed per slot per step — the verify step
    scores ``k + 1`` positions and a step commits between 1 and
    ``k + 1`` tokens.  ``draft_layers``: layers kept by the default
    :class:`TruncatedDraft` when no explicit ``draft=`` model is
    passed.
    """

    k: int = 4
    draft_layers: int = 1

    def __post_init__(self):
        enforce(self.k >= 1, "SpecConfig.k must be >= 1, got %s", self.k)
        enforce(self.draft_layers >= 1,
                "SpecConfig.draft_layers must be >= 1, got %s",
                self.draft_layers)


@runtime_checkable
class DraftModel(Protocol):
    """A proposer the engine can build draft programs from: a
    transformer config (same vocab as the target — the accept rule
    compares distributions over one vocabulary) plus a params pytree
    ``nn.transform``-compatible with that config."""

    @property
    def cfg(self) -> TransformerConfig: ...

    @property
    def params(self): ...


def truncate_lm_params(params, num_layers: int, *, name: str = "lm"):
    """Slice a :class:`TransformerLM` params tree down to its bottom
    ``num_layers`` blocks (keeping embeddings, final norm and the
    output head).  ``nn.transform``'s apply tolerates a params tree
    with exactly the keys the traced program reads, so the returned
    subtree IS the truncated model's params — no copy of the arrays,
    just a smaller dict over the same buffers."""
    sub = params[name]
    kept = {}
    n_blocks = 0
    for key, val in sub.items():
        if key.startswith("block_"):
            if int(key.split("_", 1)[1]) < num_layers:
                kept[key] = val
                n_blocks += 1
        else:
            kept[key] = val
    enforce(n_blocks == num_layers,
            "truncate_lm_params: wanted %s blocks, params hold %s",
            num_layers, n_blocks)
    return {name: kept}


class TruncatedDraft:
    """The zero-training draft: the target's own bottom ``num_layers``
    blocks re-read through the final norm and output head.  Shares the
    target's buffers (parameter slicing, no copies); quality degrades
    gracefully with depth, and ``num_layers == cfg.num_layers`` is the
    self-draft degenerate case (every proposal accepted — the parity
    fixture the tests pin greedy bit-identity with)."""

    def __init__(self, cfg: TransformerConfig, params, num_layers: int,
                 *, name: str = "lm"):
        enforce(1 <= num_layers <= cfg.num_layers,
                "TruncatedDraft: num_layers %s outside [1, %s]",
                num_layers, cfg.num_layers)
        self._cfg = dataclasses.replace(cfg, num_layers=num_layers)
        self._params = truncate_lm_params(params, num_layers, name=name)

    @property
    def cfg(self) -> TransformerConfig:
        return self._cfg

    @property
    def params(self):
        return self._params


# ---------------------------------------------------------- host accept


def greedy_accept(drafts: Sequence[int],
                  greedy: Sequence[int]) -> Tuple[List[int], int]:
    """Greedy accept rule: longest prefix of ``drafts`` matching the
    target's argmax chain ``greedy`` (``greedy[j]`` = target argmax
    after consuming ``drafts[:j]``), then the correction/bonus token
    ``greedy[a]``.  Returns ``(committed_tokens, n_accepted)`` with
    ``committed == greedy[:a + 1]`` — by induction exactly the stream
    target-only greedy decode emits, which is the bit-identity
    contract the tier-1 test pins."""
    enforce(len(greedy) == len(drafts) + 1,
            "greedy_accept: need k+1 target tokens for k drafts "
            "(got %s for %s)", len(greedy), len(drafts))
    a = 0
    while a < len(drafts) and int(drafts[a]) == int(greedy[a]):
        a += 1
    return [int(t) for t in greedy[:a + 1]], a


def rejection_sample(p: np.ndarray, q: np.ndarray,
                     drafts: Sequence[int],
                     rng: np.random.Generator,
                     ) -> Tuple[List[int], int]:
    """Standard speculative rejection sampling for ONE slot.

    ``p``: ``[k + 1, V]`` target distributions (``p[j]`` conditions on
    the committed stream plus ``drafts[:j]``); ``q``: ``[k, V]`` draft
    proposal distributions; ``drafts``: the ``k`` proposed tokens.
    Accept ``drafts[j]`` with probability ``min(1, p[j, d] / q[j, d])``;
    on the first rejection emit a correction sampled from
    ``normalize(max(p[j] - q[j], 0))`` and stop; with every draft
    accepted emit a bonus from ``p[k]``.  Returns
    ``(committed_tokens, n_accepted)`` — between 1 and ``k + 1``
    tokens.

    Correctness (the classical argument): for each position the
    emitted marginal is ``min(p, q) + (1 - beta) * normalize(max(p - q,
    0)) = p`` with ``beta = sum_t min(p(t), q(t))`` — the output
    distribution equals target-only sampling for ANY proposal ``q``,
    so a weak draft costs acceptance rate, never correctness.  The
    seeded distribution-equivalence test pins this empirically."""
    k = len(drafts)
    assert p.shape[0] == k + 1 and (k == 0 or q.shape[0] == k), (
        f"rejection_sample: p {p.shape} / q {getattr(q, 'shape', None)} "
        f"do not cover {k} drafts")
    out: List[int] = []
    for j in range(k):
        d = int(drafts[j])
        pd = float(p[j, d])
        qd = max(float(q[j, d]), 1e-30)
        if rng.random() < min(1.0, pd / qd):
            out.append(d)
            continue
        resid = np.maximum(p[j].astype(np.float64) - q[j], 0.0)
        total = float(resid.sum())
        if total <= 0.0:
            # p == q exactly (or numerics collapsed the residual): the
            # correction distribution is degenerate — fall back to the
            # target distribution itself, which the identity above
            # makes exact in this limit
            resid = np.maximum(p[j].astype(np.float64), 0.0)
            total = float(resid.sum())
        out.append(int(rng.choice(resid.shape[0], p=resid / total)))
        return out, j
    bonus = np.maximum(p[k].astype(np.float64), 0.0)
    total = float(bonus.sum())
    enforce(total > 0.0, "rejection_sample: target bonus distribution "
            "sums to %s — non-finite logits upstream", total)
    out.append(int(rng.choice(bonus.shape[0], p=bonus / total)))
    return out, k
