"""Multi-tenant LoRA adapter hosting: pool, registry, checkpoint.

The device half (``ops/adapters.py``) is a paged pool of per-layer
LoRA factor stacks the unified step gathers from by per-slot adapter
id.  This module is the host half — pure bookkeeping in the shape the
prefix radix cache established:

* :class:`AdapterPool` owns the device :class:`~paddle_tpu.ops.adapters.
  AdapterPoolState` and walks it through the KV pool's ownership ops —
  ``paged_adapter_reserve`` on load (ACQUIRE), ``paged_adapter_rc_add``
  while any engine slot references the adapter (PIN), and
  ``paged_adapter_free`` on evict (RELEASE) — so pool-lint's five
  ownership rules check this allocator through the same op sets that
  guard the KV block pool.
* :class:`AdapterRegistry` maps ``(tenant, adapter)`` keys to pool
  slots with load/unload/pin and LRU eviction of SHARER-FREE entries
  (pins == 0) under pressure; a fully pinned pool raises the typed
  :class:`AdapterPoolFull` instead of evicting live weights.  Its
  :meth:`AdapterRegistry.reconcile` feeds the registry-derived
  expected refcounts to the ``paged_adapter_reconcile`` runtime
  oracle — the adapter twin of ``host_state(reconcile=True)``.
* :func:`save_adapter` / :func:`load_adapter` are the serialized
  artifact format (flat-key ``.npz`` + JSON meta, tmp-then-rename —
  ``training/checkpoint.py``'s discipline): the shape a trained-draft
  style finetune job hands to serving.

The serving engine (``serving.py``) drives resolve -> load-on-miss ->
pin -> decode -> unpin; ``frontend.py`` routes requests by adapter
with per-tenant SLO classes.  ``docs/design/serving.md`` has the full
design.
"""

from __future__ import annotations

import json
import os
import tempfile
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.ops import adapters as aops

__all__ = ["AdapterPool", "AdapterPoolFull", "AdapterRegistry",
           "load_adapter", "save_adapter"]


class AdapterPoolFull(RuntimeError):
    """Every adapter pool slot is pinned by an active request — nothing
    is evictable, so a new adapter cannot load until a request retires.
    Carries ``(pool_slots, pinned)``."""

    def __init__(self, pool_slots: int, pinned: int):
        super().__init__(
            f"adapter pool full: all {pool_slots} slots resident, "
            f"{pinned} pinned, none evictable")
        self.pool_slots = pool_slots
        self.pinned = pinned


class AdapterPool:
    """Device adapter-pool owner: P fixed slots of per-layer LoRA A/B
    stacks plus the refcount vector, mutated only through the
    ``paged_adapter_*`` ownership ops.  Host mirror ``_rc`` shadows the
    device refcounts write-for-write so free-slot search never syncs;
    the reconcile oracle is what proves the mirror honest."""

    def __init__(self, num_layers: int, pool_slots: int, dim: int,
                 rank: int):
        if pool_slots < 1:
            raise ValueError(f"pool_slots must be >= 1, got {pool_slots}")
        if rank < 0:
            raise ValueError(f"adapter rank must be >= 0, got {rank}")
        self.num_layers = int(num_layers)
        self.pool_slots = int(pool_slots)
        self.dim = int(dim)
        self.rank = int(rank)
        self.state = aops.paged_adapter_init(num_layers, pool_slots,
                                             dim, rank)
        self._rc = np.zeros((pool_slots,), np.int64)

    # ------------------------------------------------------- ownership

    def reserve(self) -> int:
        """Claim the lowest free slot (refcount 0 -> 1, factors
        zeroed).  Returns -1 when no slot is free — the caller (the
        registry) decides between eviction and :class:`AdapterPoolFull`."""
        free = np.nonzero(self._rc == 0)[0]
        if free.size == 0:
            return -1
        slot = int(free[0])
        st, ok = aops.paged_adapter_reserve(self.state, slot)
        self.state = st
        if not bool(ok):
            raise AssertionError(
                f"adapter slot {slot}: host mirror said free but device "
                "refcount was live (mirror drift — run reconcile)")
        self._rc[slot] = 1
        return slot

    def load(self, slot: int, a_stack, b_stack, scale: float) -> None:
        """Write one adapter's factors into claimed ``slot`` (shapes
        validated against the pool's static layout)."""
        if len(a_stack) != self.num_layers or len(b_stack) != self.num_layers:
            raise ValueError(
                f"adapter has {len(a_stack)}/{len(b_stack)} A/B layers; "
                f"pool is built for {self.num_layers}")
        for i, (al, bl) in enumerate(zip(a_stack, b_stack)):
            if tuple(np.shape(al)) != (self.dim, self.rank):
                raise ValueError(
                    f"layer {i} A shape {tuple(np.shape(al))} != pool "
                    f"({self.dim}, {self.rank})")
            if tuple(np.shape(bl)) != (self.rank, self.dim):
                raise ValueError(
                    f"layer {i} B shape {tuple(np.shape(bl))} != pool "
                    f"({self.rank}, {self.dim})")
        self.state = aops.paged_adapter_load(self.state, slot, a_stack,
                                             b_stack, scale)

    def pin(self, slot: int) -> None:
        """+1 refcount: an engine slot is decoding with this adapter."""
        st = aops.paged_adapter_rc_add(self.state, slot, 1)
        self.state = st
        self._rc[slot] += 1

    def unpin(self, slot: int) -> None:
        """-1 refcount at request retire."""
        if self._rc[slot] <= 1:
            raise AssertionError(
                f"adapter slot {slot}: unpin below residency "
                f"(rc mirror {int(self._rc[slot])})")
        st = aops.paged_adapter_rc_add(self.state, slot, -1)
        self.state = st
        self._rc[slot] -= 1

    def free(self, slot: int) -> None:
        """Release a SHARER-FREE slot (refcount exactly 1) back to the
        pool — the evict path."""
        if self._rc[slot] != 1:
            raise AssertionError(
                f"adapter slot {slot}: free with rc mirror "
                f"{int(self._rc[slot])} (must be exactly 1 — resident, "
                "no pins)")
        st = aops.paged_adapter_free(self.state, slot)
        self.state = st
        self._rc[slot] = 0

    # ------------------------------------------------------- step feed

    def device_args(self, slot_ids) -> tuple:
        """The step's adapter argument: ``(a_stacks, b_stacks, scales,
        ids)`` — one gatherable pytree, static shapes, so swapping
        adapters never changes the traced signature."""
        ids = jnp_int32(slot_ids)
        return (self.state.a, self.state.b, self.state.scales, ids)

    # ------------------------------------------------------- accounting

    def refcounts(self) -> np.ndarray:
        """Host mirror of per-slot refcounts (no device sync)."""
        return self._rc.copy()

    def free_slots(self) -> int:
        return int(np.count_nonzero(self._rc == 0))

    def pool_bytes(self) -> int:
        return aops.paged_adapter_pool_bytes(
            self.num_layers, self.pool_slots, self.dim, self.rank)

    def reconcile(self, expected_rc: Optional[Sequence[int]] = None
                  ) -> List[str]:
        """Device refcounts vs an expected vector (default: the host
        mirror).  Empty list == consistent."""
        exp = self._rc if expected_rc is None else expected_rc
        return aops.paged_adapter_reconcile(self.state, exp)


def jnp_int32(x):
    """Late-bound jnp cast so importing this module never initializes
    a backend (the registry/checkpoint half is jax-free)."""
    import jax.numpy as jnp
    return jnp.asarray(np.asarray(x, np.int32))


class AdapterRegistry:
    """Host map ``(tenant, adapter) -> pool slot`` with the prefix
    cache's residency discipline: resolve touches LRU, load-on-miss
    reserves (evicting the oldest SHARER-FREE entry under pressure),
    pin/unpin guard active decode rows, unload releases sharer-free
    entries.  ``on_evict(tenant, name, slot)`` lets the engine count
    and trace evictions without the registry importing telemetry."""

    def __init__(self, pool: AdapterPool,
                 on_evict: Optional[Callable[[str, str, int], None]] = None):
        self._pool = pool
        self._on_evict = on_evict
        # insertion/touch order IS the LRU order (prefix-cache idiom)
        self._by_key: "OrderedDict[Tuple[str, str], int]" = OrderedDict()
        self._by_slot: Dict[int, Tuple[str, str]] = {}
        self._pin_count: Dict[int, int] = {}
        self._loads = 0
        self._evictions = 0

    # ------------------------------------------------------- residency

    def resolve(self, name: str, tenant: str = "default") -> Optional[int]:
        """Resident slot for ``(tenant, name)`` or None (a miss);
        touches LRU recency on hit."""
        key = (str(tenant), str(name))
        slot = self._by_key.get(key)
        if slot is not None:
            self._by_key.move_to_end(key)
        return slot

    def load(self, name: str, artifact, tenant: str = "default") -> int:
        """Make ``(tenant, name)`` resident and return its slot.
        ``artifact`` is a :func:`save_adapter` path or an in-memory
        dict ``{"a": [...], "b": [...], "scale": float}``.  Hit: LRU
        touch, no device writes.  Miss: reserve (evicting the LRU
        sharer-free entry if the pool is full) and write the factors;
        raises :class:`AdapterPoolFull` when every slot is pinned."""
        key = (str(tenant), str(name))
        slot = self._by_key.get(key)
        if slot is not None:
            self._by_key.move_to_end(key)
            return slot
        if isinstance(artifact, (str, os.PathLike)):
            artifact = load_adapter(artifact)
        slot = self._pool.reserve()
        if slot < 0:
            self._evict_lru()
            slot = self._pool.reserve()
            if slot < 0:  # pragma: no cover - _evict_lru raised already
                raise AdapterPoolFull(self._pool.pool_slots,
                                      sum(self._pin_count.values()))
        self._pool.load(slot, artifact["a"], artifact["b"],
                        float(artifact.get("scale", 1.0)))
        self._by_key[key] = slot
        self._by_slot[slot] = key
        self._pin_count[slot] = 0
        self._loads += 1
        return slot

    def _evict_lru(self) -> None:
        """Free the least-recently-used SHARER-FREE entry; raise
        :class:`AdapterPoolFull` when every resident adapter is pinned."""
        for key, slot in self._by_key.items():  # oldest first
            if self._pin_count.get(slot, 0) == 0:
                tenant, name = key
                self._pool.free(slot)
                del self._by_key[key]
                del self._by_slot[slot]
                del self._pin_count[slot]
                self._evictions += 1
                if self._on_evict is not None:
                    self._on_evict(tenant, name, slot)
                return
        raise AdapterPoolFull(self._pool.pool_slots,
                              sum(self._pin_count.values()))

    def unload(self, name: str, tenant: str = "default") -> bool:
        """Explicitly release a SHARER-FREE entry.  False when absent;
        raises when pinned (unloading live weights is always a bug)."""
        key = (str(tenant), str(name))
        slot = self._by_key.get(key)
        if slot is None:
            return False
        if self._pin_count.get(slot, 0) > 0:
            raise AssertionError(
                f"adapter {key} slot {slot} has "
                f"{self._pin_count[slot]} pinned rows; retire them "
                "before unload")
        self._pool.free(slot)
        del self._by_key[key]
        del self._by_slot[slot]
        del self._pin_count[slot]
        return True

    # ------------------------------------------------------- pinning

    def pin(self, slot: int) -> None:
        if slot not in self._by_slot:
            raise KeyError(f"adapter slot {slot} is not resident")
        self._pool.pin(slot)
        self._pin_count[slot] += 1

    def unpin(self, slot: int) -> None:
        if self._pin_count.get(slot, 0) <= 0:
            raise AssertionError(
                f"adapter slot {slot}: unpin without matching pin")
        self._pool.unpin(slot)
        self._pin_count[slot] -= 1

    # ------------------------------------------------------- accounting

    def resident(self) -> List[Tuple[str, str, int, int]]:
        """``(tenant, name, slot, pins)`` rows, LRU-oldest first."""
        return [(t, n, s, self._pin_count.get(s, 0))
                for (t, n), s in self._by_key.items()]

    def tenant_of(self, slot: int) -> Optional[str]:
        key = self._by_slot.get(slot)
        return key[0] if key is not None else None

    def rc_expected(self) -> np.ndarray:
        """The registry-derived refcount vector the device pool must
        match: 0 for free slots, ``1 + pins`` for resident ones."""
        exp = np.zeros((self._pool.pool_slots,), np.int64)
        for slot in self._by_slot:
            exp[slot] = 1 + self._pin_count.get(slot, 0)
        return exp

    def reconcile(self) -> List[str]:
        """Run the adapter-pool runtime oracle against the registry's
        OWN residency+pin view (not the pool's mirror — an honest
        cross-check needs independent books)."""
        return self._pool.reconcile(self.rc_expected())

    def stats(self) -> dict:
        return {
            "resident": len(self._by_key),
            "pool_slots": self._pool.pool_slots,
            "pinned_rows": sum(self._pin_count.values()),
            "loads": self._loads,
            "evictions": self._evictions,
        }


# ------------------------------------------------------------ artifact

_META_KEY = "meta_json"


def save_adapter(path: str, a_stack, b_stack, scale: float = 1.0,
                 meta: Optional[dict] = None) -> str:
    """Serialize one LoRA adapter to ``path`` (must end ``.npz``):
    per-layer factors under flat keys ``a/{i}`` / ``b/{i}`` (float32),
    the scalar ``scale``, and a JSON metadata blob — the checkpoint
    module's flat-key + tmp-then-rename discipline, sized for the
    artifact a finetune/trained-draft job emits.  Round-trips exactly
    through :func:`load_adapter`."""
    if not str(path).endswith(".npz"):
        raise ValueError(f"adapter artifact must end in .npz: {path!r}")
    if len(a_stack) != len(b_stack):
        raise ValueError(
            f"A has {len(a_stack)} layers, B has {len(b_stack)}")
    flat = {}
    for i, (al, bl) in enumerate(zip(a_stack, b_stack)):
        flat[f"a/{i}"] = np.asarray(al, np.float32)
        flat[f"b/{i}"] = np.asarray(bl, np.float32)
    flat["scale"] = np.float32(scale)
    info = dict(meta or {})
    info.setdefault("format", "paddle_tpu.lora.v1")
    info["num_layers"] = len(a_stack)
    if len(a_stack):
        info["dim"] = int(np.shape(a_stack[0])[0])
        info["rank"] = int(np.shape(a_stack[0])[1])
    flat[_META_KEY] = np.frombuffer(
        json.dumps(info, sort_keys=True).encode(), np.uint8)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    # atomic-ish write: temp file then rename (checkpoint.py pattern);
    # suffix must end in .npz or np.savez silently writes to <tmp>.npz
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    np.savez(tmp, **flat)
    os.replace(tmp, path)
    return path


def load_adapter(path: str) -> dict:
    """Read a :func:`save_adapter` artifact back into
    ``{"a": [per-layer f32], "b": [...], "scale": float,
    "meta": dict}`` — byte-exact factors (f32 in, f32 out)."""
    with np.load(path) as z:
        layers = sorted(int(k.split("/", 1)[1]) for k in z.files
                        if k.startswith("a/"))
        if layers != list(range(len(layers))):
            raise ValueError(
                f"adapter artifact {path!r} has non-contiguous layer "
                f"keys: {layers}")
        a = [np.asarray(z[f"a/{i}"], np.float32) for i in layers]
        b = [np.asarray(z[f"b/{i}"], np.float32) for i in layers]
        for i in layers:
            if f"b/{i}" not in z.files:
                raise ValueError(
                    f"adapter artifact {path!r} missing b/{i}")
        meta = {}
        if _META_KEY in z.files:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        return {"a": a, "b": b, "scale": float(z["scale"]),
                "meta": meta}
