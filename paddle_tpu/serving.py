"""Paged LM serving: block-pool KV cache + continuous batching.

Two serving forms over the paged cache (``ops/paged_attention.py``):

* :func:`paged_serve_builder` — the paged twin of
  ``models/transformer.py::lm_serve_builder``: ONE jitted program
  (prefill-into-pages + traced-``steps`` ``lax.while_loop`` decode,
  in-jit block allocation each step) that is TOKEN-IDENTICAL to the
  dense serve decoder at equal capacity.  The benchmarking /
  batch-request form.

* :class:`PagedServingEngine` — CONTINUOUS BATCHING: a fixed-shape
  jitted decode step over ``num_slots`` request slots plus a host-side
  admission loop.  A finished request retires immediately (its blocks
  return to the pool) and a queued prompt prefills into the freed slot
  MID-STREAM — no head-of-line blocking on long requests, and the
  decode step never recompiles (the ``compiles == 1`` serving
  contract).  Admission reserves each request's worst case
  (``ceil((prompt + max_new)/block_size)`` blocks) in HOST accounting
  only, so the in-jit allocator can never run dry; physical blocks are
  still mapped on demand, so reported occupancy tracks ACTUAL tokens.

  ``prefix_cache=True`` adds PREFIX SHARING on top: admitted prompts
  register their blocks in a host-side radix tree
  (``paddle_tpu/prefix_cache.py``), a later prompt with the same
  leading tokens maps those physical blocks by refcount increment
  (``paged_share``) and prefills only the unmatched tail, and a write
  into a still-shared block copies first (``paged_cow``) — TTFT on a
  hit collapses to the tail and effective pool capacity multiplies,
  with token streams BIT-IDENTICAL to the sharing-off engine.

Why paged: the dense serving cache costs
``num_slots * max_len * 2 * L * dim * dtype_bytes`` of HBM no matter
what is actually resident — the paged pool costs
``num_blocks * block_size`` tokens total, sized to the EXPECTED load
(p50 lengths), which is what bounds serving batch size on a chip.  The
HBM math is worked in ``docs/design/serving.md``; the design follows
Ragged Paged Attention (PAPERS.md) — the TPU-native paged-KV serving
kernel family.
"""

from __future__ import annotations

import functools
import math
import time
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.adapters import AdapterPoolFull
from paddle_tpu.core.errors import enforce
from paddle_tpu.core.dtypes import get_policy
from paddle_tpu.models.transformer import (TransformerConfig,
                                           TransformerLM,
                                           _restrict_logits,
                                           _sampling_picker)
from paddle_tpu.ops import paged_attention as paged
from paddle_tpu.ops.paged_attention import (dense_hbm_bytes,
                                            paged_hbm_bytes)
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.sharding import paged_cache_shardings
from paddle_tpu.prefix_cache import HostPrefixStore, PrefixCache
from paddle_tpu import speculative as spec_mod
from paddle_tpu.speculative import SpecConfig, TruncatedDraft
from paddle_tpu import telemetry
import paddle_tpu.nn as nn

__all__ = ["paged_serve_builder", "PagedServingEngine", "QueueFull",
           "SpecConfig", "paged_hbm_bytes", "dense_hbm_bytes"]


class QueueFull(RuntimeError):
    """Typed ``submit()`` backpressure signal: the bounded host queue
    already holds ``max_queue`` requests.  A caller that keeps
    submitting into an overloaded engine must hear "not now" as a
    TYPED condition it can route on (shed, retry elsewhere, surface a
    429) — an unbounded deque just converts overload into memory growth
    and unbounded queue-wait, the exact failure mode SLO-aware serving
    exists to remove."""

    def __init__(self, depth: int, limit: int):
        self.depth = int(depth)
        self.limit = int(limit)
        super().__init__(
            f"submit queue full: {depth} queued >= max_queue {limit}")


def _paged_model(cfg: TransformerConfig, attn_fn):
    """Transformed incremental model over paged layer views (the
    ``_cached_lm`` twin for the paged cache form)."""
    if attn_fn is None and cfg.flash:
        from paddle_tpu.ops.attention import flash_attention_fn
        attn_fn = flash_attention_fn
    return nn.transform(
        lambda ids, views, pos_ids, adapters=None:
            TransformerLM(cfg, attn_fn=attn_fn, name="lm")(
                ids, caches=views, position=0, pos_ids=pos_ids,
                adapters=adapters))


def _resolve_mesh(mesh, mesh_axis: str):
    """Normalize the serving ``mesh=`` knob: ``None`` means no
    sharding, an int ``n`` builds a 1-D ``(mesh_axis,)`` mesh over the
    first ``n`` local devices, and a ``jax.sharding.Mesh`` passes
    through (it must carry ``mesh_axis``)."""
    if mesh is None:
        return None
    if isinstance(mesh, (int, np.integer)):
        n = int(mesh)
        enforce(n >= 1, "serving mesh=%s: need at least one device", n)
        enforce(n <= len(jax.devices()),
                "serving mesh=%s devices requested, only %s present",
                n, len(jax.devices()))
        mesh = make_mesh((n,), (mesh_axis,), jax.devices()[:n])
    enforce(mesh_axis in mesh.shape,
            "serving mesh is missing axis %r (mesh axes: %s)",
            mesh_axis, tuple(mesh.shape))
    return mesh


def _mesh_shards(mesh, mesh_axis: str) -> int:
    return 1 if mesh is None else int(mesh.shape[mesh_axis])


def paged_serve_builder(cfg: TransformerConfig, attn_fn=None,
                        block_size: int = 16,
                        max_blocks_per_slot: Optional[int] = None,
                        num_blocks: Optional[int] = None,
                        decode_kernel=None, draft=None,
                        kv_dtype=None, mesh=None,
                        mesh_axis: str = "mp"):
    """Serving-shaped PAGED decode: ``lm_serve_builder``'s contract
    (traced ``steps``, one compiled program per prompt bucket, eos
    early exit, PAD past each row's end) over the block-pool cache.

    Returns ``serve(params, prompt_ids, steps, temperature=0.0,
    rng=None, eos_id=None, top_k=None, top_p=None, prompt_lens=None)
    -> [b, tp + max_new]`` with ``max_new = min(cfg.max_len,
    max_blocks_per_slot * block_size) - tp``.  Token streams are
    IDENTICAL to ``lm_serve_builder`` at equal steps (same
    ``_sampling_picker``, same rng-split order; masked block-table
    positions carry exactly-zero attention weight, so the paged gather
    cannot perturb the numerics — pinned by the tier-1 parity test).

    RAGGED batches differ from the dense decoder's convention: prompts
    are LEFT-aligned (row r's tokens in columns ``[0, len_r)``, pad on
    the RIGHT) with ``prompt_lens`` [b] — the natural paged layout,
    where each row's pages hold exactly its real tokens.  Each row
    decodes as if batched alone.

    ``num_blocks`` sizes the global pool (default: the dense-equivalent
    ``b * max_blocks_per_slot``); undersize it to serve more rows than
    dense HBM would allow — the host wrapper rejects a pool that cannot
    hold the request's worst case (actual prompt lengths + ``steps``),
    and a traced-``steps`` overflow poisons the output with ``-1``
    (a fixed-shape program cannot raise).

    ``decode_kernel`` selects the decode-attention implementation (the
    tri-state ``paged.resolve_decode_kernel`` knob, resolved ONCE here
    at build time and pinned for the program's lifetime): ``None`` =
    auto (Pallas kernel on TPU, XLA gather form elsewhere), ``True`` =
    force the kernel (interpret mode off-TPU — the parity-test path),
    ``False`` = force the gather form.  The resolved bool is exposed as
    ``serve.decode_kernel`` for telemetry rows; either way the program
    still compiles exactly once per bucket.

    ``draft`` builds the DRAFT TWIN of the target from the same
    machinery (the speculative-decoding proposer —
    ``paddle_tpu/speculative.py``): an int ``N`` returns a serve whose
    program runs the target's bottom ``N`` layers (``serve(params,
    ...)`` still takes the FULL target params; they are sliced by
    :func:`~paddle_tpu.speculative.truncate_lm_params` per call — no
    copies), a :class:`~paddle_tpu.speculative.DraftModel` returns a
    serve over its config (pass its own params).  Either way the
    truncated config is exposed as ``serve.draft_cfg`` — how
    benchmarks time the proposer in isolation and how custom drafts
    reuse the paged program machinery.  The FULL speculative pipeline
    (draft + batched verify + rollback) is the engine's
    ``spec=SpecConfig(...)`` knob.

    ``mesh`` shards the K/V block pools along their head axis over a
    ``mesh_axis`` mesh axis (an int ``n`` builds the 1-D mesh; a
    ``jax.sharding.Mesh`` is used as-is).  Params and every
    bookkeeping leaf (block tables, lengths, refcounts) stay
    REPLICATED; attention and append run per-head-shard under
    ``shard_map``, and the ONLY collective in the decode body is the
    all-gather that recombines the attention output — so sharded
    greedy streams are BIT-IDENTICAL to the single-device program
    (``docs/design/serving.md`` "multi-chip serving").
    """
    dslice = None
    if draft is not None:
        import dataclasses as _dc
        from paddle_tpu.speculative import truncate_lm_params
        if isinstance(draft, (int, np.integer)):
            enforce(1 <= int(draft) <= cfg.num_layers,
                    "paged_serve_builder: draft=%s layers outside "
                    "[1, %s]", draft, cfg.num_layers)
            cfg = _dc.replace(cfg, num_layers=int(draft))
            dslice = functools.partial(truncate_lm_params,
                                       num_layers=int(draft))
        else:
            enforce(draft.cfg.vocab_size == cfg.vocab_size,
                    "paged_serve_builder: draft vocab %s != target "
                    "vocab %s", draft.cfg.vocab_size, cfg.vocab_size)
            cfg = draft.cfg
    model = _paged_model(cfg, attn_fn)
    hd = cfg.dim // cfg.num_heads
    bs = block_size
    maxb = (max_blocks_per_slot if max_blocks_per_slot
            else -(-cfg.max_len // bs))
    cap = min(cfg.max_len, maxb * bs)     # per-slot token capacity
    # kv_dtype=None inherits the numerics policy; "int8" switches the
    # pool to quantized pages + per-block scales (token streams then
    # hold to a divergence BOUND vs the policy-dtype pool, not
    # bit-identity — tests/test_quantized_kv.py pins it)
    kv_dt = jnp.dtype(kv_dtype if kv_dtype is not None
                      else get_policy().compute_dtype)
    mesh = _resolve_mesh(mesh, mesh_axis)
    shards = _mesh_shards(mesh, mesh_axis)
    enforce(cfg.num_heads % shards == 0,
            "paged_serve_builder: num_heads %s not divisible by mesh "
            "axis %r size %s", cfg.num_heads, mesh_axis, shards)
    # the kernel runs PER SHARD inside shard_map, on the local head
    # slice — resolve viability against what each device actually sees
    use_kernel = paged.resolve_decode_kernel(
        decode_kernel, block_size=bs,
        num_heads=cfg.num_heads // shards,
        head_dim=hd, kv_dtype=kv_dt)

    @functools.partial(jax.jit, static_argnums=(5, 6, 7))
    def _pserve(params, prompt_ids, steps, temperature=0.0, rng=None,
                eos_id=None, top_k=None, top_p=None, prompt_lens=None):
        # The scopes pin dispatch AT TRACE TIME — prefill calls (t>1
        # queries) take the XLA form regardless; the per-step t=1
        # attention inside the while_loop body takes the kernel iff
        # use_kernel resolved True at build.  The mesh scope reroutes
        # every paged append/attend through its head-sharded shard_map
        # form (a no-op when mesh is None).
        with paged.decode_kernel_scope(use_kernel), \
                paged.paged_mesh_scope(mesh, mesh_axis):
            return _pserve_impl(params, prompt_ids, steps, temperature,
                                rng, eos_id, top_k, top_p, prompt_lens)

    def _pserve_impl(params, prompt_ids, steps, temperature, rng,
                     eos_id, top_k, top_p, prompt_lens):
        b, tp = prompt_ids.shape
        max_new = cap - tp
        assert max_new >= 1, (
            f"prompt {tp} leaves no room to decode in capacity {cap}")
        assert eos_id is None or 0 <= eos_id < cfg.vocab_size, (
            f"eos_id {eos_id} outside vocab {cfg.vocab_size} — a "
            "mismatched id would silently never terminate")
        assert top_k is None or 1 <= top_k <= cfg.vocab_size
        assert top_p is None or 0.0 < top_p <= 1.0
        nb = num_blocks if num_blocks else b * maxb
        cache = paged.paged_init(cfg.num_layers, b, maxb, nb, bs,
                                 cfg.num_heads, hd, kv_dt)
        if mesh is not None:
            # pin the pool layout once, up front: the while_loop carry
            # then holds the head-sharded placement stable instead of
            # letting GSPMD re-derive (and possibly gather) it per step
            cache = jax.lax.with_sharding_constraint(
                cache, paged_cache_shardings(cache, mesh, mesh_axis))
        rng_key = jax.random.key(0) if rng is None else rng
        temp = jnp.asarray(temperature, jnp.float32)
        steps = jnp.clip(jnp.asarray(steps, jnp.int32), 1, max_new)
        pad = jnp.asarray(eos_id if eos_id is not None else 0,
                          prompt_ids.dtype)
        pick = _sampling_picker(cfg, temp, prompt_ids.dtype, eos_id,
                                top_k, top_p)
        if prompt_lens is None:
            lens = jnp.full((b,), tp, jnp.int32)
        else:
            lens = jnp.clip(jnp.asarray(prompt_lens, jnp.int32), 1, tp)

        # prefill-into-pages: reserve each row's prompt blocks, write
        # k/v through the layer views, read the LAST REAL token's
        # logits (column lens-1; pad columns are masked dead weight)
        cache, ok = paged.paged_reserve(cache, lens)
        views = paged.layer_views(cache, jnp.arange(b), lens)
        pos_ids = jnp.broadcast_to(jnp.arange(tp)[None, :], (b, tp))
        (logits, views), _ = model.apply(params, {}, None, prompt_ids,
                                         views, pos_ids)
        cache = paged.paged_advance(paged.merge_views(cache, views),
                                    lens)
        last = jnp.take_along_axis(
            logits, (lens - 1)[:, None, None], axis=1)[:, 0]
        k0, rng_key = jax.random.split(rng_key)
        tok, done = pick(last, k0, jnp.zeros((b,), bool))
        buf = jnp.full((b, max_new), pad, prompt_ids.dtype)
        buf = buf.at[:, 0].set(tok)
        oom = ~ok

        def cond(carry):
            _, _, _, done, _, _, i = carry
            live = i < steps
            if eos_id is not None:
                live = live & ~jnp.all(done)
            return live

        def body(carry):
            cache, tok, key, done, buf, oom, i = carry
            active = (~done).astype(jnp.int32)
            cache, ok = paged.paged_reserve(cache, active)
            views = paged.layer_views(cache, jnp.arange(b), active)
            step_pos = cache.lengths[:, None]            # [b, 1]
            (lg, views), _ = model.apply(params, {}, None, tok[:, None],
                                         views, step_pos)
            cache = paged.paged_advance(paged.merge_views(cache, views),
                                        active)
            key, sub = jax.random.split(key)
            nxt, done = pick(lg[:, -1], sub, done)
            buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, i))
            return (cache, nxt, key, done, buf, oom | ~ok, i + 1)

        (_, _, _, _, buf, oom, _) = jax.lax.while_loop(
            cond, body, (cache, tok, rng_key, done, buf, oom,
                         jnp.asarray(1, jnp.int32)))
        # a fixed-shape program cannot raise: pool exhaustion poisons
        # the whole output LOUDLY (-1 is out of every vocab)
        buf = jnp.where(oom, jnp.asarray(-1, buf.dtype), buf)
        return jnp.concatenate([prompt_ids, buf], axis=1)

    def serve(params, prompt_ids, steps, temperature=0.0, rng=None,
              eos_id=None, top_k=None, top_p=None, prompt_lens=None):
        if dslice is not None:
            params = dslice(params)       # target params -> draft twin
        b, tp = prompt_ids.shape
        max_new = cap - tp
        if isinstance(steps, (int, np.integer)):
            assert 1 <= steps <= max_new, (
                f"paged serve: steps {int(steps)} outside [1, {max_new}]"
                f" (prompt {tp} in capacity {cap}) — the result would "
                "silently truncate")
        t_arr = np.asarray(temperature) if not hasattr(
            temperature, "aval") else temperature
        if getattr(t_arr, "ndim", 0) >= 1:
            assert t_arr.ndim == 1 and t_arr.shape[0] == b, (
                f"paged serve: temperature must be a scalar or "
                f"[batch={b}] vector, got shape {tuple(t_arr.shape)}")
        lens_arr = np.full((b,), tp, np.int64)
        if prompt_lens is not None:
            la = np.asarray(prompt_lens)
            if la.dtype.kind in "iu":            # host-concrete
                assert la.min() >= 1 and la.max() <= tp, (
                    f"paged serve: prompt_lens outside [1, {tp}] — pads "
                    "would be decoded as prompt tokens")
                lens_arr = la
            prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
        if num_blocks and isinstance(steps, (int, np.integer)):
            worst = int(sum(-(-(int(n) + int(steps)) // bs)
                            for n in lens_arr))
            assert worst <= num_blocks, (
                f"paged serve: pool of {num_blocks} blocks cannot hold "
                f"the worst case {worst} (prompts + {int(steps)} steps "
                f"at block_size {bs}) — the in-jit allocator would "
                "poison the output")
        return _pserve(params, prompt_ids,
                       jnp.asarray(steps, jnp.int32), temperature, rng,
                       eos_id, top_k, top_p, prompt_lens)

    serve._cache_size = _pserve._cache_size   # the no-retrace proof hook
    serve._jit = _pserve   # the lintable program (analysis/entrypoints.py)
    # sharding contract for the linter's mesh recipes (shard-check):
    # positional arg 1 (prompt_ids) is batch-major — shard it on a
    # data axis, replicate the rest.  Declared HERE, by the owner of
    # the calling convention, so entrypoints.py cannot drift from it.
    serve._lint_batch_args = (1,)
    serve.block_size = bs
    serve.max_blocks_per_slot = maxb
    serve.decode_kernel = use_kernel   # resolved choice, for bench rows
    serve.kv_dtype = kv_dt             # resolved pool dtype, ditto
    serve.draft_cfg = cfg if draft is not None else None
    serve.mesh = mesh                  # resolved Mesh (None = 1 device)
    serve.mesh_axis = mesh_axis
    return serve


def kv_parity_probe(cfg: TransformerConfig, params, prompts, *,
                    steps: int = 8, kv_dtype="int8",
                    block_size: int = 16, attn_fn=None,
                    decode_kernel=False, prompt_lens=None) -> float:
    """Measured max-logit divergence of a quantized paged pool against
    the policy-dtype reference pool: prefill ``prompts`` into BOTH
    pools, then drive ``steps`` greedy decode steps feeding the
    quantized pool the REFERENCE's token stream (so the number
    isolates pool quantization error — trajectories cannot fork and
    turn one flipped argmax into unbounded drift).  Returns
    ``max_t max_i |logit_q[t, i] - logit_ref[t, i]|`` over the prefill
    last-token logits and every decode step, as a host float.

    This is the parity CONTRACT's measuring stick (docs/design/
    serving.md): int8 pools promise a divergence bound, not
    bit-exactness.  Feed the result to
    :meth:`PagedServingEngine.note_kv_divergence` to surface it in
    telemetry, or to a ``bench_row`` (``benchmark/lm_decode.py
    --kv-dtype``).  ``decode_kernel`` is the usual tri-state; default
    ``False`` keeps the probe on the XLA form (cheap on CPU CI) —
    pass ``True`` to probe the kernel-interpret path."""
    prompts = jnp.asarray(prompts, jnp.int32)
    b, tp = prompts.shape
    enforce(steps >= 1 and tp + steps <= cfg.max_len,
            "kv_parity_probe: prompt %s + steps %s exceeds max_len %s",
            tp, steps, cfg.max_len)
    model = _paged_model(cfg, attn_fn)
    hd = cfg.dim // cfg.num_heads
    bs = block_size
    maxb = -(-(tp + steps) // bs)
    nb = b * maxb
    lens_j = (jnp.full((b,), tp, jnp.int32) if prompt_lens is None
              else jnp.clip(jnp.asarray(prompt_lens, jnp.int32), 1, tp))
    kv_dt = jnp.dtype(kv_dtype)
    use_kernel = paged.resolve_decode_kernel(
        decode_kernel, block_size=bs, num_heads=cfg.num_heads,
        head_dim=hd, kv_dtype=kv_dt)

    def prefill(cache):
        cache, _ = paged.paged_reserve(cache, lens_j)
        views = paged.layer_views(cache, jnp.arange(b), lens_j)
        pos = jnp.broadcast_to(jnp.arange(tp)[None, :], (b, tp))
        with paged.decode_kernel_scope(use_kernel):
            (lg, views), _ = model.apply(params, {}, None, prompts,
                                         views, pos)
        cache = paged.paged_advance(paged.merge_views(cache, views),
                                    lens_j)
        last = jnp.take_along_axis(
            lg, (lens_j - 1)[:, None, None], axis=1)[:, 0]
        return cache, last.astype(jnp.float32)

    def step(cache, tok):
        act = jnp.ones((b,), jnp.int32)
        cache, _ = paged.paged_reserve(cache, act)
        views = paged.layer_views(cache, jnp.arange(b), act)
        with paged.decode_kernel_scope(use_kernel):
            (lg, views), _ = model.apply(params, {}, None, tok[:, None],
                                         views, cache.lengths[:, None])
        cache = paged.paged_advance(paged.merge_views(cache, views),
                                    act)
        return cache, lg[:, -1].astype(jnp.float32)

    def make(dt):
        return paged.paged_init(cfg.num_layers, b, maxb, nb, bs,
                                cfg.num_heads, hd, dt)

    ref_c, last_r = prefill(make(get_policy().compute_dtype))
    q_c, last_q = prefill(make(kv_dt))
    div = jnp.max(jnp.abs(last_q - last_r))
    tok = jnp.argmax(last_r, axis=-1).astype(jnp.int32)
    for _ in range(int(steps)):
        ref_c, lr = step(ref_c, tok)
        q_c, lq = step(q_c, tok)      # same tokens: no trajectory fork
        div = jnp.maximum(div, jnp.max(jnp.abs(lq - lr)))
        tok = jnp.argmax(lr, axis=-1).astype(jnp.int32)
    return float(div)


class _Request:
    __slots__ = ("rid", "prompt", "max_new", "temperature", "tokens",
                 "blocks_reserved", "submitted_at", "first_token_at",
                 "prefix_hit_tokens", "prefix_nodes", "handoff",
                 "adapter", "tenant", "adapter_slot")

    def __init__(self, rid, prompt, max_new, temperature, blocks,
                 handoff=None, adapter=None, tenant=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.tokens = []                  # generated ids (host ints)
        self.blocks_reserved = blocks
        self.submitted_at = time.perf_counter()
        self.first_token_at = None        # set when prefill emits tok0
        self.prefix_hit_tokens = 0        # prompt tokens NOT prefilled
        self.prefix_nodes = ()            # registry nodes this rid shares
        self.handoff = handoff            # imported-KV payload or None
        self.adapter = adapter            # adapter name or None (base)
        self.tenant = tenant              # tenant id or None (default)
        self.adapter_slot = -1            # resolved pool slot at admit


class _HandoffHit:
    """Stand-in for a prefix-cache match on the handoff admission path
    (:meth:`PagedServingEngine._admit`): the prompt's KV arrives as an
    imported payload rather than from the registry, so there are no
    matched nodes — registration still runs so LATER identical prompts
    hit locally."""
    nodes = ()
    block_ids = ()
    shared_len = 0


class PagedServingEngine:
    """Continuous-batching LM server over the paged KV cache.

    ``num_slots`` fixes the decode step's batch shape — ONE compile
    serves the engine's whole lifetime (``compile_counts()['decode']``
    pins it).  ``submit()`` queues requests; ``run()`` drives the
    decode/retire/admit loop until everything finishes and returns
    ``{rid: np.ndarray(generated ids)}``.  Greedy decode is
    token-identical to ``lm_generate_builder`` per request (the decode
    math is exact — see ``ops/paged_attention.py``), so mixed-length
    continuous batching costs nothing in output quality.

    ``prompt_buckets`` are the prefill pad widths (one prefill compile
    per bucket actually used); ``eos_id``/``top_k``/``top_p`` are
    engine-static (a serving process fixes its tokenizer and sampler).
    ``decode_kernel`` picks the decode-attention implementation (the
    same tri-state knob as ``paged_serve_builder``: None = Pallas
    kernel on TPU / XLA gather elsewhere, True forces the kernel —
    interpret mode off-TPU, the CI path — False forces the gather
    form); the resolved bool lands in ``self.decode_kernel`` and the
    ``compiles == {'step': 1}`` pin holds either way.

    ``prefix_cache=True`` turns on PREFIX SHARING: every admitted
    prompt's blocks register in a host-side radix tree over
    block-size token chunks (``paddle_tpu/prefix_cache.py``) and stay
    PINNED (one refcount) past their donor's retirement; a later
    prompt with the same leading tokens maps the matched blocks into
    its slot by refcount increment (``paged_share`` — no prefill over
    the shared tokens) and runs the model only over the unmatched
    tail (``paged_chunked_attention``).  Appends into a block other
    readers still hold copy-on-write first (``paged_cow``), so token
    streams stay BIT-IDENTICAL to the sharing-off engine — pinned by
    ``tests/test_prefix_cache.py`` on both decode-attention paths.
    Admission accounting reserves one extra block per request for the
    COW copy, and pool pressure evicts LRU sharer-free registry
    leaves before rejecting.  The decode step gains the (cond-gated)
    COW transform but still compiles exactly once; with the flag off
    (default) the traced programs are unchanged.

    ``spec=SpecConfig(k=...)`` turns on SPECULATIVE DECODING
    (``paddle_tpu/speculative.py``): a draft model (``draft=`` — any
    :class:`~paddle_tpu.speculative.DraftModel`; default the target's
    own bottom ``spec.draft_layers`` layers via
    :class:`~paddle_tpu.speculative.TruncatedDraft`) proposes up to
    ``k`` tokens per slot from its OWN paged cache, the target scores
    all ``k + 1`` positions in ONE batched verify step
    (``paged_chunked_attention`` — the multi-token form with per-query
    causal bounds), host-side accept/reject commits a prefix
    (greedy = longest-prefix match, BIT-IDENTICAL to the spec-off
    engine; sampled = rejection sampling with the target's own
    restricted/tempered distributions, distribution-identical), and
    the rejected suffix ROLLS BACK by truncating the slot's
    block-table cursor (``paged_rollback`` — a pointer truncation that
    respects refcounts, so prefix sharing composes).  Per-slot verify
    windows shrink near ``max_new`` so transient cache lengths never
    exceed the admission reservation.

    ``unified_step=True`` (the default) serves plain decode, chunked
    tail prefill, and the speculative verify window through ONE
    compiled ragged step program (``compile_counts()['step']``): each
    row carries its own query-window width (``qlens``) against its
    committed base, and the ragged Pallas paged-attention kernel (or
    its XLA twin) masks per-query causal bounds, so the compile set is
    ``{'step': 1, 'prefill': 1}`` — plus ``{'draft': 1,
    'draft_prefill': 1}`` with speculation — regardless of prompt
    widths, batch mix, or verify windows.  Prefill pads to the single
    ``max(prompt_buckets)`` width instead of compiling per bucket.
    ``unified_step=False`` keeps the legacy multi-program engine
    (separate decode/prefill/tail/verify programs; with speculation
    the compile contract is ``{'decode': 1, 'verify': 1, 'draft': 1}``
    plus one prefill compile per bucket used) — retained as the
    bit-identity baseline the unified step is pinned against.

    The engine is deeply instrumented through ``paddle_tpu.telemetry``
    (``metrics=`` takes a :class:`~paddle_tpu.telemetry.MetricsRegistry`;
    default: the process-wide one): queue-wait / TTFT /
    time-per-output-token / step-time histograms, admission-reject and
    retire counters, per-step occupancy gauges, and compile events via
    the CompileWatcher — all strictly on the host side of the jitted
    step (catalog: ``docs/design/telemetry.md``).

    ``tracer=`` additionally records the PER-REQUEST lifecycle
    (submit → queue → prefill → per-step tokens → retire, one trace
    track per slot plus the ``host`` admission track) into a
    :class:`~paddle_tpu.telemetry.Tracer` ring buffer — exportable as
    Chrome trace JSON and readable by ``paddle_tpu telemetry trace``.
    ``flight_recorder=`` (a path) arms the crash dump: if ``step()`` or
    ``run()`` raises, the last ``flight_window_s`` seconds of events
    plus the engine's host state (:meth:`host_state`: slots, queue,
    pool accounting, compile counts) are written there before the
    exception propagates.  Arming the flight recorder without an
    explicit tracer creates one internally.

    ``max_queue`` bounds the host submit queue: ``submit()`` past the
    bound raises the typed :class:`QueueFull` (counted in
    ``serving_submit_rejects_total{reason="queue_full"}``) instead of
    growing the deque without limit — backpressure the caller can route
    on.  Default ``None`` keeps the historical unbounded behavior.

    ``faults=`` attaches a fault-injection scope
    (``paddle_tpu.testing.faults`` — anything with ``fire(point)``).
    The engine fires the named points ``attach`` / ``admit`` /
    ``prefill`` / ``decode_step`` / ``retire`` at the matching spots in
    its HOST loop, strictly outside the jitted programs, so an armed
    injector changes no traced bytes (the ``paged-engine-decode-faults``
    lint entrypoint pins it).  ``None`` (the default) costs one
    attribute check per point.
    """

    def __init__(self, cfg: TransformerConfig, params, *,
                 num_slots: int, num_blocks: Optional[int] = None,
                 block_size: int = 16,
                 max_blocks_per_slot: Optional[int] = None,
                 prompt_buckets=(64,), eos_id: Optional[int] = None,
                 top_k=None, top_p=None, attn_fn=None, seed: int = 0,
                 metrics=None, tracer=None,
                 flight_recorder: Optional[str] = None,
                 flight_window_s: float = 30.0, decode_kernel=None,
                 prefix_cache: bool = False,
                 max_queue: Optional[int] = None, faults=None,
                 spec: Optional[SpecConfig] = None, draft=None,
                 unified_step: bool = True, kv_dtype=None,
                 kv_pool_bytes: Optional[int] = None, mesh=None,
                 mesh_axis: str = "mp",
                 prefix_host_bytes: Optional[int] = None,
                 adapters: Optional[int] = None,
                 adapter_rank: int = 8, adapter_source=None):
        self.cfg = cfg
        self.params = params
        self.S = num_slots
        self.bs = block_size
        hd = cfg.dim // cfg.num_heads
        # Mesh sharding: the K/V block pools (and int8 scales) shard
        # along their HEAD axis over `mesh_axis`; params + every
        # bookkeeping leaf stay replicated, so the allocator and the
        # whole host admission loop run unchanged and the only
        # collective in the decode body is the attention-output
        # all-gather (ops/paged_attention.py paged_mesh_scope).
        mesh = _resolve_mesh(mesh, mesh_axis)
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        shards = _mesh_shards(mesh, mesh_axis)
        self.shards = shards
        enforce(cfg.num_heads % shards == 0,
                "engine mesh: num_heads %s not divisible by mesh axis "
                "%r size %s", cfg.num_heads, mesh_axis, shards)
        # KV-pool dtype: None inherits the numerics policy's compute
        # dtype (the pre-quantization behavior, byte-identical pytree);
        # "int8" stores quantized block pools + per-block-per-head f32
        # scales (ops/paged_attention.py — the capacity knob).
        self.kv_dtype = jnp.dtype(kv_dtype if kv_dtype is not None
                                  else get_policy().compute_dtype)
        #: real PER-SHARD HBM bytes ONE pool block costs across all
        #: layers (K+V pages plus, when quantized, their scale rows) —
        #: each chip holds num_heads/shards of every block, so this is
        #: the unit the admission ledger and the PER-CHIP kv_pool_bytes
        #: budget are denominated in (single device: shards=1, total)
        self.block_bytes = paged.paged_pool_bytes(
            1, num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=hd, block_size=block_size, kv_dtype=self.kv_dtype,
            shards=shards)
        enforce((num_blocks is None) != (kv_pool_bytes is None),
                "engine pool sizing: pass exactly one of num_blocks "
                "(block count) or kv_pool_bytes (PER-CHIP byte budget; "
                "blocks = budget // per-shard block_bytes), got "
                "num_blocks=%s kv_pool_bytes=%s", num_blocks,
                kv_pool_bytes)
        if num_blocks is None:
            # byte-budget sizing: the SAME per-chip budget admits more
            # blocks (so more resident requests) under a narrower
            # kv_dtype OR across more head shards — the int8 and
            # multi-chip capacity wins, from real bytes-per-block
            num_blocks = int(kv_pool_bytes) // self.block_bytes
            enforce(num_blocks >= 1,
                    "kv_pool_bytes=%s cannot hold even one block "
                    "(%s bytes/shard at kv_dtype=%s over %s shard(s))",
                    kv_pool_bytes, self.block_bytes,
                    self.kv_dtype.name, shards)
        self.nb = num_blocks
        self.maxb = (max_blocks_per_slot if max_blocks_per_slot
                     else -(-cfg.max_len // block_size))
        self.cap = min(cfg.max_len, self.maxb * block_size)
        self.buckets = tuple(sorted(prompt_buckets))
        self.eos_id = eos_id
        enforce(self.nb >= 1 and self.S >= 1, "engine needs a pool and "
                "at least one slot")
        enforce(max_queue is None or max_queue >= 1,
                "max_queue must be None (unbounded) or >= 1, got %s",
                max_queue)
        self.max_queue = max_queue
        self._faults = faults
        if self._faults is not None:
            self._faults.fire("attach")
        model = _paged_model(cfg, attn_fn)
        S = self.S
        # Decode-attention implementation, resolved once for the
        # engine's lifetime (same tri-state knob as paged_serve_builder;
        # None = kernel on TPU, True forces it in interpret mode off-TPU
        # for the parity/CI path, False forces the XLA gather form).
        # under the mesh the kernel runs PER SHARD inside shard_map, on
        # the local head slice — resolve against what a device sees
        self.decode_kernel = paged.resolve_decode_kernel(
            decode_kernel, block_size=block_size,
            num_heads=cfg.num_heads // shards, head_dim=hd,
            kv_dtype=self.kv_dtype)
        use_kernel = self.decode_kernel
        sharing = bool(prefix_cache)
        self.prefix_enabled = sharing
        # Host spill tier: a byte-budgeted pinned-host store under the
        # radix registry.  Pool-pressure eviction then DEMOTES
        # sharer-free prefix nodes (pages serialized host-side) instead
        # of destroying them, and a radix hit on a spilled node
        # restores its blocks before the tail prefill — effective
        # prefix capacity extends past HBM into host RAM.
        enforce(prefix_host_bytes is None or sharing,
                "prefix_host_bytes requires prefix_cache=True")
        enforce(prefix_host_bytes is None or int(prefix_host_bytes) >= 1,
                "prefix_host_bytes must be >= 1, got %s",
                prefix_host_bytes)
        self._host_store = (HostPrefixStore(int(prefix_host_bytes))
                            if sharing and prefix_host_bytes else None)
        # Multi-tenant LoRA: ``adapters=P`` attaches a P-slot pooled
        # adapter buffer (paddle_tpu/adapters.py) whose per-layer A/B
        # stacks ride the unified step as ONE extra pytree argument —
        # static shapes, so loading/evicting adapters never retraces
        # and ``compiles == {'step': 1, 'prefill': 1}`` holds with any
        # number of distinct adapters resident in a batch.  Rows with
        # no adapter (slot id -1) pass the delta's where-select
        # verbatim: bit-identical to an adapter-free engine.
        # ``adapter_source(tenant, name)`` supplies a save_adapter path
        # or factor dict on a registry miss (the load-from-host path
        # the miss-latency histogram times).
        enforce(adapters is None or int(adapters) >= 1,
                "adapters must be None (off) or >= 1 pool slots, "
                "got %s", adapters)
        enforce(adapters is None or int(adapter_rank) >= 0,
                "adapter_rank must be >= 0, got %s", adapter_rank)
        enforce(adapter_source is None or adapters is not None,
                "adapter_source requires adapters=N")
        enforce(adapters is None or bool(unified_step),
                "adapters need the unified step (the gathered-delta "
                "path is only traced there): unified_step=True")
        # A cached prefix's KV at layers >= 1 embeds the deltas of
        # whatever adapter computed it — sharing those blocks with a
        # request running a DIFFERENT adapter would replay the wrong
        # tenant's activations, so the two features are mutually
        # exclusive until the registry keys by adapter.
        enforce(adapters is None or not sharing,
                "adapters + prefix_cache: cached prefix KV embeds the "
                "computing adapter's deltas and cannot be shared "
                "across adapters — build with prefix_cache=False")
        self._apool = None
        self._adapters = None
        self._adapter_source = adapter_source
        self.adapter_rank = int(adapter_rank) if adapters else None
        if adapters is not None:
            from paddle_tpu.adapters import AdapterPool, AdapterRegistry
            self._apool = AdapterPool(cfg.num_layers, int(adapters),
                                      cfg.dim, int(adapter_rank))
            self._adapters = AdapterRegistry(
                self._apool, on_evict=self._note_adapter_evict)
            #: per-engine-slot adapter pool-slot ids (-1 = no adapter)
            #: — the host mirror the step's gather ids are built from
            self._adapter_slots = np.full((S,), -1, np.int32)

        def _pin(c):
            # every traced fn returns its cache through this: the
            # donated-in and returned-out pool layouts must agree (the
            # step's output IS the next step's input), so pin the
            # head-sharded placement on the way out rather than let
            # GSPMD re-derive it per program
            if mesh is None:
                return c
            return jax.lax.with_sharding_constraint(
                c, paged_cache_shardings(c, mesh, mesh_axis))

        def decode_fn(params, cache, tok, active, temps, done, key):
            # the scopes pin decode-attention dispatch at trace time;
            # the fallback observer fires (once per compile, host-side)
            # when a kernel-selected program takes the XLA form anyway,
            # feeding serving_kernel_fallback_total{reason=...}
            with paged.decode_kernel_scope(use_kernel), \
                    paged.kernel_fallback_scope(
                        self._note_kernel_fallback), \
                    paged.paged_mesh_scope(mesh, mesh_axis):
                act = active.astype(jnp.int32)
                if sharing:
                    # un-share each appending slot's cursor block
                    # before the write: a freshly registered/shared
                    # tail block must not mutate under its other
                    # readers.  Statically gated — with prefix_cache
                    # off the traced program is unchanged — and the
                    # copy itself is cond-gated, so the common
                    # no-divergence step skips the traffic.
                    cache, cok = paged.paged_cow(cache, act)
                cache, ok = paged.paged_reserve(cache, act)
                views = paged.layer_views(cache, jnp.arange(S), act)
                (lg, views), _ = model.apply(params, {}, None,
                                             tok[:, None], views,
                                             cache.lengths[:, None])
                cache = paged.paged_advance(
                    paged.merge_views(cache, views), act)
                pick = _sampling_picker(cfg, temps, jnp.int32, eos_id,
                                        top_k, top_p)
                nxt, done = pick(lg[:, -1], key, done)
                if sharing:
                    ok = ok & cok
                return _pin(cache), nxt, done, ok

        def prefill_fn(params, cache, slot, prompt, plen, temp, key):
            # same scope for symmetry; t>1 queries take the XLA form
            with paged.decode_kernel_scope(use_kernel), \
                    paged.paged_mesh_scope(mesh, mesh_axis):
                want = jnp.zeros((S,), jnp.int32).at[slot].set(plen)
                cache, ok = paged.paged_reserve(cache, want)
                views = paged.layer_views(cache, slot[None], plen[None])
                w = prompt.shape[1]
                pos_ids = jnp.arange(w)[None, :]
                (lg, views), _ = model.apply(params, {}, None, prompt,
                                             views, pos_ids)
                cache = paged.paged_advance(
                    paged.merge_views(cache, views), want)
                last = jax.lax.dynamic_index_in_dim(lg[0], plen - 1,
                                                    axis=0,
                                                    keepdims=False)
                pick = _sampling_picker(cfg,
                                        jnp.asarray(temp, jnp.float32),
                                        jnp.int32, eos_id, top_k, top_p)
                tok0, done0 = pick(last[None], key,
                                   jnp.zeros((1,), bool))
                return _pin(cache), tok0[0], done0[0], ok

        def prefill_tail_fn(params, cache, slot, tail, tlen, temp, key):
            # TAIL prefill after a prefix-cache hit: ``paged_share``
            # already mapped the matched blocks and set the slot's
            # length to the shared token count, so only the unmatched
            # ``tlen`` tokens run through the model — each attending
            # the resident prefix plus the earlier tail tokens via the
            # chunked view.  COW first: a matched partial block is
            # shared mid-block and the tail appends into it.
            with paged.decode_kernel_scope(use_kernel), \
                    paged.paged_mesh_scope(mesh, mesh_axis):
                want = jnp.zeros((S,), jnp.int32).at[slot].set(tlen)
                cache, cok = paged.paged_cow(cache, want)
                cache, ok = paged.paged_reserve(cache, want)
                off = cache.lengths[slot]
                views = paged.chunked_layer_views(cache, slot[None],
                                                  tlen[None])
                w = tail.shape[1]
                pos_ids = (off + jnp.arange(w))[None, :]
                (lg, views), _ = model.apply(params, {}, None, tail,
                                             views, pos_ids)
                cache = paged.paged_advance(
                    paged.merge_views(cache, views), want)
                last = jax.lax.dynamic_index_in_dim(lg[0], tlen - 1,
                                                    axis=0,
                                                    keepdims=False)
                pick = _sampling_picker(cfg,
                                        jnp.asarray(temp, jnp.float32),
                                        jnp.int32, eos_id, top_k, top_p)
                tok0, done0 = pick(last[None], key,
                                   jnp.zeros((1,), bool))
                return _pin(cache), tok0[0], done0[0], ok & cok

        # Speculation config resolves FIRST: the unified step's static
        # window width is k+1 with a draft attached (verify windows),
        # 1 without (plain decode).
        self.spec = spec
        self.spec_k = None
        self.draft = None
        dmodel = None
        if spec is not None:
            enforce(isinstance(spec, SpecConfig),
                    "spec must be a SpecConfig, got %r", type(spec))
            if draft is None:
                draft = TruncatedDraft(cfg, params, spec.draft_layers)
            enforce(draft.cfg.vocab_size == cfg.vocab_size,
                    "draft vocab %s != target vocab %s — the accept "
                    "rule compares distributions over one vocabulary",
                    draft.cfg.vocab_size, cfg.vocab_size)
            enforce(draft.cfg.num_heads % shards == 0,
                    "engine mesh: draft num_heads %s not divisible by "
                    "mesh axis %r size %s (the draft pool shards the "
                    "same way as the target's)", draft.cfg.num_heads,
                    mesh_axis, shards)
            self.draft = draft
            self._draft_params = draft.params
            k = int(spec.k)
            self.spec_k = k
            dmodel = _paged_model(draft.cfg, attn_fn)
        restrict = _restrict_logits(cfg, top_k, top_p)
        V = cfg.vocab_size
        arange_s = jnp.arange(S)
        self._unified = bool(unified_step)
        #: static query-window width of the unified step program
        self.step_width = 1 if spec is None else self.spec_k + 1
        #: the ONE ragged-prefill pad width (replaces per-bucket
        #: prefill compiles in unified mode)
        self._prefill_width = max(self.buckets)

        def step_fn(params, cache, toks, qlens, temps, done, key,
                    ad=None):
            # THE unified ragged step: every live slot appends and
            # scores ``qlens[s]`` fresh tokens (0 = idle this call)
            # through ONE compiled program — a plain-decode row is a
            # width-1 window, a speculative verify row a 1+drafts
            # window, all served by the ragged paged-attention kernel
            # (per-query causal bounds against the per-row committed
            # base).  Outputs: the sampled/greedy next token at each
            # row's last real window column (the decode contract), the
            # per-column argmax (greedy accept), and — with a draft
            # attached — the restricted/tempered per-column target
            # distributions rejection sampling consumes.  Idle and pad
            # lanes compute don't-care values the host never reads.
            # ``ad`` (adapter engines only): the pooled-LoRA argument
            # ``(a_stacks, b_stacks, scales, ids[S])`` — each row's
            # low-rank delta gathers by its pool-slot id inside the
            # model (f32 accum, id=-1 rows select through verbatim);
            # ``None`` traces the byte-identical adapter-free program.
            W = self.step_width
            with paged.decode_kernel_scope(use_kernel), \
                    paged.kernel_fallback_scope(
                        self._note_kernel_fallback), \
                    paged.kernel_dispatch_scope(
                        self._note_kernel_dispatch), \
                    paged.paged_mesh_scope(mesh, mesh_axis):
                if sharing:
                    # un-share each appending slot's cursor block
                    # before the write (cond-gated in-graph COW)
                    cache, cok = paged.paged_cow(cache, qlens)
                cache, ok = paged.paged_reserve(cache, qlens)
                views = paged.chunked_layer_views(cache, arange_s,
                                                  qlens)
                pos_ids = (cache.lengths[:, None]
                           + jnp.arange(W)[None, :])
                (lg, views), _ = model.apply(params, {}, None, toks,
                                             views, pos_ids, ad)
                cache = paged.paged_advance(
                    paged.merge_views(cache, views), qlens)
                lf = lg.astype(jnp.float32)               # [S, W, V]
                greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
                last = jnp.take_along_axis(
                    lg, jnp.maximum(qlens - 1, 0)[:, None, None],
                    axis=1)[:, 0]                         # [S, V]
                pick = _sampling_picker(cfg, temps, jnp.int32, eos_id,
                                        top_k, top_p)
                nxt, done = pick(last, key, done)
                if sharing:
                    ok = ok & cok
                if spec is not None:
                    tcol = jnp.maximum(temps, 1e-6)[:, None, None]
                    probs = jax.nn.softmax(restrict(
                        (lf / tcol).reshape(S * W, V)),
                        axis=-1).reshape(S, W, V)
                    return _pin(cache), nxt, done, greedy, probs, ok
                return _pin(cache), nxt, done, greedy, ok

        def prefill_ragged_fn(params, cache, slot, toks, tlen, temp,
                              key, ad=None):
            # ONE ragged prefill program for fresh prompts AND
            # prefix-hit tails: append ``tlen`` tokens to ``slot`` at
            # its current committed base (0 for a fresh slot,
            # shared_len after paged_share) and score them through the
            # chunked view — the per-query causal bound makes the
            # fresh-prompt case (base 0) and the tail case one shape,
            # so the per-bucket prefill/tail compiles collapse to one.
            with paged.decode_kernel_scope(use_kernel), \
                    paged.kernel_fallback_scope(
                        self._note_kernel_fallback), \
                    paged.kernel_dispatch_scope(
                        self._note_kernel_dispatch), \
                    paged.paged_mesh_scope(mesh, mesh_axis):
                want = jnp.zeros((S,), jnp.int32).at[slot].set(tlen)
                if sharing:
                    cache, cok = paged.paged_cow(cache, want)
                cache, ok = paged.paged_reserve(cache, want)
                off = cache.lengths[slot]
                views = paged.chunked_layer_views(cache, slot[None],
                                                  tlen[None])
                w = toks.shape[1]
                pos_ids = (off + jnp.arange(w))[None, :]
                if ad is not None:
                    # prefill runs ONE slot: gather that row's id from
                    # the [S] vector in-graph so the program stays
                    # slot-agnostic (one compile for every slot)
                    ad = (ad[0], ad[1], ad[2], ad[3][slot][None])
                (lg, views), _ = model.apply(params, {}, None, toks,
                                             views, pos_ids, ad)
                cache = paged.paged_advance(
                    paged.merge_views(cache, views), want)
                last = jax.lax.dynamic_index_in_dim(lg[0], tlen - 1,
                                                    axis=0,
                                                    keepdims=False)
                pick = _sampling_picker(cfg,
                                        jnp.asarray(temp, jnp.float32),
                                        jnp.int32, eos_id, top_k, top_p)
                tok0, done0 = pick(last[None], key,
                                   jnp.zeros((1,), bool))
                if sharing:
                    ok = ok & cok
                return _pin(cache), tok0[0], done0[0], ok

        # The cache (pool + block tables) is DEAD the moment each step
        # returns its successor — donate it so XLA updates the pool
        # in place instead of holding two copies of the engine's
        # biggest buffer live across every decode step (the
        # donation-audit lint rule's canonical case; CPU ignores
        # donation, TPU honors it).
        self._free = jax.jit(paged.paged_free, donate_argnums=(0,))
        if self._unified:
            self._step = jax.jit(step_fn, donate_argnums=(1,))
            self._prefill = jax.jit(prefill_ragged_fn,
                                    donate_argnums=(1,))
            watched = dict(step=self._step, prefill=self._prefill)
        else:
            self._decode = jax.jit(decode_fn, donate_argnums=(1,))
            self._prefill = jax.jit(prefill_fn, donate_argnums=(1,))
            watched = dict(decode=self._decode, prefill=self._prefill)
        # shard-check contract: decode_fn/step_fn args 2..5 (tok[s],
        # active/qlens, temps, done) are slot-major [S]-leading
        # vectors — the lint mesh recipe shards them on the data axis;
        # params stay replicated.  The paged pool's HEAD-axis sharding
        # is the mesh= knob above; the sharded paged-engine-step-*
        # recipes pin its layout via paged_cache_shardings instead.
        self._decode_slot_args = (2, 3, 4, 5)
        # share/rc_add are tiny refcount/table host transforms used by
        # BOTH prefix sharing and the disaggregated KV handoff import
        # (paddle_tpu/cluster): always built, but only registered with
        # the compile watcher under sharing — the historical
        # compile-count contracts name 'share' only in sharing mode,
        # and the handoff's share is the same sub-millisecond table op.
        self._share = jax.jit(paged.paged_share, donate_argnums=(0,))
        self._rc_add = jax.jit(paged.paged_rc_add, donate_argnums=(0,))
        if sharing:
            # prefix-sharing host transforms.  Legacy mode additionally
            # keeps the per-tail-width prefill program (one compile per
            # TAIL pad width used); unified mode serves tails through
            # the single ragged prefill program.
            if not self._unified:
                self._prefill_tail = jax.jit(prefill_tail_fn,
                                             donate_argnums=(1,))
                watched["prefill_tail"] = self._prefill_tail
            watched["share"] = self._share
        if spec is not None:

            def _propose(lg_row, temps, sub):
                # the draft's proposal rule mirrors _sampling_picker
                # exactly (greedy from RAW f32 argmax, sampling from
                # the restricted/tempered distribution) and returns q
                # itself — rejection sampling needs the proposal
                # distribution, not just the token
                lf = lg_row.astype(jnp.float32)           # [S, V]
                greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
                scaled = restrict(
                    lf / jnp.maximum(temps, 1e-6)[:, None])
                sampled = jax.random.categorical(
                    sub, scaled, axis=-1).astype(jnp.int32)
                tok = jnp.where(temps > 0, sampled, greedy)
                return tok, jax.nn.softmax(scaled, axis=-1)

            def draft_fn(dparams, dcache, pend, pend_len, temps, key):
                # ONE program per spec step: a chunked catch-up append
                # of the 1-2 pending committed tokens (committed to the
                # stream last step but not yet in the draft cache)
                # yields proposal d_1, then k-1 unrolled t=1 decode
                # steps propose the rest.  The t=1 steps take the
                # Pallas kernel when resolved; the t=2 catch-up is
                # chunked, and the observer records its typed fallback.
                with paged.decode_kernel_scope(use_kernel), \
                        paged.kernel_fallback_scope(
                            self._note_kernel_fallback), \
                        paged.paged_mesh_scope(mesh, mesh_axis):
                    keys = jax.random.split(key, k)
                    dcache, ok = paged.paged_reserve(dcache, pend_len)
                    views = paged.chunked_layer_views(dcache, arange_s,
                                                      pend_len)
                    pos_ids = (dcache.lengths[:, None]
                               + jnp.arange(2)[None, :])
                    (lg, views), _ = dmodel.apply(dparams, {}, None,
                                                  pend, views, pos_ids)
                    dcache = paged.paged_advance(
                        paged.merge_views(dcache, views), pend_len)
                    last = jnp.take_along_axis(
                        lg, jnp.maximum(pend_len - 1, 0)[:, None, None],
                        axis=1)[:, 0]
                    tok, q = _propose(last, temps, keys[0])
                    drafts, qs = [tok], [q]
                    for i in range(1, k):
                        stp = (pend_len > 0).astype(jnp.int32)
                        dcache, ok_i = paged.paged_reserve(dcache, stp)
                        views = paged.layer_views(dcache, arange_s, stp)
                        (lg, views), _ = dmodel.apply(
                            dparams, {}, None, tok[:, None], views,
                            dcache.lengths[:, None])
                        dcache = paged.paged_advance(
                            paged.merge_views(dcache, views), stp)
                        ok = ok & ok_i
                        tok, q = _propose(lg[:, -1], temps, keys[i])
                        drafts.append(tok)
                        qs.append(q)
                    return (_pin(dcache), jnp.stack(drafts, axis=1),
                            jnp.stack(qs, axis=1), ok)

            def verify_fn(params, cache, toks, valid, temps):
                # the multi-token VERIFY: one chunked-attention step
                # scores all k+1 positions per slot (position j
                # conditions on the committed stream plus drafts[:j]
                # via paged_chunked_attention's per-query causal
                # bound), appending the candidate KVs optimistically —
                # the host truncates the rejected suffix with
                # paged_rollback.  COW first when sharing: a rollback
                # into a shared block must never leave behind a write
                # its other readers can see.
                with paged.decode_kernel_scope(use_kernel), \
                        paged.kernel_fallback_scope(
                            self._note_kernel_fallback), \
                        paged.paged_mesh_scope(mesh, mesh_axis):
                    if sharing:
                        cache, cok = paged.paged_cow(cache, valid)
                    cache, ok = paged.paged_reserve(cache, valid)
                    views = paged.chunked_layer_views(cache, arange_s,
                                                      valid)
                    pos_ids = (cache.lengths[:, None]
                               + jnp.arange(k + 1)[None, :])
                    (lg, views), _ = model.apply(params, {}, None, toks,
                                                 views, pos_ids)
                    cache = paged.paged_advance(
                        paged.merge_views(cache, views), valid)
                    lf = lg.astype(jnp.float32)           # [S, k+1, V]
                    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
                    tcol = jnp.maximum(temps, 1e-6)[:, None, None]
                    probs = jax.nn.softmax(restrict(
                        (lf / tcol).reshape(S * (k + 1), V)),
                        axis=-1).reshape(S, k + 1, V)
                    if sharing:
                        ok = ok & cok
                    return _pin(cache), greedy, probs, ok

            def draft_prefill_fn(dparams, dcache, slot, prompt, plen):
                # the draft sees the FULL prompt even when the target's
                # admission was a prefix-cache hit: the draft pool has
                # no registry, and proposal quality is all this buys
                with paged.decode_kernel_scope(use_kernel), \
                        paged.paged_mesh_scope(mesh, mesh_axis):
                    want = jnp.zeros((S,), jnp.int32).at[slot].set(plen)
                    dcache, ok = paged.paged_reserve(dcache, want)
                    views = paged.layer_views(dcache, slot[None],
                                              plen[None])
                    w = prompt.shape[1]
                    pos_ids = jnp.arange(w)[None, :]
                    (_, views), _ = dmodel.apply(dparams, {}, None,
                                                 prompt, views, pos_ids)
                    dcache = paged.paged_advance(
                        paged.merge_views(dcache, views), want)
                    return _pin(dcache), ok

            self._draft = jax.jit(draft_fn, donate_argnums=(1,))
            self._draft_prefill = jax.jit(draft_prefill_fn,
                                          donate_argnums=(1,))
            self._rollback = jax.jit(paged.paged_rollback,
                                     donate_argnums=(0,))
            # shard-check contract (paged-engine-decode-spec): verify
            # args 2..4 (toks, valid, temps) are slot-major — shard
            # them on the data axis, params + pool replicated (same
            # rationale as _decode_slot_args)
            self._verify_slot_args = (2, 3, 4)
            watched["draft"] = self._draft
            watched["draft_prefill"] = self._draft_prefill
            watched["rollback"] = self._rollback
            if not self._unified:
                # legacy multi-program mode: verify is its own
                # compiled program; unified mode folds the verify
                # window into the step program above
                self._verify = jax.jit(verify_fn, donate_argnums=(1,))
                watched["verify"] = self._verify
        from paddle_tpu.analysis.watch import CompileWatcher
        self._compile_watch = CompileWatcher(**watched)
        self.cache = paged.paged_init(cfg.num_layers, S, self.maxb,
                                      self.nb, self.bs, cfg.num_heads,
                                      hd, self.kv_dtype)
        if mesh is not None:
            # place the fresh pool in its head-sharded layout up front
            # so the first donated step starts from the steady-state
            # placement (no resharding transfer on step one)
            self.cache = jax.device_put(
                self.cache,
                paged_cache_shardings(self.cache, mesh, mesh_axis))
        self._key = jax.random.key(seed)
        # host mirrors: fixed-shape device carries + per-slot requests
        self._slots = [None] * S          # _Request or None
        self._tok = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._done = np.ones((S,), bool)
        self._queue = deque()
        self._results = {}
        self._next_rid = 0
        self._reserved = 0                # worst-case blocks, admitted
        self._pinned = 0                  # registry-pinned pool blocks
        self._prefix = (PrefixCache(self.bs,
                                    host_store=self._host_store)
                        if sharing else None)
        # tail pad widths: a hit's unmatched tail can be one token
        # (the full-prompt-hit replay), so the tail buckets extend the
        # prompt buckets downward; one tail-prefill compile per width
        # actually used
        self._tail_buckets = tuple(sorted({1, self.bs, *self.buckets}))
        if spec is not None:
            # the draft's own block pool, sized to the worst case
            # (every slot at per-slot capacity plus k in-flight
            # proposals): the draft allocator can never run dry, so it
            # needs no admission ledger of its own.  Draft positions
            # can transiently exceed max_len by up to k-2 near
            # capacity — the position embedding clips (mode="clip"),
            # degrading PROPOSALS only, never committed tokens.
            self._dmaxb = -(-(self.cap + self.spec_k) // self.bs)
            self._dnb = S * self._dmaxb
            self.dcache = paged.paged_init(
                draft.cfg.num_layers, S, self._dmaxb, self._dnb,
                self.bs, draft.cfg.num_heads,
                draft.cfg.dim // draft.cfg.num_heads,
                get_policy().compute_dtype)
            if mesh is not None:
                self.dcache = jax.device_put(
                    self.dcache,
                    paged_cache_shardings(self.dcache, mesh, mesh_axis))
            self._dlen = [None] * S       # draft cache length mirror
            self._dpend = [None] * S      # committed, not yet drafted
            self._spec_rng = np.random.default_rng(seed)
        self.decode_steps = 0
        self.tokens_decoded = 0
        self._run_seconds = 0.0
        # last-step heartbeat (host_state(): the watchdog/router feed)
        self._last_step_wall = None       # time.time() at last step end
        self._last_step_seconds = None    # duration of that step
        # Telemetry — ALL host-side, observed only after device values
        # come home (int()/np.asarray syncs): a metric update inside the
        # jitted step would be the host-callback-in-loop lint error, and
        # the compiles == {'step': 1} pin proves instrumentation does
        # not perturb tracing.  Handles are resolved once here so the
        # per-step cost is a few dict-free increments.
        self.metrics = (metrics if metrics is not None
                        else telemetry.get_registry())
        # Request-level tracing + flight recorder (telemetry/trace.py).
        # Host-side like the metrics: every event is stamped after a
        # device value already came home.  None = tracing off (the
        # probe per event site is one attribute check).
        if tracer is None and flight_recorder is not None:
            tracer = telemetry.Tracer(
                name="serving", flight_path=flight_recorder,
                flight_window_s=flight_window_s)
        elif tracer is not None and flight_recorder is not None:
            tracer.flight_path = flight_recorder
            tracer.flight_window_s = float(flight_window_s)
        self.tracer = tracer
        m = self.metrics
        self._m_queue_wait = m.histogram(
            "serving_queue_wait_seconds",
            help="submit() -> admission (prefill start) wait")
        self._m_ttft = m.histogram(
            "serving_ttft_seconds",
            help="submit() -> first token on the host (prefill incl. "
                 "queue wait)")
        self._m_tpot = m.histogram(
            "serving_time_per_output_token_seconds",
            help="(retire - first token) / (tokens - 1), recorded at "
                 "retire — the steady-state decode latency per token")
        self._m_step = m.histogram(
            "serving_step_seconds",
            help="one step() call: admit + jitted decode + retire")
        self._m_steps = m.counter(
            "serving_decode_steps_total", help="decode steps driven")
        self._m_tokens = m.counter(
            "serving_tokens_decoded_total",
            help="tokens produced by decode steps (prefill tok0 excluded"
                 ", matching stats()['tokens_decoded'])")
        self._m_submitted = m.counter(
            "serving_submitted_total", help="requests accepted by submit")
        self._m_rejects = m.counter(
            "serving_admission_rejects_total",
            help="admission attempts blocked, by reason=slots|pool "
                 "(counted once per blocked _admit call)")
        self._m_submit_rejects = m.counter(
            "serving_submit_rejects_total",
            help="submit() calls rejected before queuing, by reason "
                 "(queue_full = bounded-queue backpressure)")
        self._m_retired = m.counter(
            "serving_retired_total",
            help="requests retired, by reason=eos|max_new")
        self._m_occup = m.gauge(
            "serving_pool_occupancy_fraction",
            help="host-side estimate of pool blocks holding live tokens"
                 " / pool size, sampled per step (device truth: "
                 "occupancy(), which syncs)")
        self._m_blocks = m.gauge(
            "serving_pool_blocks_in_use",
            help="host-side estimate of pool blocks holding live tokens")
        self._m_reserved_g = m.gauge(
            "serving_blocks_reserved_worst_case",
            help="admission accounting: worst-case blocks reserved")
        self._m_slots_g = m.gauge(
            "serving_slots_active", help="slots holding a live request")
        self._m_compiles = m.gauge(
            "serving_compiles",
            help="compiles since engine construction per jitted fn "
                 "(CompileWatcher), sampled per step; decode must stay 1")
        # compile_seconds{program=} rides the watcher itself: poll()
        # (per step / per prefill) turns count growth into histogram
        # observations and, past each program's first compile, a
        # "recompile" trace instant naming the program
        self._compile_watch.bind_metrics(m)
        self._m_kernel_fallback = m.counter(
            "serving_kernel_fallback_total",
            help="kernel-selected attention calls that traced the XLA "
                 "gather form anyway, by reason="
                 + "|".join(paged.KERNEL_FALLBACK_REASONS)
                 + " (fires at trace time, once per attention call per"
                 " layer per compiled program — never per step)")
        self._m_kernel_dispatch = m.counter(
            "serving_kernel_dispatch_total",
            help="paged-attention calls that traced the Pallas kernel,"
                 " by form=" + "|".join(paged.KERNEL_DISPATCH_FORMS)
                 + " — the positive twin of serving_kernel_fallback_"
                 "total (fires at trace time; the selfcheck mixed-"
                 "batch gate pins form=ragged nonzero)")
        self._m_kv_pool_bytes = m.gauge(
            "serving_kv_pool_bytes",
            help="target KV block-pool footprint in HBM bytes (pages + "
                 "quantization scales), by dtype= and shards= — TOTAL "
                 "across the mesh (per-chip = value / shards), set once"
                 " at construction; the int8/bf16 ratio IS the capacity"
                 " headline")
        self._m_kv_pool_bytes.set(
            float(self.nb * self.block_bytes * shards),
            dtype=self.kv_dtype.name, shards=str(shards))
        self._m_kv_div = m.gauge(
            "serving_kv_max_logit_divergence",
            help="max |logit(quantized) - logit(reference)| observed by "
                 "the most recent parity probe (kv_parity_probe / "
                 "note_kv_divergence) — NOT sampled by the engine loop; "
                 "0 until a probe reports")
        self._m_handoff_export = m.counter(
            "serving_handoff_exports_total",
            help="prompts prefilled and exported as KV handoff "
                 "payloads (prefill_to_handoff — the disaggregated "
                 "prefill role's output)")
        self._m_handoff_import = m.counter(
            "serving_handoff_imports_total",
            help="admissions that mapped an imported KV handoff "
                 "payload instead of prefilling the prompt "
                 "(submit_handoff — the disaggregated decode role's "
                 "input)")
        if self._apool is not None:
            self._m_adapter_resident = m.gauge(
                "serving_adapter_resident",
                help="adapters resident in the pooled A/B buffers, "
                     "sampled per step (pool capacity: the adapters= "
                     "knob; evictions keep this <= capacity)")
            self._m_adapter_evictions = m.counter(
                "serving_adapter_evictions_total",
                help="LRU sharer-free adapters evicted from the pool "
                     "under load pressure, by tenant= (a pinned "
                     "adapter — any active row decoding with it — is "
                     "never a victim)")
            self._m_adapter_loads = m.counter(
                "serving_adapter_loads_total",
                help="adapter factor loads written into pool slots, by"
                     " tenant= (warm load_adapter() calls plus "
                     "admission misses)")
            self._m_adapter_hits = m.counter(
                "serving_adapter_hits_total",
                help="admissions whose adapter was already resident, "
                     "by tenant= (no host->device factor traffic)")
            self._m_adapter_misses = m.counter(
                "serving_adapter_misses_total",
                help="admissions that loaded their adapter from "
                     "adapter_source, by tenant= — each observes "
                     "serving_adapter_load_seconds")
            self._m_adapter_load_s = m.histogram(
                "serving_adapter_load_seconds",
                help="wall time to make a missing adapter resident "
                     "(artifact read + factor device writes) — the "
                     "miss-vs-hit latency split's miss side; resident "
                     "hits never observe here",
                buckets=(.0005, .001, .0025, .005, .01, .025, .05,
                         .1, .25, .5, 1.0))
            self._m_adapter_tokens = m.counter(
                "serving_adapter_tokens_total",
                help="generated tokens retired per tenant= (adapter "
                     "and base requests both count; base rows without "
                     "a tenant land on tenant=\"default\") — the "
                     "per-tenant usage-metering feed")
        if spec is not None:
            self._m_spec_drafted = m.counter(
                "serving_spec_draft_tokens_total",
                help="draft tokens proposed into verify windows (a "
                     "slot's window is 1+min(k, remaining-1) wide)")
            self._m_spec_accepted = m.counter(
                "serving_spec_accepted_tokens_total",
                help="draft tokens accepted by verify and committed")
            self._m_spec_rollback = m.counter(
                "serving_spec_rollback_tokens_total",
                help="verify-appended tokens discarded by accept/"
                     "reject (cursor truncation via paged_rollback, or "
                     "freed with the slot at retire)")
            self._m_spec_accept_rate = m.histogram(
                "serving_spec_accept_rate",
                help="per-slot accepted/proposed per spec step (slots "
                     "with a non-empty draft window)",
                buckets=(0.0, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                         0.875, 1.0))
            self._m_spec_tps = m.histogram(
                "serving_spec_tokens_per_step",
                help="tokens committed per slot per spec step (1 to "
                     "k+1) — the frontend's completion-rate feed",
                buckets=tuple(float(i)
                              for i in range(1, self.spec_k + 2)))
        if sharing:
            self._m_prefix_hits = m.counter(
                "serving_prefix_hits_total",
                help="admissions that mapped >=1 cached prefix block "
                     "instead of prefilling it")
            self._m_prefix_misses = m.counter(
                "serving_prefix_misses_total",
                help="admissions with no cached prefix block")
            self._m_prefix_tokens = m.counter(
                "serving_prefix_hit_tokens_total",
                help="prompt tokens served from cached blocks instead "
                     "of prefill (a full-prompt hit still replays its "
                     "final token, which is counted as prefilled)")
            self._m_prefix_hist = m.histogram(
                "serving_prefix_hit_length_tokens",
                help="matched prefix length per admission, tokens "
                     "(misses observe 0)",
                buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                         128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0))
            self._m_prefix_pinned = m.gauge(
                "serving_prefix_pinned_blocks",
                help="pool blocks pinned by the prefix registry (their "
                     "refcount survives every slot retiring)")
            self._m_prefix_shared = m.gauge(
                "serving_prefix_shared_blocks",
                help="registered blocks currently mapped by at least "
                     "one live request (host-side estimate)")
            self._m_prefix_evict = m.counter(
                "serving_prefix_evictions_total",
                help="registered blocks leaving their tier under pool "
                     "pressure (LRU sharer-free leaves) or by flush; "
                     "tier=hbm counts blocks leaving the device pool "
                     "(demoted OR destroyed), tier=host counts host-"
                     "store entries destroyed.  The unlabeled series "
                     "is the historical name and sums both tiers.")
            if self._host_store is not None:
                self._m_prefix_spilled_bytes = m.gauge(
                    "serving_prefix_spilled_bytes",
                    help="host bytes the spill tier currently pins "
                         "(pages + int8 scales of demoted prefix "
                         "blocks; reconciles with the host store)")
                self._m_prefix_spilled_blocks = m.gauge(
                    "serving_prefix_spilled_blocks",
                    help="registry nodes whose pages live only in the "
                         "host tier (block_id freed back to the pool)")
                self._m_prefix_spills = m.counter(
                    "serving_prefix_spills_total",
                    help="resident prefix blocks demoted to the host "
                         "tier instead of destroyed")
                self._m_prefix_restores = m.counter(
                    "serving_prefix_restores_total",
                    help="admissions that promoted >=1 spilled node "
                         "back to the device pool before tail prefill")
                self._m_prefix_restore_blocks = m.counter(
                    "serving_prefix_restore_blocks_total",
                    help="pool blocks re-imported from the host tier "
                         "on restore hits")
                self._m_prefix_restore_s = m.histogram(
                    "serving_prefix_restore_seconds",
                    help="wall time of one restore (host concat + "
                         "paged_import_blocks + device_put + re-pin)",
                    buckets=(.0005, .001, .0025, .005, .01, .025, .05,
                             .1, .25, .5, 1.0))

    # ---------------------------------------------------------- host API

    def submit(self, prompt_ids, max_new: int,
               temperature: float = 0.0, *, adapter=None,
               tenant=None) -> int:
        """Queue one request; returns its id.  ``prompt_ids``: 1-D int
        sequence.  Capacity contract is loud: the prompt must fit a
        bucket and ``prompt + max_new`` the per-slot capacity.

        ``adapter=``/``tenant=`` (adapter engines): decode this
        request under ``(tenant, adapter)``'s pooled LoRA delta —
        resolved (loading through ``adapter_source`` on a miss) and
        pinned at admission, unpinned at retire.  ``adapter=None``
        rides the slot-id -1 fast path: bit-identical to an engine
        built without adapters."""
        enforce(adapter is None or self._apool is not None,
                "submit: adapter=%r but the engine was built without "
                "an adapter pool (pass adapters=N)", adapter)
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = prompt.shape[0]
        enforce(n >= 1, "submit: empty prompt")
        enforce(any(n <= w for w in self.buckets),
                "submit: prompt length %d exceeds every prefill bucket "
                "%s", n, self.buckets)
        enforce(max_new >= 1 and n + max_new <= self.cap,
                "submit: prompt %d + max_new %d exceeds per-slot "
                "capacity %d", n, max_new, self.cap)
        blocks = -(-(n + max_new) // self.bs)
        # with prefix sharing a request's worst case carries one extra
        # block: the copy-on-write replacement of a shared/pinned block
        worst = blocks + 1 if self.prefix_enabled else blocks
        enforce(worst <= self.nb,
                "submit: request worst case %d blocks exceeds the pool "
                "(%d) — it could never be admitted", worst, self.nb)
        if self.max_queue is not None \
                and len(self._queue) >= self.max_queue:
            # backpressure, not memory growth: the typed reject is the
            # signal SLO-aware callers (the frontend) shed on
            self._m_submit_rejects.inc(reason="queue_full")
            if self.tracer is not None:
                self.tracer.instant("submit_rejected", track="host",
                                    reason="queue_full",
                                    queued=len(self._queue))
            raise QueueFull(len(self._queue), self.max_queue)
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, prompt, max_new, float(temperature), blocks,
                       adapter=adapter, tenant=tenant)
        self._queue.append(req)
        self._m_submitted.inc()
        if self.tracer is not None:
            extra = {}
            if adapter is not None:
                extra["adapter"] = str(adapter)
            if tenant is not None:
                extra["tenant"] = str(tenant)
            self.tracer.instant("submit", track="host", rid=rid,
                                ts=req.submitted_at, prompt_len=int(n),
                                max_new=int(max_new), **extra)
        return rid

    def prefill_to_handoff(self, prompt_ids,
                           temperature: float = 0.0, *,
                           rid: Optional[int] = None) -> dict:
        """Prefill a prompt and EXPORT its KV blocks as a handoff
        payload instead of decoding — the disaggregated PREFILL role
        (``paddle_tpu/cluster``): a prefill worker calls this per
        admitted prompt and ships the payload to a decode worker's
        :meth:`submit_handoff`.

        A free slot is borrowed for the call and freed before
        returning, so this composes with live decode traffic on the
        same engine.  The sampled first token is deliberately
        DISCARDED: the decode side maps the blocks with the length
        cursor one short and replays the final prompt token through
        its own tail prefill, which regenerates the first token
        bit-identically (the prefix-cache full-prompt-hit replay
        contract) — no token or RNG state crosses the wire.

        ``rid`` tags the trace events only (this engine never owns the
        request): the cluster worker passes the controller's request
        id from the wire trace context, so the prefill and export
        spans land on the same cross-process waterfall as the decode
        side's."""
        t0 = time.perf_counter()
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = prompt.shape[0]
        enforce(n >= 1, "prefill_to_handoff: empty prompt")
        enforce(any(n <= w for w in self.buckets),
                "prefill_to_handoff: prompt length %d exceeds every "
                "prefill bucket %s", n, self.buckets)
        blocks = -(-n // self.bs)
        enforce(self._reserved + self._pinned + blocks <= self.nb,
                "prefill_to_handoff: %d blocks needed but only %d "
                "unreserved in the pool", blocks,
                self.nb - self._reserved - self._pinned)
        try:
            slot = self._slots.index(None)
        except ValueError:
            enforce(False, "prefill_to_handoff: no free slot")
        if self._faults is not None:
            self._faults.fire("prefill")
        width = (self._prefill_width if self._unified
                 else min(w for w in self.buckets if n <= w))
        padded = np.zeros((1, width), np.int32)
        padded[0, :n] = prompt
        self.cache, _tok0, _done0, ok = self._prefill(
            self.params, self.cache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(padded), jnp.asarray(n, jnp.int32),
            float(temperature), self._split(), *self._ad_extra())
        assert bool(ok), "paged pool exhausted despite handoff " \
                         "accounting (engine bug)"
        t_sync = time.perf_counter()   # bool(ok) synced the prefill
        payload = paged.paged_export_blocks(self.cache, slot)
        payload["prompt"] = prompt
        self.cache = self._free(
            self.cache, jnp.asarray(np.arange(self.S) == slot))
        self._m_handoff_export.inc()
        if self.tracer is not None:
            # complete spans (not instants) so the merged cluster
            # trace can place the wire leg between export end and the
            # decode side's import start
            self.tracer.complete("prefill", t0, t_sync, track="host",
                                 rid=rid, prompt_len=int(n),
                                 handoff=True)
            self.tracer.complete("handoff_export", t_sync, track="host",
                                 rid=rid, prompt_len=int(n),
                                 blocks=int(blocks))
        self._compile_watch.poll(time.perf_counter() - t0,
                                 tracer=self.tracer)
        return payload

    def submit_handoff(self, payload: dict, max_new: int,
                       temperature: float = 0.0) -> int:
        """Queue a request whose prompt KV arrives as an imported
        handoff payload (:meth:`prefill_to_handoff` on another engine)
        — the disaggregated DECODE role.  Admission writes the
        payload's pages (and, for int8 pools, their per-block scales)
        into free pool blocks, maps them into the slot with
        ``paged_share``-style refcount pinning, and replays only the
        final prompt token, so the greedy stream is bit-identical to a
        local :meth:`submit` of the same prompt.  Capacity and
        queue-bound contracts match :meth:`submit`."""
        enforce(self._unified or self.prefix_enabled,
                "submit_handoff needs the tail-prefill program: build "
                "the engine with unified_step=True (default) or "
                "prefix_cache=True")
        prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
        n = prompt.shape[0]
        enforce(n >= 1, "submit_handoff: empty prompt")
        enforce(int(payload["length"]) == n,
                "submit_handoff: payload covers %s tokens but the "
                "prompt is %d — partial handoffs are not a thing",
                payload["length"], n)
        enforce(jnp.dtype(payload["kv_dtype"]) == self.kv_dtype,
                "submit_handoff: payload kv_dtype %s != pool %s",
                payload["kv_dtype"], self.kv_dtype.name)
        enforce(int(payload["block_size"]) == self.bs,
                "submit_handoff: payload block_size %s != pool %d",
                payload["block_size"], self.bs)
        enforce(any(n <= w for w in self.buckets),
                "submit_handoff: prompt length %d exceeds every "
                "prefill bucket %s", n, self.buckets)
        enforce(max_new >= 1 and n + max_new <= self.cap,
                "submit_handoff: prompt %d + max_new %d exceeds "
                "per-slot capacity %d", n, max_new, self.cap)
        blocks = -(-(n + max_new) // self.bs)
        worst = blocks + 1 if self.prefix_enabled else blocks
        enforce(worst <= self.nb,
                "submit_handoff: request worst case %d blocks exceeds "
                "the pool (%d) — it could never be admitted", worst,
                self.nb)
        if self.max_queue is not None \
                and len(self._queue) >= self.max_queue:
            self._m_submit_rejects.inc(reason="queue_full")
            if self.tracer is not None:
                self.tracer.instant("submit_rejected", track="host",
                                    reason="queue_full",
                                    queued=len(self._queue))
            raise QueueFull(len(self._queue), self.max_queue)
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, prompt, max_new, float(temperature),
                       blocks, handoff=payload)
        self._queue.append(req)
        self._m_submitted.inc()
        if self.tracer is not None:
            self.tracer.instant("submit", track="host", rid=rid,
                                ts=req.submitted_at, prompt_len=int(n),
                                max_new=int(max_new), handoff=True)
        return rid

    def _split(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _note_kernel_fallback(self, reason: str):
        """Trace-time observer (``paged.kernel_fallback_scope``): a
        program that SELECTED the Pallas decode kernel traced the XLA
        gather form anyway.  Fires on the host during tracing (once
        per attention call per layer per compiled program) — never
        inside a compiled step."""
        self._m_kernel_fallback.inc(reason=reason)

    def _note_kernel_dispatch(self, form: str):
        """Trace-time observer (``paged.kernel_dispatch_scope``): a
        paged-attention call traced the Pallas kernel — ``form`` is
        ``decode`` (t=1 window) or ``ragged`` (multi-token window).
        The selfcheck mixed-batch gate asserts nonzero ragged
        dispatches so a silent regression to the XLA path is loud."""
        self._m_kernel_dispatch.inc(form=form)

    def note_kv_divergence(self, value: float):
        """Record a measured quantization divergence (max absolute
        logit delta vs a reference pool, the ``kv_parity_probe``
        output) into ``serving_kv_max_logit_divergence{dtype=}``.  The
        engine never measures this itself — a probe needs a second,
        reference-dtype forward pass — so the gauge reports whatever
        the operator's most recent probe found."""
        self._m_kv_div.set(float(value), dtype=self.kv_dtype.name)

    # ------------------------------------------------------- adapters

    def _note_adapter_evict(self, tenant: str, name: str, slot: int):
        """Registry eviction observer: an LRU sharer-free adapter left
        the pool under load pressure.  Host-side, after the eager
        ``paged_adapter_free`` returned — never inside a traced step."""
        self._m_adapter_evictions.inc(tenant=tenant)
        if self.tracer is not None:
            self.tracer.instant("adapter_evict", track="host",
                                tenant=tenant, adapter=name,
                                pool_slot=int(slot))

    def load_adapter(self, name: str, artifact,
                     tenant: str = "default") -> int:
        """Make adapter ``(tenant, name)`` resident ahead of traffic
        (the warm path — admission misses route through
        ``adapter_source`` instead).  ``artifact``: a
        :func:`paddle_tpu.adapters.save_adapter` path or an in-memory
        ``{"a": [...], "b": [...], "scale": float}``.  Returns the
        pool slot; raises ``AdapterPoolFull`` when every slot is
        pinned by active requests."""
        enforce(self._apool is not None,
                "load_adapter: engine built without adapters "
                "(pass adapters=N)")
        t0 = time.perf_counter()
        slot = self._adapters.load(name, artifact, tenant=tenant)
        self._m_adapter_loads.inc(tenant=str(tenant))
        self._m_adapter_load_s.observe(time.perf_counter() - t0)
        if self.tracer is not None:
            self.tracer.instant("adapter_load", track="host",
                                tenant=str(tenant), adapter=str(name),
                                pool_slot=int(slot))
        return slot

    def unload_adapter(self, name: str, tenant: str = "default") -> bool:
        """Explicitly release a sharer-free resident adapter."""
        enforce(self._apool is not None,
                "unload_adapter: engine built without adapters")
        return self._adapters.unload(name, tenant=tenant)

    def adapter_step_args(self):
        """The unified step's adapter argument for the CURRENT slot
        map: ``(a_stacks, b_stacks, scales, ids[S])`` — what the
        decode/prefill dispatches (and the ``paged-engine-step-lora``
        lint entrypoint) pass as the step's last parameter."""
        enforce(self._apool is not None,
                "adapter_step_args: engine built without adapters")
        return self._apool.device_args(self._adapter_slots)

    def _ad_extra(self) -> tuple:
        """``(adapter_arg,)`` for adapter engines, ``()`` otherwise —
        splatted onto every unified step/prefill dispatch so the
        non-adapter call sites stay byte-identical."""
        if self._apool is None:
            return ()
        return (self._apool.device_args(self._adapter_slots),)

    def _acquire_adapter(self, req) -> int:
        """Admission-side adapter residency: resolve ``(tenant,
        adapter)`` to a pool slot — loading through ``adapter_source``
        on a miss (the timed load-from-host path) — and PIN it for the
        request's lifetime.  Raises ``AdapterPoolFull`` when the pool
        is resident-full and fully pinned (the caller rejects the
        admission like pool pressure, without dequeuing)."""
        tenant = req.tenant if req.tenant is not None else "default"
        t0 = time.perf_counter()
        slot = self._adapters.resolve(req.adapter, tenant=tenant)
        if slot is None:
            enforce(self._adapter_source is not None,
                    "adapter %r (tenant %r) is not resident and the "
                    "engine has no adapter_source to load it from — "
                    "load_adapter() it first or attach a source",
                    req.adapter, tenant)
            artifact = self._adapter_source(tenant, req.adapter)
            slot = self._adapters.load(req.adapter, artifact,
                                       tenant=tenant)
            dt = time.perf_counter() - t0
            self._m_adapter_misses.inc(tenant=tenant)
            self._m_adapter_loads.inc(tenant=tenant)
            self._m_adapter_load_s.observe(dt)
            if self.tracer is not None:
                self.tracer.instant("adapter_load", track="host",
                                    tenant=tenant,
                                    adapter=str(req.adapter),
                                    pool_slot=int(slot), rid=req.rid,
                                    load_s=dt)
        else:
            self._m_adapter_hits.inc(tenant=tenant)
        self._adapters.pin(slot)
        return slot

    def _admit(self):
        """Prefill queued requests into free slots while the pool's
        worst-case accounting allows — called before every decode step,
        which is what splices new work in MID-STREAM.

        With the prefix cache on, each prompt first matches the radix
        registry: matched blocks map into the slot by refcount
        increment (no prefill over the shared tokens, the
        :meth:`_admit_hit` fast path) and only the unmatched tail runs
        through the model; after prefill the prompt's blocks register
        and PIN (:meth:`_register_prefix`) so the next request behind
        the same prefix hits.  Worst-case accounting adds the pinned
        blocks plus one COW-slack block per admission, and pool
        pressure evicts LRU sharer-free registry leaves before
        rejecting."""
        while self._queue:
            if self._faults is not None:
                # one "admit" invocation per admission ATTEMPT with
                # queued work, before any state moves — an injected
                # raise here models admission failure and leaves the
                # queue/slots/ledger exactly as they were
                self._faults.fire("admit")
            try:
                slot = self._slots.index(None)
            except ValueError:
                self._m_rejects.inc(reason="slots")
                if self.tracer is not None:
                    self.tracer.instant("admission_blocked",
                                        track="host", reason="slots",
                                        queued=len(self._queue))
                return                    # all slots busy
            req = self._queue[0]
            hit = None
            need = req.blocks_reserved
            slack = 0
            if self._prefix is not None and req.handoff is not None:
                # handoff admission skips the registry match (the
                # prompt's KV arrives in the payload) but still
                # REGISTERS after import, which can pin its tail block
                # — the same COW-slack rule as a fresh admission
                slack = 1
                short = (self._reserved + self._pinned + need + slack
                         - self.nb)
                if short > 0:
                    self._evict_prefix(short)
            elif self._prefix is not None:
                hit = self._prefix.match(req.prompt)
                if hit.block_ids:
                    # RESIDENT matched blocks are paid for already:
                    # reserve the tail plus ONE block of copy-on-write
                    # slack.  SPILLED matched blocks stay in `need` —
                    # the restore re-imports each into a fresh pool
                    # block, and _admit_hit transfers that reservation
                    # to the registry pin once the block is resident.
                    resident = sum(1 for nd in hit.nodes
                                   if not nd.spilled)
                    need = need - resident + 1
                # registration may pin this request's own tail block
                # past its reservation's reach — one more COW-slack
                # block keeps the ledger an upper bound
                # (_register_prefix works the transfer rule)
                slack = 1
                for nd in hit.nodes:      # protect the match from the
                    nd.sharers.add(req.rid)   # eviction pass below
                short = (self._reserved + self._pinned + need + slack
                         - self.nb)
                if short > 0:
                    self._evict_prefix(short)
            if self._reserved + self._pinned + need + slack > self.nb:
                if hit is not None:
                    for nd in hit.nodes:
                        nd.sharers.discard(req.rid)
                self._m_rejects.inc(reason="pool")
                if self.tracer is not None:
                    self.tracer.instant("admission_blocked",
                                        track="host", reason="pool",
                                        rid=req.rid,
                                        queued=len(self._queue))
                return                    # pool cannot take it yet
            ad_slot = -1
            if self._apool is not None and req.adapter is not None:
                try:
                    ad_slot = self._acquire_adapter(req)
                except AdapterPoolFull:
                    # every adapter slot is pinned by an active
                    # request: block admission (request stays queued)
                    # exactly like KV-pool pressure — a retire will
                    # unpin and the next _admit proceeds
                    if hit is not None:
                        for nd in hit.nodes:
                            nd.sharers.discard(req.rid)
                    self._m_rejects.inc(reason="adapter_pool")
                    if self.tracer is not None:
                        self.tracer.instant("admission_blocked",
                                            track="host",
                                            reason="adapter_pool",
                                            rid=req.rid,
                                            queued=len(self._queue))
                    return
            if self._faults is not None:
                try:
                    # fires once per request actually reaching its
                    # prefill dispatch; the request is still queued, so
                    # an injected raise loses nothing — only the
                    # eviction-guard marks need unwinding
                    self._faults.fire("prefill")
                except BaseException:
                    if hit is not None:
                        for nd in hit.nodes:
                            nd.sharers.discard(req.rid)
                    if ad_slot >= 0:
                        # the pin was the only state moved so far
                        self._adapters.unpin(ad_slot)
                    raise
            self._queue.popleft()
            req.blocks_reserved = need
            if self._apool is not None:
                # slot-map mirror BEFORE the prefill dispatch: the
                # prompt's own logits must run under its adapter
                req.adapter_slot = ad_slot
                self._adapter_slots[slot] = ad_slot
            t_admit = time.perf_counter()
            self._m_queue_wait.observe(t_admit - req.submitted_at)
            if self.tracer is not None:
                # queue span sits on the slot's track so the request's
                # waterfall reads top-to-bottom on one line
                self.tracer.instant("admit", track="host", rid=req.rid,
                                    ts=t_admit, slot=slot)
                self.tracer.complete("queue", req.submitted_at, t_admit,
                                     track=f"slot{slot}", rid=req.rid)
            if req.handoff is not None:
                tok0, done0, ok, width, ptoks = self._admit_handoff(
                    req, slot)
            elif hit is not None and hit.block_ids:
                tok0, done0, ok, width, ptoks = self._admit_hit(
                    req, slot, hit)
            else:
                # unified mode pads every prompt to the ONE ragged
                # prefill width (the program masks per-row, so pad
                # lanes are don't-care); legacy picks a bucket and
                # compiles per width used
                width = (self._prefill_width if self._unified
                         else min(w for w in self.buckets
                                  if req.prompt.shape[0] <= w))
                padded = np.zeros((1, width), np.int32)
                padded[0, :req.prompt.shape[0]] = req.prompt
                self.cache, tok0, done0, ok = self._prefill(
                    self.params, self.cache,
                    jnp.asarray(slot, jnp.int32), jnp.asarray(padded),
                    jnp.asarray(req.prompt.shape[0], jnp.int32),
                    req.temperature, self._split(), *self._ad_extra())
                ptoks = int(req.prompt.shape[0])
            assert bool(ok), "paged pool exhausted despite admission " \
                             "accounting (engine bug)"
            if self._prefix is not None:
                if hit is None:           # handoff: no registry match
                    hit = _HandoffHit()   # ran; register + pin below
                elif hit.block_ids:
                    self._m_prefix_hits.inc()
                    self._m_prefix_tokens.inc(req.prefix_hit_tokens)
                    self._m_prefix_hist.observe(float(hit.shared_len))
                else:
                    self._m_prefix_misses.inc()
                    self._m_prefix_hist.observe(float(hit.shared_len))
                self._register_prefix(req, slot, hit)
            self._reserved += req.blocks_reserved
            self._slots[slot] = req
            req.tokens.append(int(tok0))   # host sync: tok0 is REAL now
            req.first_token_at = time.perf_counter()
            ttft = req.first_token_at - req.submitted_at
            self._m_ttft.observe(ttft)
            if self.tracer is not None:
                self.tracer.complete("prefill", t_admit,
                                     req.first_token_at,
                                     track=f"slot{slot}", rid=req.rid,
                                     prompt_len=req.prompt.shape[0],
                                     prefill_tokens=ptoks,
                                     bucket=width)
                self.tracer.instant("first_token", track=f"slot{slot}",
                                    rid=req.rid,
                                    ts=req.first_token_at,
                                    ttft_s=ttft)
            self._tok[slot] = req.tokens[-1]
            self._temps[slot] = req.temperature
            self._done[slot] = bool(done0)
            if bool(done0) or req.max_new == 1:
                self._retire(slot,
                             "eos" if bool(done0) else "max_new")

    def _admit_hit(self, req, slot, hit):
        """Admission fast path for a prefix-cache hit: map the matched
        blocks into the slot (``paged_share`` — refcount increments, no
        prefill over the shared tokens) and run the model over the
        unmatched tail only.  A FULL-prompt hit still replays the final
        prompt token with the length cursor held one short — the
        prefill must emit sampling logits — and ``paged_cow`` routes
        the replayed write into a private block, never under the
        registered copy's other readers."""
        n = int(req.prompt.shape[0])
        spilled = [nd for nd in hit.nodes if nd.spilled]
        if spilled:
            self._restore_spilled(req, slot, spilled)
        new_len = hit.shared_len if hit.shared_len < n else n - 1
        nmap = len(hit.block_ids)
        bid = np.zeros((self.maxb,), np.int32)
        # read ids off the NODES, not hit.block_ids — a restore just
        # rewrote the spilled entries' block_id from -1 to fresh blocks
        bid[:nmap] = [nd.block_id for nd in hit.nodes]
        self.cache = self._share(
            self.cache, jnp.asarray(slot, jnp.int32), jnp.asarray(bid),
            jnp.asarray(nmap, jnp.int32),
            jnp.asarray(new_len, jnp.int32))
        tlen = n - new_len
        if self._unified:
            # the unified ragged prefill serves tails too — same
            # program, same pad width, no per-tail-bucket compiles
            width = self._prefill_width
            tail_prog = self._prefill
        else:
            width = min(w for w in self._tail_buckets if tlen <= w)
            tail_prog = self._prefill_tail
        padded = np.zeros((1, width), np.int32)
        padded[0, :tlen] = req.prompt[new_len:]
        self.cache, tok0, done0, ok = tail_prog(
            self.params, self.cache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(padded), jnp.asarray(tlen, jnp.int32),
            req.temperature, self._split(), *self._ad_extra())
        req.prefix_hit_tokens = new_len
        if self.tracer is not None:
            self.tracer.instant("prefix_hit", track=f"slot{slot}",
                                rid=req.rid, shared_tokens=new_len,
                                matched_tokens=hit.shared_len,
                                blocks=nmap, prefill_tokens=tlen)
        return tok0, done0, ok, width, tlen

    def _restore_spilled(self, req, slot, spilled):
        """Promote a hit's spilled suffix back to the device pool
        before the share: pop each node's host payload (logical
        order), write them into free blocks in ONE
        ``paged_import_blocks`` call, re-shard under a mesh, PIN the
        imported blocks (+1 refcount — write-then-pin-then-share, so a
        concurrent claim can never zero a just-restored page), then
        promote the registry nodes onto their new block ids.  The
        admission ledger covered these blocks inside the request's
        reservation; pinning transfers them to ``_pinned`` exactly as
        :meth:`_register_prefix` transfers fresh registrations."""
        t_r0 = time.perf_counter()
        payloads = [self._host_store.pop(nd.prefix_keys())
                    for nd in spilled]
        blocks = paged.paged_concat_block_payloads(payloads)
        cache, ids = paged.paged_import_blocks(self.cache, blocks)
        assert ids is not None, \
            "restore found no free blocks despite admission " \
            "accounting (engine bug)"
        if self.mesh is not None:
            # eager host-side page writes drop the pool's head-axis
            # placement — restore it before the donated step sees a
            # mixed-layout cache (the handoff-import rule)
            cache = jax.device_put(
                cache,
                paged_cache_shardings(cache, self.mesh, self.mesh_axis))
        delta = np.zeros((self.nb,), np.int32)
        for b in ids:
            delta[b] += 1
        self.cache = self._rc_add(cache, jnp.asarray(delta))
        for nd, b in zip(spilled, ids):
            self._prefix.promote(nd, int(b))
        self._pinned += len(spilled)
        req.blocks_reserved -= len(spilled)
        nbytes = sum(HostPrefixStore.payload_bytes(p) for p in payloads)
        self._m_prefix_restores.inc()
        self._m_prefix_restore_blocks.inc(len(spilled))
        self._m_prefix_restore_s.observe(time.perf_counter() - t_r0)
        if self.tracer is not None:
            self.tracer.instant("prefix_restore", track=f"slot{slot}",
                                rid=req.rid, blocks=len(spilled),
                                bytes=nbytes)

    def _admit_handoff(self, req, slot):
        """Admission path for an imported-KV request
        (:meth:`submit_handoff`): write the payload's pages into free
        pool blocks (``paged_import_blocks`` — scales land with the
        pages, before any claim could zero them), map them into
        ``slot`` with the length cursor held ONE TOKEN SHORT
        (``paged_share`` sets each imported block's refcount to 1 —
        this slot owns them; retire frees them back to the pool), and
        replay the final prompt token through the tail prefill — the
        prefix-cache full-prompt-hit recipe, so the emitted first
        token and every decode token after it are bit-identical to a
        local prefill of the same prompt."""
        t0 = time.perf_counter()
        n = int(req.prompt.shape[0])
        cache, ids = paged.paged_import_blocks(self.cache, req.handoff)
        assert ids is not None, \
            "handoff import found no free blocks despite admission " \
            "accounting (engine bug)"
        if self.mesh is not None:
            # the eager host-side .at[].set page writes drop the pool's
            # head-axis placement — restore it before the donated step
            # sees a mixed-layout cache
            cache = jax.device_put(
                cache,
                paged_cache_shardings(cache, self.mesh, self.mesh_axis))
        new_len = n - 1
        nmap = len(ids)
        bid = np.zeros((self.maxb,), np.int32)
        bid[:nmap] = ids
        self.cache = self._share(
            cache, jnp.asarray(slot, jnp.int32), jnp.asarray(bid),
            jnp.asarray(nmap, jnp.int32),
            jnp.asarray(new_len, jnp.int32))
        tlen = 1
        if self._unified:
            width = self._prefill_width
            tail_prog = self._prefill
        else:
            width = min(w for w in self._tail_buckets if tlen <= w)
            tail_prog = self._prefill_tail
        padded = np.zeros((1, width), np.int32)
        padded[0, :tlen] = req.prompt[new_len:]
        self.cache, tok0, done0, ok = tail_prog(
            self.params, self.cache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(padded), jnp.asarray(tlen, jnp.int32),
            req.temperature, self._split(), *self._ad_extra())
        req.prefix_hit_tokens = new_len
        req.handoff = None                # pages are resident: drop the
        self._m_handoff_import.inc()      # payload's host copy
        if self.tracer is not None:
            # a complete span (was an instant): the merged cluster
            # trace ends the synthesized wire leg where this starts
            self.tracer.complete("handoff_import", t0,
                                 track=f"slot{slot}", rid=req.rid,
                                 blocks=nmap, imported_tokens=new_len)
        return tok0, done0, ok, width, tlen

    def _register_prefix(self, req, slot, hit):
        """Register the admitted prompt's blocks in the radix tree and
        PIN the newly registered ones (+1 refcount each: a cached
        prefix must survive its donor retiring).  Ledger transfer: a
        pinned block is carried by ``_pinned`` from here on, so the
        request's reservation drops by the new pins — plus one block
        of COW slack when its own tail block got pinned (the next
        decode append into it must copy out first)."""
        row = np.asarray(self.cache.block_tables)[slot]
        new_nodes = self._prefix.insert(req.prompt, row)
        for nd in new_nodes:
            nd.sharers.add(req.rid)
        req.prefix_nodes = tuple(hit.nodes) + tuple(new_nodes)
        if new_nodes:
            delta = np.zeros((self.nb,), np.int32)
            for nd in new_nodes:
                delta[nd.block_id] += 1
            self.cache = self._rc_add(self.cache, jnp.asarray(delta))
            self._pinned += len(new_nodes)
            tail_new = any(nd.is_tail for nd in new_nodes)
            req.blocks_reserved += (1 if tail_new else 0) - len(new_nodes)

    def _export_block(self, block_id: int) -> dict:
        """Registry demotion exporter: one block's pages (+ int8
        scales) as a host payload — the engine owns the device, the
        registry only decides WHICH block spills."""
        return paged.paged_export_block(self.cache, block_id)

    def _evict_prefix(self, n_blocks: int, spill: bool = True) -> int:
        """Unpin up to ``n_blocks`` LRU sharer-free registry leaves.
        The pin is the only refcount such a block still holds, so the
        decrement returns it to the pool immediately.  With a host
        store attached (and ``spill`` true) victims DEMOTE — pages
        serialized host-side before the unpin — instead of being
        destroyed; either way the freed blocks leave the device pool,
        so the ledger math is identical."""
        pre_host = self._prefix.host_evictions
        if spill and self._host_store is not None:
            pre_spills = self._prefix.spills
            freed = self._prefix.demote(n_blocks, self._export_block)
            n_spilled = self._prefix.spills - pre_spills
        else:
            freed = self._prefix.evict(n_blocks)
            n_spilled = 0
        if freed:
            delta = np.zeros((self.nb,), np.int32)
            for b in freed:
                delta[b] -= 1
            self.cache = self._rc_add(self.cache, jnp.asarray(delta))
            self._pinned -= len(freed)
            # unlabeled series = historical name, sums both tiers
            self._m_prefix_evict.inc(len(freed))
            self._m_prefix_evict.inc(len(freed), tier="hbm")
            if self.tracer is not None:
                self.tracer.instant("prefix_evict", track="host",
                                    blocks=len(freed),
                                    spilled=n_spilled)
        if n_spilled:
            self._m_prefix_spills.inc(n_spilled)
            if self.tracer is not None:
                self.tracer.instant("prefix_spill", track="host",
                                    blocks=n_spilled,
                                    host_bytes=self._host_store
                                    .total_bytes)
        n_host = self._prefix.host_evictions - pre_host
        if n_host:
            # host-budget LRU drops and orphaned spilled subtrees
            self._m_prefix_evict.inc(n_host)
            self._m_prefix_evict.inc(n_host, tier="host")
        return len(freed)

    def spill_prefix_cache(self, max_blocks: Optional[int] = None) -> int:
        """Demote up to ``max_blocks`` (default: every evictable)
        sharer-free registry leaves into the host tier, returning
        their device blocks to the pool; returns how many blocks were
        unpinned.  The cold-start / pressure-relief knob: the spilled
        prefixes keep answering radix matches and restore on their
        next hit."""
        enforce(self._prefix is not None,
                "spill_prefix_cache: engine built without prefix_cache")
        enforce(self._host_store is not None,
                "spill_prefix_cache: engine built without "
                "prefix_host_bytes")
        return self._evict_prefix(
            self.nb if max_blocks is None else int(max_blocks),
            spill=True)

    def flush_prefix_cache(self) -> int:
        """Evict every evictable registry entry (sharer-free leaves,
        cascading through emptied parents) and return their blocks to
        the pool; returns how many blocks were unpinned.  Drains BOTH
        tiers: host-store entries are destroyed (never demoted-to) on
        the way out.  Entries still mapped by live requests survive —
        flush again after they retire for a full clear."""
        enforce(self._prefix is not None,
                "flush_prefix_cache: engine built without prefix_cache")
        if self._host_store is not None:
            dropped = self._prefix.drop_spilled()
            if dropped:
                self._m_prefix_evict.inc(dropped)
                self._m_prefix_evict.inc(dropped, tier="host")
        return self._evict_prefix(self.nb, spill=False)

    def _retire(self, slot: int, reason: str = "max_new"):
        if self._faults is not None:
            # before any mutation: an injected raise leaves the
            # finished request in its slot for the supervisor to replay
            self._faults.fire("retire")
        req = self._slots[slot]
        n = len(req.tokens)
        t_retire = time.perf_counter()
        if n > 1 and req.first_token_at is not None:
            self._m_tpot.observe(
                (t_retire - req.first_token_at) / (n - 1))
        self._m_retired.inc(reason=reason)
        if self.tracer is not None:
            if req.first_token_at is not None:
                self.tracer.complete("decode", req.first_token_at,
                                     t_retire, track=f"slot{slot}",
                                     rid=req.rid, tokens=n)
            self.tracer.instant("retire", track=f"slot{slot}",
                                rid=req.rid, ts=t_retire,
                                reason=reason, tokens=n)
        self._results[req.rid] = np.asarray(req.tokens, np.int32)
        self.cache = self._free(
            self.cache, jnp.asarray(np.arange(self.S) == slot))
        self._reserved -= req.blocks_reserved
        if self._apool is not None:
            # unpin BEFORE clearing the slot map: a queued adapter
            # blocked on adapter_pool pressure can admit this _admit
            if req.adapter_slot >= 0:
                self._adapters.unpin(req.adapter_slot)
            self._adapter_slots[slot] = -1
            self._m_adapter_tokens.inc(
                n, tenant=str(req.tenant if req.tenant is not None
                              else "default"))
        if self._prefix is not None:
            # the registry pins keep this request's registered blocks
            # resident; only the live-sharer marks (eviction guards)
            # release here
            for nd in req.prefix_nodes:
                nd.sharers.discard(req.rid)
        if self.spec is not None and self._dlen[slot] is not None:
            # the draft cache mirrors the slot's lifetime: free its
            # blocks with the slot (refcount decrement of every mapped
            # block — any un-rolled-back proposal KVs go with them)
            self.dcache = self._free(
                self.dcache, jnp.asarray(np.arange(self.S) == slot))
            self._dlen[slot] = None
            self._dpend[slot] = None
        self._slots[slot] = None
        self._done[slot] = True

    def _sample_gauges(self):
        """Per-step host-side gauges.  Block usage is the request-level
        estimate (``ceil((prompt + tokens)/block_size)`` per active
        slot — same accounting as :meth:`hbm_report`), so sampling
        costs no device transfer; :meth:`occupancy` stays the device
        truth.  Compile counts come from the CompileWatcher already
        held for the ``compiles == 1`` pin."""
        active = [r for r in self._slots if r is not None]
        in_use = sum(-(-(r.prompt.shape[0] + len(r.tokens)) // self.bs)
                     for r in active)
        self._m_blocks.set(in_use)
        self._m_occup.set(in_use / self.nb)
        self._m_reserved_g.set(self._reserved)
        self._m_slots_g.set(len(active))
        for fn, n in self._compile_watch.counts().items():
            self._m_compiles.set(n, fn=fn)
        if self._apool is not None:
            self._m_adapter_resident.set(
                self._adapters.stats()["resident"])
        if self._prefix is not None:
            st = self._prefix.stats()
            self._m_prefix_pinned.set(st["pinned_blocks"])
            self._m_prefix_shared.set(st["shared_blocks"])
            if self._host_store is not None:
                self._m_prefix_spilled_bytes.set(
                    self._host_store.total_bytes)
                self._m_prefix_spilled_blocks.set(st["spilled_nodes"])

    def step(self):
        """One decode step over every active slot, then retire/admit.
        Each call is timed into ``_run_seconds`` (and the
        ``serving_step_seconds`` histogram) HERE, so throughput
        accounting is correct whether callers drive :meth:`step`
        directly or via :meth:`run`.  If the step raises and a flight
        recorder is armed, the crash dump is written before the
        exception propagates."""
        try:
            return self._step_impl()
        except Exception as exc:
            self._flight_dump(exc)
            raise

    def _step_impl(self):
        t0 = time.perf_counter()
        self._admit()
        active = np.asarray([r is not None for r in self._slots])
        if not active.any():
            return False
        if self._faults is not None:
            # "crash/hang mid-decode": requests hold slots and blocks,
            # generated prefixes exist only in host memory — exactly
            # the state a supervisor must requeue-and-replay
            self._faults.fire("decode_step")
        if self.spec is not None and any(
                r is not None and r.max_new - len(r.tokens) > 1
                for r in self._slots):
            self._spec_decode(active, t0)
        else:
            # spec off — or every live slot needs exactly ONE more
            # token, where the plain step beats draft+verify and is
            # what keeps the 'decode' compile count at exactly 1 with
            # speculation on (the bounded-compile contract)
            self._plain_decode(active, t0)
        self._admit()                     # splice into freed slots NOW
        self._sample_gauges()
        dt = time.perf_counter() - t0
        self._run_seconds += dt           # the decode paths synced: real
        self._m_step.observe(dt)
        # compile_seconds + "recompile" trace instants: any program
        # that compiled during this step gets the step's duration as
        # its (upper-bound) compile-time observation
        self._compile_watch.poll(dt, tracer=self.tracer)
        self._last_step_wall = time.time()
        self._last_step_seconds = dt
        return True

    def _plain_decode(self, active, t0):
        if self._unified:
            # plain decode through the unified step: every active row
            # is a width-1 ragged window (column 0 = its pending
            # token; spec engines pad to the k+1 step width, idle
            # verify columns are don't-care lanes)
            toks = np.zeros((self.S, self.step_width), np.int32)
            toks[:, 0] = self._tok
            out = self._step(
                self.params, self.cache, jnp.asarray(toks),
                jnp.asarray(active.astype(np.int32)),
                jnp.asarray(self._temps), jnp.asarray(self._done),
                self._split(), *self._ad_extra())
            if self.spec is not None:
                self.cache, nxt, done, _greedy, _probs, ok = out
            else:
                self.cache, nxt, done, _greedy, ok = out
        else:
            self.cache, nxt, done, ok = self._decode(
                self.params, self.cache, jnp.asarray(self._tok),
                jnp.asarray(active), jnp.asarray(self._temps),
                jnp.asarray(self._done), self._split())
        assert bool(ok), "paged pool exhausted despite admission " \
                         "accounting (engine bug)"
        nxt, done = np.asarray(nxt), np.asarray(done)
        t_sync = time.perf_counter()      # np.asarray synced: tokens real
        self.decode_steps += 1
        n_active = int(active.sum())
        self.tokens_decoded += n_active
        self._m_steps.inc()
        self._m_tokens.inc(n_active)
        if self.tracer is not None:
            self.tracer.complete("decode_step", t0, t_sync, track="host",
                                 n_active=n_active,
                                 step=self.decode_steps)
        for s in np.nonzero(active)[0]:
            req = self._slots[s]
            req.tokens.append(int(nxt[s]))
            if self.tracer is not None:
                self.tracer.instant("token", track=f"slot{int(s)}",
                                    rid=req.rid, ts=t_sync,
                                    index=len(req.tokens) - 1)
            self._tok[s] = nxt[s]
            self._done[s] = done[s]
            if done[s] or len(req.tokens) >= req.max_new:
                self._retire(s, "eos" if done[s] else "max_new")

    def _draft_admit(self, slot: int):
        """Prefill the draft cache for a freshly admitted slot — on
        demand at its first speculative step, over the FULL prompt
        (the draft pool has no prefix registry; a target-side prefix
        hit changes nothing here).  One draft-prefill compile per
        prompt bucket actually used."""
        req = self._slots[slot]
        assert len(req.tokens) == 1, \
            "draft admit after plain decode steps (engine bug)"
        n = int(req.prompt.shape[0])
        width = (self._prefill_width if self._unified
                 else min(w for w in self.buckets if n <= w))
        padded = np.zeros((1, width), np.int32)
        padded[0, :n] = req.prompt
        self.dcache, ok = self._draft_prefill(
            self._draft_params, self.dcache,
            jnp.asarray(slot, jnp.int32), jnp.asarray(padded),
            jnp.asarray(n, jnp.int32))
        assert bool(ok), "draft pool exhausted (engine bug: the draft " \
                         "pool is sized for the worst case)"
        self._dlen[slot] = n
        # the prefill's sampling already happened on the TARGET; the
        # draft only needs the pending token appended next step
        self._dpend[slot] = [int(req.tokens[-1])]

    def _spec_decode(self, active, t0):
        """One SPECULATIVE step: draft up to ``k`` proposals per live
        slot from the draft cache, verify all ``k + 1`` positions in
        one batched target step, accept/reject on the host, roll the
        rejected suffix back by cursor truncation.  Per-slot verify
        windows are ``1 + min(k, remaining - 1)`` wide, so a transient
        cache length never exceeds the slot's admission reservation
        and commits never overshoot ``max_new``."""
        S, k = self.S, self.spec_k
        for s in np.nonzero(active)[0]:
            if self._dlen[int(s)] is None:
                self._draft_admit(int(s))
        valid = np.zeros((S,), np.int32)
        pend = np.zeros((S, 2), np.int32)
        pend_len = np.zeros((S,), np.int32)
        for s in np.nonzero(active)[0]:
            req = self._slots[s]
            rem = req.max_new - len(req.tokens)
            valid[s] = 1 + min(k, rem - 1)
            pl = self._dpend[int(s)]
            pend[s, :len(pl)] = pl
            pend_len[s] = len(pl)
        temps = jnp.asarray(self._temps)
        self.dcache, drafts, qprobs, dok = self._draft(
            self._draft_params, self.dcache, jnp.asarray(pend),
            jnp.asarray(pend_len), temps, self._split())
        drafts_h = np.asarray(drafts)                    # [S, k]
        toks = np.zeros((S, k + 1), np.int32)
        toks[:, 0] = self._tok                # the pending target token
        toks[:, 1:] = drafts_h
        if self._unified:
            # the verify window rides the unified step (same compiled
            # program as plain decode): the step's own pick/done
            # outputs are for width-1 rows — the host accept/reject
            # below is what commits spec tokens, so both are discarded
            self.cache, _nxt, _done, greedy, probs, vok = self._step(
                self.params, self.cache, jnp.asarray(toks),
                jnp.asarray(valid), temps, jnp.asarray(self._done),
                self._split(), *self._ad_extra())
        else:
            self.cache, greedy, probs, vok = self._verify(
                self.params, self.cache, jnp.asarray(toks),
                jnp.asarray(valid), temps)
        greedy_h = np.asarray(greedy)                    # [S, k+1]
        assert bool(dok) and bool(vok), \
            "paged pool exhausted despite admission accounting " \
            "(engine bug)"
        if any(self._temps[int(s)] > 0 for s in np.nonzero(active)[0]):
            probs_h = np.asarray(probs)       # V-sized transfers only
            q_h = np.asarray(qprobs)          # when someone samples
        t_sync = time.perf_counter()
        cur = np.asarray(self.cache.lengths).copy()
        dcur = np.asarray(self.dcache.lengths).copy()
        tnew, dnew = cur.copy(), dcur.copy()
        plans = []
        n_committed = n_accepted = n_drafted = n_rejected = 0
        for s in np.nonzero(active)[0]:
            s = int(s)
            req = self._slots[s]
            nd = int(valid[s]) - 1            # drafts in this window
            n_drafted += nd
            d = [int(x) for x in drafts_h[s, :nd]]
            if self._temps[s] > 0:
                out, a = spec_mod.rejection_sample(
                    probs_h[s, :nd + 1], q_h[s, :nd], d, self._spec_rng)
            else:
                out, a = spec_mod.greedy_accept(
                    d, [int(x) for x in greedy_h[s, :nd + 1]])
            if self.eos_id is not None and self.eos_id in out:
                out = out[:out.index(self.eos_id) + 1]
            c = len(out)
            a = min(a, c)                     # drafts surviving eos cut
            n_accepted += a
            n_rejected += int(valid[s]) - c
            reason = None
            if self.eos_id is not None and out[-1] == self.eos_id:
                reason = "eos"
            elif len(req.tokens) + c >= req.max_new:
                reason = "max_new"
            if reason is None:
                # non-retiring: truncate the target cache back to the
                # committed stream minus its pending token, the draft
                # back to the accepted-proposal frontier.  Retiring
                # slots skip rollback — _retire's free decrements every
                # mapped block's refcount, rejected KVs included.
                tnew[s] = cur[s] - (int(valid[s]) - c)
                dnew[s] = dcur[s] - ((k - 1) - min(a, k - 1))
            plans.append((s, out, a, nd, reason))
        if np.any(tnew < cur):
            self.cache = self._rollback(
                self.cache, jnp.asarray(tnew.astype(np.int32)))
        if np.any(dnew < dcur):
            self.dcache = self._rollback(
                self.dcache, jnp.asarray(dnew.astype(np.int32)))
        for s, out, a, nd, reason in plans:
            req = self._slots[s]
            for t in out:
                req.tokens.append(int(t))
                if self.tracer is not None:
                    # one instant PER COMMITTED TOKEN: multi-token
                    # steps stay legible in the trace waterfalls
                    self.tracer.instant("token", track=f"slot{s}",
                                        rid=req.rid, ts=t_sync,
                                        index=len(req.tokens) - 1)
            n_committed += len(out)
            self._tok[s] = out[-1]
            if nd > 0:
                self._m_spec_accept_rate.observe(a / nd)
            self._m_spec_tps.observe(float(len(out)))
            if reason is not None:
                self._retire(s, reason)
            else:
                # next step's draft catch-up: the correction token
                # alone, or (every draft accepted) the last proposal —
                # whose KV the draft never appended — plus the bonus
                self._dpend[s] = ([int(out[-2]), int(out[-1])]
                                  if a >= k else [int(out[-1])])
                self._dlen[s] = int(dnew[s])
        self.decode_steps += 1
        self.tokens_decoded += n_committed
        self._m_steps.inc()
        self._m_tokens.inc(n_committed)
        self._m_spec_drafted.inc(n_drafted)
        self._m_spec_accepted.inc(n_accepted)
        self._m_spec_rollback.inc(n_rejected)
        if self.tracer is not None:
            self.tracer.complete("decode_step", t0, t_sync, track="host",
                                 n_active=len(plans),
                                 step=self.decode_steps, spec=True,
                                 committed=n_committed,
                                 accepted=n_accepted)

    def run(self):
        """Drive to completion; returns ``{rid: generated ids}``.
        Timing accumulates per :meth:`step` call, so ``stats()`` rates
        are identical however the loop is driven.  A raise on the way
        (from the step itself or the deadlock check) writes the flight
        record first when one is armed."""
        while self._queue or any(r is not None for r in self._slots):
            progressed = self.step()
            if not progressed and self._queue:
                exc = RuntimeError(
                    "serving deadlock: queued work but nothing active "
                    "— a request too large for the current pool")
                self._flight_dump(exc)
                raise exc
        return self.pop_results()

    def pop_results(self):
        """Take (and clear) the finished streams ``{rid: np.ndarray}``.
        The step-driven twin of :meth:`run`'s return — a caller that
        drives :meth:`step` itself (the serving front-end) collects
        completions here after each step instead of reading the private
        results dict."""
        out, self._results = self._results, {}
        return out

    # --------------------------------------------------- flight recorder

    def host_state(self, reconcile: bool = False) -> dict:
        """JSON-safe engine host state for the flight recorder.  HOST
        accounting only — no device sync (:meth:`occupancy` would block
        on a device that may be the thing that just wedged).

        ``reconcile=True`` additionally runs the pool's runtime
        reconciliation oracle (:func:`paddle_tpu.ops.paged_attention.
        paged_reconcile`) over the main pool — balanced against the
        prefix registry's pins — and the draft pool, under a
        ``"pool_reconcile"`` key.  That READS DEVICE ARRAYS (a sync),
        so it is opt-in and must never be requested from the crash-dump
        path; the telemetry selfcheck and the pool property tests are
        the intended callers."""
        state = self._host_state_base()
        if reconcile:
            pins = (None if self._prefix is None
                    else self._prefix.pin_counts(self.nb))
            problems = paged.paged_reconcile(self.cache, pins=pins)
            if self.spec is not None:
                problems += [f"draft: {p}" for p in
                             paged.paged_reconcile(self.dcache)]
            if self._apool is not None:
                # the adapter pool's oracle twin rides the same key so
                # one reconcile gate covers every refcounted pool
                problems += [f"adapter: {p}" for p in
                             self._adapters.reconcile()]
            state["pool_reconcile"] = {"ok": not problems,
                                       "problems": problems}
        return state

    def _host_state_base(self) -> dict:
        return {
            "slots": [None if r is None else {
                "rid": r.rid,
                "prompt_len": int(r.prompt.shape[0]),
                "tokens": len(r.tokens),
                "max_new": r.max_new,
                "submitted_at": r.submitted_at,
                "first_token_at": r.first_token_at,
            } for r in self._slots],
            "queue_depth": len(self._queue),
            "queued_rids": [r.rid for r in self._queue],
            "submit_queue": {
                "depth": len(self._queue),
                "max_queue": self.max_queue,
            },
            "blocks_reserved_worst_case": self._reserved,
            "prefix_pinned_blocks": self._pinned,
            "prefix_cache": (None if self._prefix is None
                             else self._prefix.stats()),
            "prefix_host_tier": (None if self._host_store is None else {
                "budget_bytes": self._host_store.max_bytes,
                "bytes": self._host_store.total_bytes,
                "entries": len(self._host_store),
            }),
            # the pool ledger in one place: everything the watchdog and
            # the frontend's router read, with no private attributes
            "ledger": {
                "reserved_blocks": self._reserved,
                "pinned_blocks": self._pinned,
                "shared_blocks": (0 if self._prefix is None
                                  else self._prefix.stats()
                                  ["shared_blocks"]),
                "pool_blocks": self.nb,
            },
            # heartbeat: when the last decode step ENDED (wall clock)
            # and how long it took — None before the first step
            "last_step_wall": self._last_step_wall,
            "last_step_seconds": self._last_step_seconds,
            "adapters": (None if self._apool is None else {
                **self._adapters.stats(),
                "rank": self.adapter_rank,
                "slot_map": [int(x) for x in self._adapter_slots],
            }),
            "pool_blocks": self.nb,
            "block_size": self.bs,
            "num_slots": self.S,
            "spec": (None if self.spec is None else {
                "k": self.spec_k,
                "draft_layers": self.draft.cfg.num_layers,
                "draft_pool_blocks": self._dnb,
                "draft_lengths": [None if v is None else int(v)
                                  for v in self._dlen],
            }),
            "compiles": self.compile_counts(),
            "decode_steps": self.decode_steps,
            "tokens_decoded": self.tokens_decoded,
            "retired": len(self._results),
        }

    def _flight_dump(self, exc: BaseException):
        """Write the crash dump once per exception object (``run()``
        re-raises what ``step()`` already dumped).  Never raises."""
        if self.tracer is None or self.tracer.flight_path is None:
            return
        if getattr(exc, "_ptpu_flight_dumped", False):
            return
        try:
            exc._ptpu_flight_dumped = True
        except Exception:
            pass                          # exotic exception: dump anyway
        try:
            state = self.host_state()
        except Exception:
            state = {"error": "host_state() itself raised"}
        self.tracer.dump_flight(
            reason=f"{type(exc).__name__}: {exc}", state=state)

    # ------------------------------------------------------- reporting

    def compile_counts(self):
        """Compiles since engine construction, via the shared
        :class:`~paddle_tpu.analysis.CompileWatcher` — the
        ``compiles == {'step': 1}`` serving contract's measuring
        stick."""
        return self._compile_watch.counts()

    def occupancy(self):
        """Actual pool usage (device truth) + host reservation."""
        free = int(np.asarray(self.cache.free).sum())
        return {"pool_blocks": self.nb,
                "blocks_in_use": self.nb - free,
                "blocks_reserved_worst_case": self._reserved,
                "blocks_pinned_prefix": self._pinned,
                "fraction_in_use": (self.nb - free) / self.nb}

    def hbm_report(self):
        """Cache-HBM accounting: paged bytes for the ACTIVE requests'
        actual lengths vs what the dense ``[S, max_len]`` cache would
        pin — the scaling the paged layout exists for.  Pool totals
        come from the REAL bytes-per-block (``self.block_bytes``, which
        counts the quantization scale tensors alongside the int8
        pages); the dense comparison stays at the compute dtype — a
        dense cache has no quantized form here, so comparing against
        it at kv bytes would overstate the paged win."""
        hd = self.cfg.dim // self.cfg.num_heads
        kv_bytes = self.kv_dtype.itemsize
        lens = [len(r.tokens) + r.prompt.shape[0]
                for r in self._slots if r is not None]
        L, h = self.cfg.num_layers, self.cfg.num_heads
        # scale rows: [num_blocks, num_heads] f32 per layer, K and V
        scale_bytes = (2 * L * h * 4 * self.nb
                       if self.cache.quantized else 0)
        return {
            "active_lengths": lens,
            "kv_dtype": self.kv_dtype.name,
            # bytes one block costs ON EACH CHIP (each holds its
            # num_heads/shards slice of every block); single device:
            # shards == 1 and per-shard == total, the legacy meaning
            "block_bytes": self.block_bytes,
            "shards": self.shards,
            "paged_bytes_per_request": paged_hbm_bytes(
                lens, block_size=self.bs, num_layers=L, num_heads=h,
                head_dim=hd, dtype_bytes=kv_bytes),
            "dense_bytes_per_request": dense_hbm_bytes(
                self.cfg.max_len, num_layers=L, num_heads=h,
                head_dim=hd,
                dtype_bytes=jnp.dtype(get_policy().compute_dtype)
                .itemsize),
            # per-shard vs mesh-total, stated separately so nothing
            # conflates them once pools shard (the selfcheck pins the
            # serving_kv_pool_bytes gauge == pool_bytes_total)
            "pool_bytes_per_shard": self.nb * self.block_bytes,
            "pool_bytes_total": (self.nb * self.block_bytes
                                 * self.shards),
            "kv_scale_bytes": scale_bytes,
            # blocks the prefix registry holds resident past their
            # donors (the HBM rent prefix sharing pays for its hits;
            # total across the mesh, like pool_bytes_total)
            "prefix_pinned_blocks": self._pinned,
            "prefix_pinned_bytes": (self._pinned * self.block_bytes
                                    * self.shards),
            # the host tier those pins demote into under pressure —
            # HOST bytes, deliberately outside every HBM total above
            "prefix_host_bytes": (0 if self._host_store is None
                                  else self._host_store.total_bytes),
            "prefix_host_budget_bytes": (
                0 if self._host_store is None
                else self._host_store.max_bytes),
            # the pooled LoRA buffers' rent: f32 A/B stacks for every
            # pool slot, resident for the engine's lifetime (replicated
            # across the mesh, so per-chip == total)
            "adapter_pool_bytes": (0 if self._apool is None
                                   else self._apool.pool_bytes()),
        }

    def stats(self):
        """Engine counters + rate + latency digests.  ``tokens_per_s``
        divides by per-``step()`` accumulated wall time (each step call
        ends on a host sync), so it is correct for callers that drive
        ``step()`` directly as well as for ``run()``.  The full metric
        series live in ``self.metrics.snapshot()``."""
        dt = max(self._run_seconds, 1e-9)
        spec_stats = None
        if self.spec is not None:
            spec_stats = {
                "k": self.spec_k,
                "accept_rate": self._m_spec_accept_rate.summary(),
                "tokens_per_step": self._m_spec_tps.summary(),
            }
        return {"decode_steps": self.decode_steps,
                "tokens_decoded": self.tokens_decoded,
                "run_seconds": self._run_seconds,
                "tokens_per_s": self.tokens_decoded / dt,
                "compiles": self.compile_counts(),
                "occupancy": self.occupancy(),
                "spec": spec_stats,
                "adapters": (None if self._apool is None
                             else self._adapters.stats()),
                "latency": {
                    "queue_wait_s": self._m_queue_wait.summary(),
                    "ttft_s": self._m_ttft.summary(),
                    "per_output_token_s": self._m_tpot.summary(),
                    "step_s": self._m_step.summary()}}
