"""Row-sparse (lazy) optimization for embedding tables.

Twin of the reference's sparse-parameter machinery:

* ``SparseRowCpuMatrix::sgdUpdate`` (``math/SparseRowMatrix.h:116``) —
  optimizer math applied only to the rows a batch touched;
* ``OptimizerWithRegularizerSparse`` (``parameter/OptimizerWithRegularizer.h:
  22-127``) — L1/L2 regularization applied *lazily*: each row catches up on
  the decay it missed since the last time it was touched (the reference's
  per-row ``t0`` vector);
* per-parameter optimizer routing (``ParameterOptimizer::create`` choosing
  sparse vs dense paths per ``ParameterConfig``), reproduced here as a
  ``partition`` combinator (one Transform per label).

TPU-native formulation: gradients stay dense ``[rows, dim]`` arrays (XLA's
scatter-add from the embedding backward keeps untouched rows exactly zero),
and "row touched" is a mask computed from the gradient — the *semantics*
are per-row-lazy while the *compute* is a dense masked update the TPU
vectorizes.  Numerics match the reference's lazy scheme exactly: untouched
rows carry NO optimizer-state evolution and NO weight decay until next
touched, then catch up.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from paddle_tpu.core.errors import enforce
from paddle_tpu.optim.transforms import Transform


def partition(transforms: Dict[str, Transform],
              label_fn: Callable[[str, Any], str]) -> Transform:
    """Route each parameter to one of several transforms by label
    (the per-parameter optimizer choice of ``ParameterOptimizer::create``).

    ``label_fn(path, leaf) -> label``; paths are '/'-joined names.  Each
    transform sees a sub-pytree holding only its params (others pruned), so
    its state mirrors exactly the params it owns.
    """
    labels = sorted(transforms)

    def _split(tree):
        """tree -> {label: subtree-with-only-that-label's-leaves}"""
        out: Dict[str, Any] = {lab: {} for lab in labels}

        def walk(node, path, outs):
            for k, v in node.items():
                p = f"{path}/{k}" if path else k
                if isinstance(v, dict):
                    subs = {lab: {} for lab in labels}
                    walk(v, p, subs)
                    for lab in labels:
                        if subs[lab]:
                            outs[lab][k] = subs[lab]
                else:
                    lab = label_fn(p, v)
                    enforce(lab in transforms,
                            "partition: label %r for param %s not in %s",
                            lab, p, labels)
                    outs[lab][k] = v

        walk(tree, "", out)
        return out

    def _merge(parts):
        out: Dict[str, Any] = {}
        for part in parts.values():
            def fold(dst, src):
                for k, v in src.items():
                    if isinstance(v, dict):
                        fold(dst.setdefault(k, {}), v)
                    else:
                        dst[k] = v
            fold(out, part)
        return out

    def init(params):
        split = _split(params)
        return {lab: transforms[lab].init(split[lab]) for lab in labels}

    def update(grads, state, params, step):
        gsplit = _split(grads)
        psplit = _split(params)
        new_updates = {}
        new_state = {}
        for lab in labels:
            upd, st = transforms[lab].update(gsplit[lab], state[lab],
                                             psplit[lab], step)
            new_updates[lab] = upd
            new_state[lab] = st
        return _merge(new_updates), new_state

    return Transform(init, update)


def sparse_rows(inner: Transform, l2: float = 0.0, l1: float = 0.0,
                shrink: float = 1.0, lr=None) -> Transform:
    """Row-lazy wrapper: apply ``inner`` + decay only to touched rows.

    Meant for a subtree of 2-D ``[rows, dim]`` tables (route it there with
    :func:`partition`).  A row is "touched" when its gradient row is
    non-zero.  Untouched rows keep their value AND their optimizer state
    frozen; when touched again they first catch up ``dt`` steps of decay:
    ``p *= (1 - eta*l2)**dt`` then soft-threshold by ``eta * l1 * dt``,
    where ``eta`` is the learning rate at catch-up time — matching the
    lr-scaled per-step decay dense params get from ``l1/l2_decay``
    (``OptimizerWithRegularizerSparse`` semantics with the reference's t0
    bookkeeping, ``Regularizer.cpp``).  ``lr`` is a float or
    ``schedules``-style callable of ``step``; default 1.0 (unscaled decay).
    ``shrink`` scales the whole decay (the ``shrinkRatio`` of
    CacheRowCpuMatrix-style setups).
    """

    def _lr_at(step):
        if lr is None:
            return 1.0
        return lr(step) if callable(lr) else lr

    def init(params):
        t0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros((p.shape[0],), jnp.int32), params)
        return {"inner": inner.init(params), "t0": t0}

    def _catch_up(p, touched, dt, eta):
        dtf = dt.astype(jnp.float32)[:, None]
        out = p
        if l2:
            out = out * jnp.power(1.0 - eta * l2 * shrink, dtf)
        if l1:
            thresh = eta * l1 * shrink * dtf
            out = jnp.sign(out) * jnp.maximum(jnp.abs(out) - thresh, 0.0)
        return jnp.where(touched[:, None], out, p)

    def update(grads, state, params, step):
        touched = jax.tree_util.tree_map(
            lambda g: jnp.any(g != 0, axis=tuple(range(1, g.ndim))), grads)
        # catch-up regularization on touched rows (dt steps missed);
        # expressed as an additive update (Transform contract).
        dt = jax.tree_util.tree_map(
            lambda t0: (step + 1 - t0).astype(jnp.int32), state["t0"])
        eta = _lr_at(step)
        reg_params = jax.tree_util.tree_map(
            lambda p, m, d: _catch_up(p, m, d, eta), params, touched, dt)

        upd, inner_state = inner.update(grads, state["inner"], reg_params,
                                        step)

        def mask_rows(u, m):
            return jnp.where(m.reshape((-1,) + (1,) * (u.ndim - 1)), u, 0.0)

        # final delta = (reg_params - params) + masked inner update
        deltas = jax.tree_util.tree_map(
            lambda rp, p, u, m: (rp - p) + mask_rows(u, m),
            reg_params, params, upd, touched)

        def _mirrors(slot):
            """Does this state slot mirror the params-tree structure?"""
            try:
                return (jax.tree_util.tree_structure(slot)
                        == jax.tree_util.tree_structure(touched))
            except Exception:
                return False

        # Freeze inner state on untouched rows.  State containers are
        # walked recursively (dict slots of per-optimizer buffers, tuple
        # states of chain()); any sub-slot that mirrors the params tree is
        # row-masked, scalar/global leaves (step counters, beta powers)
        # update normally.
        def freeze_leaf(new_s, old_s, m):
            if not hasattr(new_s, "ndim"):
                return new_s
            if new_s.ndim >= 1 and new_s.shape[:1] == m.shape:
                return jnp.where(
                    m.reshape((-1,) + (1,) * (new_s.ndim - 1)), new_s, old_s)
            return new_s

        def freeze_any(new_s, old_s):
            if _mirrors(new_s):
                return jax.tree_util.tree_map(freeze_leaf, new_s, old_s,
                                              touched)
            if isinstance(new_s, dict):
                return {k: freeze_any(new_s[k], old_s[k]) for k in new_s}
            if isinstance(new_s, (tuple, list)):
                return type(new_s)(freeze_any(a, b)
                                   for a, b in zip(new_s, old_s))
            return new_s

        new_inner = freeze_any(inner_state, state["inner"])

        new_t0 = jax.tree_util.tree_map(
            lambda t0, m: jnp.where(m, step + 1, t0), state["t0"], touched)
        return deltas, {"inner": new_inner, "t0": new_t0}

    return Transform(init, update)


def embedding_label_fn(patterns=("emb",), sparse_label="sparse",
                       dense_label="dense"):
    """label_fn for :func:`partition`: 2-D params whose path contains one
    of ``patterns`` go to the sparse transform."""

    def fn(path: str, leaf) -> str:
        if getattr(leaf, "ndim", 0) == 2 and any(s in path
                                                 for s in patterns):
            return sparse_label
        return dense_label

    return fn
