"""First-order optimizers.

Twins of ``paddle/parameter/FirstOrderOptimizer.h`` (SGD+momentum :24,
AdaGrad :111, AdaDelta :141, RMSProp :167, DecayedAdaGrad :210, Adam :255,
Adamax :286) and the vectorized apply kernels in
``paddle/math/TrainingAlgorithmOp.h:38-114``.  Update formulas follow the
reference exactly (epsilon placement, bias correction, rou/decay naming) so
`test_optimizers.py` can check them against independent reference
implementations the way ``test_TrainingAlgorithm.cpp`` checks against
``OriginalOptimizerApi.h``.

Each optimizer takes ``lr`` as a float or a schedule (step -> lr).
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.errors import ConfigError
from paddle_tpu.optim.transforms import Transform, _zeros_like

LR = Union[float, Callable]


def _lr_at(lr: LR, step):
    if callable(lr):
        return lr(step)
    return jnp.asarray(lr, jnp.float32)


def _tm(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def sgd(lr: LR) -> Transform:
    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        return _tm(lambda g: -eta * g, g), s
    return Transform(lambda p: (), update)


def momentum(lr: LR, mu: float = 0.9, nesterov: bool = False) -> Transform:
    """SGD with momentum (SgdOptimizer + momentum semantics,
    ``sgdUpdate`` in parameter/ParameterUpdateFunctions.cpp:
    v = mu*v - lr*g; p += v)."""
    def init(p):
        return {"v": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        v = _tm(lambda v, g: mu * v - eta * g, s["v"], g)
        if nesterov:
            upd = _tm(lambda v, g: mu * v - eta * g, v, g)
        else:
            upd = v
        return upd, {"v": v}
    return Transform(init, update)


def adagrad(lr: LR, epsilon: float = 1e-6) -> Transform:
    """AdaGrad (adagradApply, TrainingAlgorithmOp.h:54):
    accum += g^2; p -= lr * g / (sqrt(accum) + eps)."""
    def init(p):
        return {"accum": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        accum = _tm(lambda a, g: a + g * g, s["accum"], g)
        upd = _tm(lambda g, a: -eta * g / (jnp.sqrt(a) + epsilon), g, accum)
        return upd, {"accum": accum}
    return Transform(init, update)


def decayed_adagrad(lr: LR, rou: float = 0.95,
                    epsilon: float = 1e-6) -> Transform:
    """DecayedAdaGrad (decayedAdagradApply, TrainingAlgorithmOp.h:95):
    accum = rou*accum + (1-rou)*g^2."""
    def init(p):
        return {"accum": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        accum = _tm(lambda a, g: rou * a + (1 - rou) * g * g, s["accum"], g)
        upd = _tm(lambda g, a: -eta * g / (jnp.sqrt(a) + epsilon), g, accum)
        return upd, {"accum": accum}
    return Transform(init, update)


def adadelta(lr: LR = 1.0, rou: float = 0.95,
             epsilon: float = 1e-6) -> Transform:
    """AdaDelta (adadeltaApply, TrainingAlgorithmOp.h:38):
    E[g^2] = rou*E[g^2] + (1-rou)g^2;
    dx = -sqrt((E[dx^2]+eps)/(E[g^2]+eps)) * g;
    E[dx^2] = rou*E[dx^2] + (1-rou)dx^2; p += lr*dx."""
    def init(p):
        return {"accum_g": _zeros_like(p), "accum_dx": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        accum_g = _tm(lambda a, g: rou * a + (1 - rou) * g * g,
                      s["accum_g"], g)
        dx = _tm(lambda g, ag, adx: -jnp.sqrt((adx + epsilon)
                                              / (ag + epsilon)) * g,
                 g, accum_g, s["accum_dx"])
        accum_dx = _tm(lambda a, d: rou * a + (1 - rou) * d * d,
                       s["accum_dx"], dx)
        upd = _tm(lambda d: eta * d, dx)
        return upd, {"accum_g": accum_g, "accum_dx": accum_dx}
    return Transform(init, update)


def rmsprop(lr: LR, rou: float = 0.95, epsilon: float = 1e-6) -> Transform:
    """RMSProp with mean-centering (rmspropApply, TrainingAlgorithmOp.h:70 —
    the reference keeps E[g] too: denom = sqrt(E[g^2] - E[g]^2 + eps))."""
    def init(p):
        return {"accum_g2": _zeros_like(p), "accum_g": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        g2 = _tm(lambda a, g: rou * a + (1 - rou) * g * g, s["accum_g2"], g)
        g1 = _tm(lambda a, g: rou * a + (1 - rou) * g, s["accum_g"], g)
        upd = _tm(lambda g, a2, a1: -eta * g
                  / jnp.sqrt(a2 - a1 * a1 + epsilon), g, g2, g1)
        return upd, {"accum_g2": g2, "accum_g": g1}
    return Transform(init, update)


def adam(lr: LR, beta1: float = 0.9, beta2: float = 0.999,
         epsilon: float = 1e-8) -> Transform:
    """Adam (adamApply, TrainingAlgorithmOp.h:102, AdamOptimizer
    FirstOrderOptimizer.h:255) with bias correction."""
    def init(p):
        return {"m": _zeros_like(p), "v": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = _tm(lambda m, g: beta1 * m + (1 - beta1) * g, s["m"], g)
        v = _tm(lambda v, g: beta2 * v + (1 - beta2) * g * g, s["v"], g)
        correction = jnp.sqrt(1.0 - jnp.power(beta2, t)) \
            / (1.0 - jnp.power(beta1, t))
        upd = _tm(lambda m, v: -eta * correction * m
                  / (jnp.sqrt(v) + epsilon), m, v)
        return upd, {"m": m, "v": v}
    return Transform(init, update)


def adamax(lr: LR, beta1: float = 0.9, beta2: float = 0.999) -> Transform:
    """Adamax (adamaxApply, TrainingAlgorithmOp.h:110):
    u = max(beta2*u, |g|); p -= lr/(1-beta1^t) * m/u."""
    def init(p):
        return {"m": _zeros_like(p), "u": _zeros_like(p)}

    def update(g, s, p, step):
        eta = _lr_at(lr, step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = _tm(lambda m, g: beta1 * m + (1 - beta1) * g, s["m"], g)
        u = _tm(lambda u, g: jnp.maximum(beta2 * u, jnp.abs(g)), s["u"], g)
        upd = _tm(lambda m, u: -eta / (1.0 - jnp.power(beta1, t))
                  * m / jnp.maximum(u, 1e-12), m, u)
        return upd, {"m": m, "u": u}
    return Transform(init, update)


NAMED = {
    "sgd": sgd,
    "momentum": momentum,
    "adagrad": adagrad,
    "decayed_adagrad": decayed_adagrad,
    "adadelta": adadelta,
    "rmsprop": rmsprop,
    "adam": adam,
    "adamax": adamax,
}


def from_name(name: str, lr: LR, **kwargs) -> Transform:
    if name not in NAMED:
        raise ConfigError(f"Unknown optimizer {name!r}; "
                          f"available: {sorted(NAMED)}")
    return NAMED[name](lr, **kwargs)
