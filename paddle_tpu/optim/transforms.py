"""Gradient-transformation core.

TPU-native twin of the reference's optimizer separation
(``paddle/parameter/FirstOrderOptimizer.h``, ``ParameterOptimizer::create``
``ParameterOptimizer.cpp:28``, and the standalone C optimizer lib
``paddle/optimizer``): an optimizer is a pure ``(init, update)`` pair over
parameter pytrees with explicit, serializable state — the natural JAX
formulation (same shape as optax, implemented from scratch so state layout
and semantics exactly mirror the reference's per-parameter buffers,
``ParameterType`` momentum/accum slots ``utils/GlobalConstants.h:28-53``).

``update`` receives ``step`` (0-based batch counter) so learning-rate
schedules (``parameter/LearningRateScheduler.cpp``) stay pure functions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class Transform(NamedTuple):
    """(init, update) pair.

    init(params) -> state
    update(grads, state, params, step) -> (updates, new_state)

    ``updates`` are *deltas to add* to params: ``params + updates``.
    """
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any, jax.Array], Tuple[Any, Any]]


def apply_updates(params, updates):
    return jax.tree_util.tree_map(lambda p, u: (p + u).astype(p.dtype),
                                  params, updates)


def chain(*transforms: Transform) -> Transform:
    """Compose transforms left-to-right (clip -> regularize -> optimizer)."""

    def init(params):
        return tuple(t.init(params) for t in transforms)

    def update(grads, state, params, step):
        new_states = []
        for t, s in zip(transforms, state):
            grads, s = t.update(grads, s, params, step)
            new_states.append(s)
        return grads, tuple(new_states)

    return Transform(init, update)


def _zeros_like(params):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)


def scale(factor: float) -> Transform:
    return Transform(
        lambda params: (),
        lambda g, s, p, step: (jax.tree_util.tree_map(
            lambda x: x * factor, g), s))


def identity() -> Transform:
    return Transform(lambda params: (),
                     lambda g, s, p, step: (g, s))


def global_norm(tree) -> jax.Array:
    """Global L2 norm over every leaf, accumulated in f32 — the shared
    reduction behind gradient clipping
    (``regularizers.clip_by_global_norm``) and the training health
    statistics (``telemetry/health.py``)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))


def norm_tap() -> Transform:
    """Identity transform whose STATE is the global L2 norm of whatever
    flows through it — the update-ratio observation hook at the
    transform boundary.  Chain it last (``chain(..., optimizer,
    norm_tap())``) to capture ``norm(dw)`` of the final update deltas;
    the state rides the optimizer state tree, so it reaches the host
    with the step outputs, never via a callback."""
    def init(params):
        return jnp.float32(0.0)

    def update(g, s, p, step):
        return g, global_norm(g)

    return Transform(init, update)
