"""Gradient-transformation core.

TPU-native twin of the reference's optimizer separation
(``paddle/parameter/FirstOrderOptimizer.h``, ``ParameterOptimizer::create``
``ParameterOptimizer.cpp:28``, and the standalone C optimizer lib
``paddle/optimizer``): an optimizer is a pure ``(init, update)`` pair over
parameter pytrees with explicit, serializable state — the natural JAX
formulation (same shape as optax, implemented from scratch so state layout
and semantics exactly mirror the reference's per-parameter buffers,
``ParameterType`` momentum/accum slots ``utils/GlobalConstants.h:28-53``).

``update`` receives ``step`` (0-based batch counter) so learning-rate
schedules (``parameter/LearningRateScheduler.cpp``) stay pure functions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class Transform(NamedTuple):
    """(init, update) pair.

    init(params) -> state
    update(grads, state, params, step) -> (updates, new_state)

    ``updates`` are *deltas to add* to params: ``params + updates``.
    """
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any, jax.Array], Tuple[Any, Any]]


def apply_updates(params, updates):
    return jax.tree_util.tree_map(lambda p, u: (p + u).astype(p.dtype),
                                  params, updates)


def chain(*transforms: Transform) -> Transform:
    """Compose transforms left-to-right (clip -> regularize -> optimizer)."""

    def init(params):
        return tuple(t.init(params) for t in transforms)

    def update(grads, state, params, step):
        new_states = []
        for t, s in zip(transforms, state):
            grads, s = t.update(grads, s, params, step)
            new_states.append(s)
        return grads, tuple(new_states)

    return Transform(init, update)


def _zeros_like(params):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)


def scale(factor: float) -> Transform:
    return Transform(
        lambda params: (),
        lambda g, s, p, step: (jax.tree_util.tree_map(
            lambda x: x * factor, g), s))


def identity() -> Transform:
    return Transform(lambda params: (),
                     lambda g, s, p, step: (g, s))
