"""Learning-rate schedules.

Twin of ``paddle/parameter/LearningRateScheduler.cpp`` (registered names:
poly, constant, exp, discexp, linear, manual, pass_manual) and the C lib's
const/linear policies (``paddle/optimizer/lr_policy.h:18,41``).  A schedule
is a pure ``step -> multiplier-on-base-lr`` function of the 0-based batch
counter, usable inside jit.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant(lr: float) -> Schedule:
    return lambda step: jnp.asarray(lr, jnp.float32)


def poly(lr: float, decay_a: float, decay_b: float) -> Schedule:
    """v1 poly schedule: lr * (1 + a*t)^(-b) (LearningRateScheduler.cpp)."""
    def sched(step):
        return lr * jnp.power(1.0 + decay_a * step.astype(jnp.float32),
                              -decay_b)
    return sched


def exp_decay(lr: float, decay_a: float, decay_b: float) -> Schedule:
    """lr * a^(t/b) (exp schedule)."""
    def sched(step):
        return lr * jnp.power(decay_a, step.astype(jnp.float32) / decay_b)
    return sched


def discexp(lr: float, decay_a: float, decay_b: float) -> Schedule:
    """lr * a^floor(t/b) (discrete exponential)."""
    def sched(step):
        return lr * jnp.power(decay_a,
                              jnp.floor(step.astype(jnp.float32) / decay_b))
    return sched


def linear(lr: float, decay_a: float, decay_b: float) -> Schedule:
    """max(lr - a*t, b) (linear decay with floor)."""
    def sched(step):
        return jnp.maximum(lr - decay_a * step.astype(jnp.float32), decay_b)
    return sched


def manual(lr: float, segments: Sequence[Tuple[int, float]]) -> Schedule:
    """Piecewise-constant by step thresholds: [(boundary_step, lr), ...]
    (twin of the 'manual' schedule's seg=step_range:lr spec)."""
    boundaries = jnp.asarray([b for b, _ in segments], jnp.int32)
    values = jnp.asarray([lr] + [v for _, v in segments], jnp.float32)

    def sched(step):
        idx = jnp.sum(step >= boundaries)
        return values[idx]
    return sched


def warmup_cosine(lr: float, warmup_steps: int, total_steps: int,
                  final_scale: float = 0.0) -> Schedule:
    """Modern extension (not in reference): linear warmup + cosine decay."""
    def sched(step):
        stepf = step.astype(jnp.float32)
        warm = stepf / jnp.maximum(1.0, warmup_steps)
        progress = jnp.clip((stepf - warmup_steps)
                            / jnp.maximum(1.0, total_steps - warmup_steps),
                            0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        scale = final_scale + (1.0 - final_scale) * cos
        return lr * jnp.where(stepf < warmup_steps, warm, scale)
    return sched


NAMED = {
    "constant": constant,
    "poly": poly,
    "exp": exp_decay,
    "discexp": discexp,
    "linear": linear,
}


def from_config(name: str, lr: float, decay_a: float = 0.0,
                decay_b: float = 0.0) -> Schedule:
    from paddle_tpu.core.errors import ConfigError
    if name == "constant":
        return constant(lr)
    if name not in NAMED:
        raise ConfigError(f"Unknown LR schedule {name!r}; "
                          f"available: {sorted(NAMED)} + manual/warmup_cosine")
    return NAMED[name](lr, decay_a, decay_b)
