"""Optimizers as composable gradient transforms (the
ParameterOptimizer/TrainingAlgorithmOp twin: 8 v1 optimizers +
regularizers, clipping, LR schedules, averaging, sparse rows)."""
from paddle_tpu.optim.transforms import (Transform, apply_updates, chain,
                                         scale, identity, global_norm,
                                         norm_tap)
from paddle_tpu.optim.optimizers import (sgd, momentum, adagrad,
                                         decayed_adagrad, adadelta, rmsprop,
                                         adam, adamax, from_name)
from paddle_tpu.optim import schedules, regularizers, average, sparse
from paddle_tpu.optim.regularizers import (l1_decay, l2_decay, clip_by_value,
                                           clip_by_global_norm)
from paddle_tpu.core.config import OptimizationConfig
from paddle_tpu.core.errors import ConfigError


def from_config(config: OptimizationConfig) -> Transform:
    """Build the full update pipeline from an OptimizationConfig —
    twin of ParameterOptimizer::create + OptimizerWithRegularizer
    (``parameter/OptimizerWithRegularizer.h:22-127``): clip -> decay ->
    optimizer, with the configured LR schedule."""
    lr = schedules.from_config(config.learning_rate_schedule,
                               config.learning_rate,
                               config.learning_rate_decay_a,
                               config.learning_rate_decay_b)
    parts = []
    if config.gradient_clipping_threshold > 0:
        parts.append(clip_by_global_norm(config.gradient_clipping_threshold))
    if config.l1_rate > 0:
        parts.append(l1_decay(config.l1_rate))
    if config.l2_rate > 0:
        parts.append(l2_decay(config.l2_rate))
    kwargs = dict(config.extra)
    if config.learning_method == "momentum":
        kwargs.setdefault("mu", config.momentum)
    base = from_name(config.learning_method, lr, **kwargs)
    if config.sparse_update:
        # Embedding-like tables go row-lazy: decay catches up only when a
        # row is touched (lr-scaled, matching the dense l1/l2_decay
        # semantics), optimizer state frozen in between.  Gradient clipping
        # applies on both sides; the global-norm is per-partition, which
        # matches the reference's per-parameter clipping
        # (FirstOrderOptimizer.h:342) more closely than a whole-tree norm.
        dense = chain(*parts, base) if parts else base
        sparse_inner = (chain(clip_by_global_norm(
            config.gradient_clipping_threshold), base)
            if config.gradient_clipping_threshold > 0 else base)
        lazy = sparse.sparse_rows(sparse_inner, l2=config.l2_rate,
                                  l1=config.l1_rate, lr=lr)
        return sparse.partition(
            {"sparse": lazy, "dense": dense},
            sparse.embedding_label_fn(patterns=tuple(
                config.sparse_patterns)))
    parts.append(base)
    return chain(*parts) if len(parts) > 1 else parts[0]


__all__ = [
    "Transform", "apply_updates", "chain", "scale", "identity", "sgd",
    "momentum", "adagrad", "decayed_adagrad", "adadelta", "rmsprop", "adam",
    "adamax", "from_name", "from_config", "schedules", "regularizers",
    "average", "sparse", "l1_decay", "l2_decay", "clip_by_value",
    "clip_by_global_norm", "global_norm", "norm_tap",
]
