"""Parameter averaging.

Twin of ``paddle/parameter/AverageOptimizer.{h,cpp}`` (``average_window``
in OptimizationConfig): keeps a running average of parameter values
alongside training; evaluation/checkpoint can use the averaged weights
(``doApply``/``restore`` semantics).

Implemented as a stateful tracker driven from the train loop rather than a
gradient transform, since it observes post-update parameter values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init(params):
    return {"sum": jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, jnp.float32), params),
        "count": jnp.zeros((), jnp.float32)}


def accumulate(avg_state, params):
    return {
        "sum": jax.tree_util.tree_map(
            lambda s, p: s + p.astype(jnp.float32), avg_state["sum"], params),
        "count": avg_state["count"] + 1.0,
    }


def averaged_params(avg_state, params):
    """Return averaged weights (falling back to current if window empty)."""
    count = avg_state["count"]
    return jax.tree_util.tree_map(
        lambda s, p: jnp.where(count > 0,
                               (s / jnp.maximum(count, 1.0)).astype(p.dtype),
                               p),
        avg_state["sum"], params)
