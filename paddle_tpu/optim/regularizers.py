"""Weight decay / regularization and gradient clipping transforms.

Twins of ``paddle/parameter/Regularizer.{h,cpp}`` (L1/L2 decay applied at
update time, scaled by learning rate per the v1 semantics) and the gradient
clipping hook (``ParameterUpdaterHook.cpp`` pathes + clipping in
``FirstOrderOptimizer.h:342``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.optim.transforms import Transform, global_norm


def l2_decay(rate: float) -> Transform:
    """Add L2 gradient term g += rate * p (L2Regularizer)."""
    def update(g, s, p, step):
        new_g = jax.tree_util.tree_map(lambda g, p: g + rate * p, g, p)
        return new_g, s
    return Transform(lambda p: (), update)


def l1_decay(rate: float) -> Transform:
    """Add L1 subgradient g += rate * sign(p) (L1Regularizer)."""
    def update(g, s, p, step):
        new_g = jax.tree_util.tree_map(
            lambda g, p: g + rate * jnp.sign(p), g, p)
        return new_g, s
    return Transform(lambda p: (), update)


def clip_by_value(threshold: float) -> Transform:
    """Element-wise clip to [-t, t] (error_clipping_threshold semantics)."""
    def update(g, s, p, step):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), g), s
    return Transform(lambda p: (), update)


def clip_by_global_norm(threshold: float) -> Transform:
    """Scale all grads so the global L2 norm <= threshold
    (gradient_clipping_threshold, FirstOrderOptimizer.h:342)."""
    def update(g, s, p, step):
        norm = global_norm(g)
        scale = jnp.minimum(1.0, threshold / jnp.maximum(norm, 1e-12))
        return jax.tree_util.tree_map(lambda x: x * scale, g), s
    return Transform(lambda p: (), update)
