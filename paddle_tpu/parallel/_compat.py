"""shard_map across jax versions.

jax>=0.5 exports :func:`jax.shard_map` (replication checking spelled
``check_vma``); jax<0.5 ships it only as
``jax.experimental.shard_map.shard_map`` (spelled ``check_rep``).  The
callers here use the modern spelling; this adapter renames the kwarg
when falling back so the sharding programs stay version-portable.
"""

from __future__ import annotations

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, **kwargs)

__all__ = ["shard_map"]
