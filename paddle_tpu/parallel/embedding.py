"""Sharded embedding tables (the sparse-parameter-distribution twin).

The reference shards huge embedding rows across parameter servers and has
trainers prefetch the rows each batch needs (``SparseRowMatrix.h:204``
SparsePrefetchRowCpuMatrix, pserver ``getParameterSparse``
``ParameterServer2.cpp:572``, trainer prefetch ``TrainerInternal.cpp:93``).

TPU-native design: the table's ROW axis shards over a mesh axis; lookup
runs under ``shard_map`` — each device gathers the requested rows it owns
(out-of-range ids hit a zero row) and one ``psum`` over the axis assembles
full rows on every device.  The psum rides ICI and moves exactly
``batch × dim`` floats per device — the same traffic as the reference's
prefetch round-trip, with no server process.  The backward is the mirrored
scatter-add: each device keeps the gradient rows it owns (psum's transpose
is identity on the cotangent, and the local mask zeroes foreign rows), so
gradient memory stays sharded too.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from paddle_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.errors import enforce
from paddle_tpu.nn import initializers as init
from paddle_tpu.nn.module import Module, param


def sharded_lookup(table: jax.Array, ids: jax.Array, mesh: Mesh,
                   axis: str) -> jax.Array:
    """Gather rows of a row-sharded ``[vocab, dim]`` table.

    ``table`` must be sharded ``P(axis, None)`` (see :func:`table_sharding`);
    ``ids`` replicated.  Returns ``[*ids.shape, dim]`` replicated.
    """
    n_shards = mesh.shape[axis]
    vocab = table.shape[0]
    enforce(vocab % n_shards == 0,
            "vocab %d must divide by mesh axis %r size %d", vocab, axis,
            n_shards)
    rows_per = vocab // n_shards

    def local(table_shard, ids_):
        # Globally-OOV ids clamp to the last row first — the same
        # contract as dense nn.Embedding (mode="clip"), so swapping a
        # model to the sharded table cannot change OOV semantics.
        ids_ = jnp.clip(ids_, 0, vocab - 1)
        # Which of my rows does each id hit?  Foreign ids gather row 0 of
        # my shard and are masked to zero; the psum sums one real
        # contribution per id.
        idx = jax.lax.axis_index(axis)
        lo = idx * rows_per
        local_ids = ids_ - lo
        mine = (local_ids >= 0) & (local_ids < rows_per)
        safe = jnp.clip(local_ids, 0, rows_per - 1)
        rows = jnp.take(table_shard, safe, axis=0)
        rows = jnp.where(mine[..., None], rows, 0)
        return jax.lax.psum(rows, axis)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P())(table, ids)


def table_sharding(mesh: Mesh, axis: str) -> NamedSharding:
    """Row-sharded layout for an embedding table."""
    return NamedSharding(mesh, P(axis, None))


class ShardedEmbedding(Module):
    """Embedding whose table rows shard over ``axis``
    (SparsePrefetchRowCpuMatrix + pserver distribution twin).

    Use ``paddle_tpu.parallel.sharding.apply_rules`` (or ``jax.device_put``
    with :func:`table_sharding`) to place the created table; the lookup is
    layout-correct either way — ``shard_map`` re-shards as declared.
    """

    def __init__(self, vocab_size: int, dim: int, mesh: Mesh, axis: str,
                 w_init=None, name: Optional[str] = None):
        super().__init__(name)
        self.vocab_size = vocab_size
        self.dim = dim
        self.mesh = mesh
        self.axis = axis
        self.w_init = w_init or init.normal(0.01)

    def forward(self, ids):
        table = param("w", (self.vocab_size, self.dim), jnp.float32,
                      self.w_init)
        return sharded_lookup(table, ids, self.mesh, self.axis)


def embedding_rules(axis: str, patterns=("emb",)):
    """Sharding rules routing embedding tables' row axis onto ``axis``
    (for ``sharding.apply_rules``)."""
    return [(p, P(axis, None)) for p in patterns]
