"""Mixture-of-Experts with expert parallelism (``ep`` mesh axis).

The reference's closest ancestor is sparse-parameter distribution — rows of
huge embeddings living on parameter-server shards with per-batch prefetch
(``SparseRowMatrix.h:204``, ``ParameterServer2.cpp:572``).  The TPU-native
generalization: expert weights shard over an ``ep`` mesh axis, tokens are
routed top-k and dispatched with capacity-bounded einsums, and XLA turns the
token shuffle into all-to-all over ICI.

Static-shape design (GShard-style): capacity ``C = ceil(T * cf * k / E)``
per expert; overflowing tokens drop (their combine weight is zero), keeping
every shape compile-time constant.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_policy
from paddle_tpu.nn import initializers as init
from paddle_tpu.nn.module import Module, param, add_aux_loss
from paddle_tpu.ops import activations


def top_k_routing(gate_logits: jax.Array, k: int, capacity: int):
    """Top-k token→expert routing with capacity.

    gate_logits: [T, E].  Returns (dispatch [T, E, C] bool-ish float,
    combine [T, E, C] float, aux_loss scalar).
    """
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, k)          # [T, k]

    # Load-balancing aux loss (GShard eq.4): E * mean(frac_tokens * mean_prob)
    top1 = topk_idx[:, 0]
    frac = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # Position of each (token, choice) in its expert's buffer: running count
    # of prior tokens routed to the same expert, across choices in priority
    # order (choice 0 of all tokens first — GShard's priority rule).
    fill = jnp.zeros((e,), jnp.int32)
    for choice in range(k):
        idx = topk_idx[:, choice]                            # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)     # [T, E]
        pos_within = jnp.cumsum(onehot, axis=0) - onehot     # prior same-expert
        pos = jnp.sum(pos_within * onehot, axis=1) + fill[idx]
        keep = pos < capacity
        gate = topk_probs[:, choice] * keep
        disp_hot = (jax.nn.one_hot(idx, e, dtype=jnp.float32)[..., None] *
                    jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                                   dtype=jnp.float32)[:, None, :])
        disp_hot = disp_hot * keep[:, None, None]
        dispatch = dispatch + disp_hot
        combine = combine + disp_hot * gate[:, None, None]
        fill = fill + jnp.sum(onehot, axis=0)
    return dispatch, combine, aux


class MoEMLP(Module):
    """Top-k routed expert FFN (dispatch/combine einsums, GShard layout).

    Expert weights carry a leading ``[E, ...]`` axis — shard it over ``ep``
    via ``sharding.moe_ep_rules()`` and XLA inserts the all-to-all.
    """

    def __init__(self, dim: int, hidden: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 2.0,
                 act="gelu", aux_loss_weight: float = 0.01,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.hidden = dim, hidden
        self.num_experts, self.top_k = num_experts, top_k
        self.capacity_factor = capacity_factor
        self.act = activations.get(act)
        self.aux_loss_weight = aux_loss_weight

    def forward(self, x):
        policy = get_policy()
        orig_shape = x.shape
        d = orig_shape[-1]
        tokens = x.reshape(-1, d)                            # [T, d]
        t = tokens.shape[0]
        e, k = self.num_experts, self.top_k
        capacity = max(1, math.ceil(t * self.capacity_factor * k / e))

        w_gate = param("w_gate", (d, e), policy.param_dtype,
                       init.xavier_uniform())
        gate_logits = tokens.astype(jnp.float32) @ w_gate.astype(jnp.float32)
        dispatch, combine, aux = top_k_routing(gate_logits, k, capacity)
        add_aux_loss(self.aux_loss_weight * aux)

        w_in = param("w_in", (e, d, self.hidden), policy.param_dtype,
                     init.xavier_uniform(fan_in=d, fan_out=self.hidden))
        b_in = param("b_in", (e, self.hidden), policy.param_dtype, init.zeros)
        w_out = param("w_out", (e, self.hidden, d), policy.param_dtype,
                      init.xavier_uniform(fan_in=self.hidden, fan_out=d))
        b_out = param("b_out", (e, d), policy.param_dtype, init.zeros)

        ct = policy.cast_to_compute
        # dispatch: [T,E,C] × tokens [T,d] → expert inputs [E,C,d]
        expert_in = jnp.einsum("tec,td->ecd", ct(dispatch), ct(tokens))
        h = jnp.einsum("ecd,edh->ech", expert_in, ct(w_in)) + ct(b_in)[:, None]
        h = self.act(h)
        expert_out = jnp.einsum("ech,ehd->ecd", h, ct(w_out)) \
            + ct(b_out)[:, None]
        out = jnp.einsum("tec,ecd->td", ct(combine), expert_out)
        return policy.cast_to_output(out).reshape(orig_shape)


def moe_ep_rules(axis: str = "ep"):
    """Sharding rules putting the expert axis of MoE weights on ``axis``."""
    from jax.sharding import PartitionSpec as P
    return (
        (r"moe/w_in$", P(axis, None, None)),
        (r"moe/b_in$", P(axis, None)),
        (r"moe/w_out$", P(axis, None, None)),
        (r"moe/b_out$", P(axis, None)),
    )
