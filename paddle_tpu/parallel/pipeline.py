"""Pipeline parallelism over a ``pp`` mesh axis (GPipe-style microbatching).

New capability vs the reference (SURVEY.md §2.4.6 — the reference's
"pipelining" is only per-parameter update overlap,
``TrainerInternal.cpp:69-73``).  TPU-idiomatic design: the model's repeated
trunk is S identical stages whose parameters carry a leading ``[S, ...]``
axis sharded over ``pp``; inside ``shard_map`` every device runs the same
tick loop, activations hop stage→stage via ``ppermute`` (one ICI hop per
tick), and a ``lax.scan`` over ``M + S - 1`` ticks drains M microbatches
through the pipe.  Reverse-mode AD through the scan+ppermute produces the
backward pipeline schedule automatically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(stage_param_trees):
    """Stack per-stage param trees into one tree with a leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *stage_param_trees)


def pipeline_apply(stage_fn: Callable, mesh: Mesh, axis: str = "pp"):
    """Build ``run(stacked_params, microbatches) -> outputs``.

    ``stage_fn(stage_params, x) -> y`` with ``y.shape == x.shape`` (a
    residual-block trunk).  ``stacked_params`` leaves are ``[S, ...]`` and
    should be sharded ``P(axis)``; ``microbatches`` is ``[M, mb, ...]``
    (replicated).  Output is ``[M, mb, ...]`` replicated.
    """
    n_stages = mesh.shape[axis]
    shift = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def run(stacked_params, xs):
        from paddle_tpu.core.errors import enforce
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            enforce(leaf.shape[0] == n_stages,
                    "stacked stage axis %d != pp mesh axis size %d",
                    leaf.shape[0], n_stages)

        def local(params_blk, xs_full):
            my_params = jax.tree_util.tree_map(lambda a: a[0], params_blk)
            s = lax.axis_index(axis)
            m = xs_full.shape[0]
            ticks = m + n_stages - 1

            state = jnp.zeros_like(xs_full[0])
            outputs = jnp.zeros_like(xs_full)

            def tick(carry, t):
                state, outputs = carry
                x_t = xs_full[jnp.clip(t, 0, m - 1)]
                inp = jnp.where(s == 0, x_t, state)
                out = stage_fn(my_params, inp)
                widx = t - (n_stages - 1)
                do_write = (s == n_stages - 1) & (widx >= 0)
                upd = lax.dynamic_update_index_in_dim(
                    outputs, out, jnp.clip(widx, 0, m - 1), 0)
                outputs = jnp.where(do_write, upd, outputs)
                state = lax.ppermute(out, axis, shift)
                return (state, outputs), None

            (_, outputs), _ = lax.scan(tick, (state, outputs),
                                       jnp.arange(ticks))
            # Result lives on the last stage; broadcast over the ring.
            outputs = jnp.where(s == n_stages - 1, outputs, 0)
            return lax.psum(outputs, axis)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, xs)

    return run
