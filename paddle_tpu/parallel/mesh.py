"""Device-mesh utilities.

TPU-native replacement for the reference's distribution machinery: where
the reference splits batches over ``trainer_count`` worker threads with a
ring gather/scatter (``MultiGradientMachine.h:44-95``) and syncs multi-node
gradients through a sharded parameter server (``ParameterServer2``), the TPU
build declares a ``jax.sharding.Mesh`` over the chips and lets XLA compile
the collectives onto ICI (SURVEY.md §2.4).

Axis conventions:
  * ``dp`` — data parallelism (batch split; grad psum) — replaces
    MultiGradientMachine + sync RemoteParameterUpdater
  * ``mp`` — tensor/model parallelism (weight sharding) — extends
    ParallelNeuralNetwork's per-layer device placement
  * ``sp`` — sequence/context parallelism (long-sequence sharding)
  * ``pp`` — pipeline stages (new capability, absent in reference)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.errors import enforce

DP, MP, PP, SP = "dp", "mp", "pp", "sp"


def make_mesh(shape: Optional[Sequence[int]] = None,
              axes: Optional[Sequence[str]] = None,
              devices=None) -> Mesh:
    """Create a Mesh.  Default: all devices on one ``dp`` axis."""
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
        axes = axes or (DP,)
    axes = tuple(axes or (DP, MP, PP, SP)[:len(shape)])
    enforce(int(np.prod(shape)) == len(devices),
            "mesh shape %s does not cover %d devices", shape, len(devices))
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axes)


def batch_sharding(mesh: Mesh, axis: str = DP) -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, axis: str = DP, spec: Optional[P] = None,
                stacked: bool = False):
    """Device-put a pytree of host arrays with batch sharding.

    Default: leading (batch) dim over ``axis``.  ``spec`` overrides with
    an arbitrary PartitionSpec (e.g. ``P(None, "sp")`` for
    sequence-sharded ring-attention batches).  ``stacked=True`` prepends
    an unsharded leading dim for a ``[k, batch, ...]`` batch STACK — the
    scan axis stays whole on every device while each scanned batch keeps
    the same layout the per-dispatch path would see."""
    if spec is None:
        spec = P(axis)
    if stacked:
        spec = P(None, *spec)
    sharding = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def replicate(tree, mesh: Mesh):
    sharding = replicated(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), tree)
