"""ZeRO-1: optimizer-state sharding over the data-parallel axis.

New capability vs the reference (SURVEY.md §2.4.6).  Where the reference
kept full optimizer state on every trainer (or centralised it on parameter
servers), the TPU build lays each state tensor out sharded over ``dp``:
under jit, the XLA SPMD partitioner then compiles the gradient sum as
reduce-scatter into the shard, runs the optimizer math on 1/N of the state,
and all-gathers the updated parameters — the classic ZeRO-1 schedule, derived
entirely from sharding annotations.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _spec_for(x, axis_size: int, axis: str):
    shape = getattr(x, "shape", ())
    for dim, extent in enumerate(shape):
        if extent % axis_size == 0 and extent >= axis_size:
            return P(*([None] * dim + [axis]))
    return P()


def shard_opt_state(opt_state, mesh: Mesh, axis: str = "dp"):
    """device_put every state leaf sharded over ``axis`` (first divisible
    dim; replicated if none divides evenly)."""
    size = mesh.shape[axis]

    def put(x):
        return jax.device_put(x, NamedSharding(mesh, _spec_for(x, size, axis)))

    return jax.tree_util.tree_map(put, opt_state)
