"""Ring attention: sequence/context parallelism over an ``sp`` mesh axis.

Absent from the reference (its long-sequence story is padding-free batching,
``SURVEY.md §5``); first-class here because long context shapes the core
design.  The sequence axis of q/k/v shards over ``sp``; each device holds one
query block and the KV blocks rotate around the ring via ``ppermute`` (one
ICI hop per step), merged with flash-attention log-sum-exp accumulation
(``ops.attention.blockwise_attn_chunk``) so the result is *exactly* softmax
attention over the full sequence while no device ever materialises more than
one KV block.

Differentiable end-to-end: reverse-mode AD through ``shard_map``+``ppermute``
+``scan`` yields the reverse ring automatically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.ops.attention import (
    attn_bias, blockwise_attn_chunk, blockwise_finalize, blockwise_init_carry)


def ring_attention(mesh: Mesh, axis: str = "sp"):
    """Returns ``attn_fn(q, k, v, mask=None, causal=False)`` for BTHD tensors
    whose time axis is sharded over ``axis``.  Drop-in for
    ``MultiHeadAttention(attn_fn=...)``.
    """
    n = mesh.shape[axis]
    fwd_perm = [(j, (j + 1) % n) for j in range(n)]

    def attn_fn(q, k, v, mask=None, causal=False):
        has_mask = mask is not None

        def local(q_blk, k_blk, v_blk, mask_blk):
            # q_blk: [b, t_blk, h, d] — this device's query block.
            b, t_blk, h, d = q_blk.shape
            my_idx = lax.axis_index(axis)
            carry = blockwise_init_carry(b, t_blk, h, d)

            def step(acc, ring_step):
                carry, kb, vb, mb = acc
                kv_idx = (my_idx - ring_step) % n
                bias = attn_bias(mb if has_mask else None, causal,
                                 t_blk, t_blk, q_offset=my_idx * t_blk,
                                 k_offset=kv_idx * t_blk)
                carry = blockwise_attn_chunk(q_blk, kb, vb, bias, carry)
                kb = lax.ppermute(kb, axis, fwd_perm)
                vb = lax.ppermute(vb, axis, fwd_perm)
                if has_mask:
                    mb = lax.ppermute(mb, axis, fwd_perm)
                return (carry, kb, vb, mb), None

            (carry, _, _, _), _ = lax.scan(
                step, (carry, k_blk, v_blk, mask_blk), jnp.arange(n))
            return blockwise_finalize(carry).astype(q_blk.dtype)

        qkv_spec = P(None, axis, None, None)
        mask_spec = P(None, axis)
        if not has_mask:
            # feed a dummy all-true mask so the shard_map signature is static
            mask = jnp.ones(q.shape[:2], bool)
        return shard_map(
            local, mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )(q, k, v, mask)

    return attn_fn
