"""Mesh/sharding toolkit: dp/tp/pp/sp/ep rules, ZeRO-1, ring
attention, MoE expert dispatch, row-sharded embeddings (the
multi-machine twin — ICI/DCN collectives replace the pserver).
Mesh layouts built here are statically checkable: give the entrypoint
a ``paddle_tpu.analysis.ShardRecipe`` and ``tpu-lint`` lowers it under
a real CPU mesh, rejects collectives inside decode loops, and budgets
its per-shard HBM footprint (``docs/design/analysis.md``)."""
from paddle_tpu.parallel.mesh import (make_mesh, batch_sharding, replicated,
                                      shard_batch, replicate, DP, MP, PP, SP)
from paddle_tpu.parallel import sharding
from paddle_tpu.parallel import zero
from paddle_tpu.parallel.ring_attention import ring_attention
from paddle_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from paddle_tpu.parallel.expert import MoEMLP, moe_ep_rules
from paddle_tpu.parallel.embedding import (ShardedEmbedding, sharded_lookup,
                                           table_sharding, embedding_rules)

__all__ = ["make_mesh", "batch_sharding", "replicated", "shard_batch",
           "replicate", "sharding", "zero", "ring_attention",
           "pipeline_apply", "stack_stage_params", "MoEMLP", "moe_ep_rules",
           "ShardedEmbedding", "sharded_lookup", "table_sharding",
           "embedding_rules",
           "DP", "MP", "PP", "SP"]
