"""Mesh/sharding toolkit: dp/tp/pp/sp/ep rules, ZeRO-1, ring
attention, MoE expert dispatch, row-sharded embeddings (the
multi-machine twin — ICI/DCN collectives replace the pserver)."""
from paddle_tpu.parallel.mesh import (make_mesh, batch_sharding, replicated,
                                      shard_batch, replicate, DP, MP, PP, SP)
from paddle_tpu.parallel import sharding
from paddle_tpu.parallel import zero
from paddle_tpu.parallel.ring_attention import ring_attention
from paddle_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from paddle_tpu.parallel.expert import MoEMLP, moe_ep_rules
from paddle_tpu.parallel.embedding import (ShardedEmbedding, sharded_lookup,
                                           table_sharding, embedding_rules)

__all__ = ["make_mesh", "batch_sharding", "replicated", "shard_batch",
           "replicate", "sharding", "zero", "ring_attention",
           "pipeline_apply", "stack_stage_params", "MoEMLP", "moe_ep_rules",
           "ShardedEmbedding", "sharded_lookup", "table_sharding",
           "embedding_rules",
           "DP", "MP", "PP", "SP"]
