from paddle_tpu.parallel.mesh import (make_mesh, batch_sharding, replicated,
                                      shard_batch, replicate, DP, MP, PP, SP)
from paddle_tpu.parallel import sharding

__all__ = ["make_mesh", "batch_sharding", "replicated", "shard_batch",
           "replicate", "sharding", "DP", "MP", "PP", "SP"]
