"""Parameter-sharding rules.

TPU-native generalization of the reference's model parallelism
(``ParallelNeuralNetwork`` per-layer ``device`` placement,
``ParallelNeuralNetwork.h:34``, ``Layer.h:69``): instead of pinning whole
layers to devices, parameters are *sharded* across the ``mp`` mesh axis by
name-pattern rules, and XLA inserts the tensor-parallel collectives.  Rules
are ``(regex-on-param-path, PartitionSpec)`` pairs, first match wins,
default replicated.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.nn.module import flatten_names, unflatten_names

Rules = Sequence[Tuple[str, P]]


def spec_axes(spec: Optional[P]) -> frozenset:
    """Mesh-axis names a PartitionSpec actually uses (nested tuple
    entries flattened; ``None`` dims skipped).  Empty set == fully
    replicated.  One home for this so the linter
    (``analysis/shard_rules.py``) and the runtime sharding helpers
    cannot disagree about what 'replicated' means."""
    names = set()
    for entry in (spec or ()):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            names.update(e for e in entry if e is not None)
        else:
            names.add(entry)
    return frozenset(names)


def apply_rules(params, mesh: Mesh, rules: Optional[Rules]):
    """device_put each param with its matched sharding (replicated default)."""
    flat = flatten_names(params)
    out = {}
    for name, value in flat.items():
        spec = P()
        for pattern, candidate in (rules or ()):
            if re.search(pattern, name):
                spec = candidate
                break
        out[name] = jax.device_put(value, NamedSharding(mesh, spec))
    return unflatten_names(out)


def shardings_like(params, mesh: Mesh, rules: Optional[Rules]):
    """NamedSharding pytree for params (for jit out_shardings/donation)."""
    flat = flatten_names(params)
    out = {}
    for name in flat:
        spec = P()
        for pattern, candidate in (rules or ()):
            if re.search(pattern, name):
                spec = candidate
                break
        out[name] = NamedSharding(mesh, spec)
    return unflatten_names(out)


def paged_cache_shardings(cache, mesh: Mesh, axis: str = "mp"):
    """NamedSharding pytree for a ``PagedKVCache`` under head-axis mesh
    sharding — the multi-chip serving layout (``docs/design/serving.md``
    "multi-chip serving"): K/V block pools shard on their head axis
    (``[nb, bs, h, hd]`` → ``P(None, None, axis)``), the int8
    per-block-per-head scales follow (``[nb, h]`` → ``P(None, axis)``),
    and every bookkeeping leaf — block tables, lengths, blocks_used,
    refcounts — stays REPLICATED so the allocator partitions
    collective-free.  Duck-typed over the cache's NamedTuple fields so
    this module never imports ``ops.paged_attention``.

    Used by ``PagedServingEngine`` for initial cache placement/
    donation pinning and by the sharded ``paged-engine-step-*`` lint
    recipes as a callable arg_spec."""
    # no trailing None: jit keys programs on the spec VERBATIM, and
    # compiled outputs come back as P(None, None, axis) — a trailing
    # None here would force a spurious recompile on the first
    # post-step prefill
    pool = NamedSharding(mesh, P(None, None, axis))
    scale = NamedSharding(mesh, P(None, axis))
    rep = NamedSharding(mesh, P())
    return type(cache)(
        k_pages=tuple(pool for _ in cache.k_pages),
        v_pages=tuple(pool for _ in cache.v_pages),
        block_tables=rep, lengths=rep, blocks_used=rep,
        refcounts=rep,
        k_scales=tuple(scale for _ in cache.k_scales),
        v_scales=tuple(scale for _ in cache.v_scales))


def lstm_tp_rules(axis: str = "mp") -> Rules:
    """Tensor-parallel layout for the LSTM stack: gate projections shard on
    the 4h output dim, embeddings on vocab rows, the readout on classes.

    Under these rules construct the LSTM layers with ``use_pallas=False``:
    GSPMD cannot partition the fused Pallas recurrence over ``axis``, so the
    XLA scan (which shards cleanly) is the right schedule."""
    return (
        (r"lstm_\d+/w_x$", P(None, axis)),
        (r"lstm_\d+/w_h$", P(None, axis)),
        (r"lstm_\d+/b$", P(axis)),
        (r"embed/w$", P(axis, None)),
        (r"fc/w$", P(None, axis)),
    )


def mlp_tp_rules(axis: str = "mp") -> Rules:
    """Megatron-style column/row split for alternating linear layers."""
    return (
        (r"linear_0/w$", P(None, axis)),
        (r"linear_1/w$", P(axis, None)),
    )


def pipeline_pp_rules(axis: str = "pp") -> Rules:
    """Stage-stacked trunk params ([S, ...] leading axis) shard one stage
    per ``pp`` device; everything else (embedding, readout) replicates.
    Pairs with ``models.transformer.pipelined_mlp_lm_builder``."""
    return ((r"(^|/)stage_", P(axis)),)


def transformer_tp_rules(axis: str = "mp") -> Rules:
    """Megatron layout for TransformerLM: q/k/v column-split (heads shard),
    attention output row-split; FFN in column-split, out row-split; embedding
    and readout vocab-split."""
    return (
        (r"attn/w_[qkv]$", P(None, axis)),
        (r"attn/w_o$", P(axis, None)),
        (r"ffn/in/w$", P(None, axis)),
        (r"ffn/in/b$", P(axis)),
        (r"ffn/out/w$", P(axis, None)),
        (r"embed/w$", P(axis, None)),
        # vocab readout only — MoE expert w_out belongs to moe_ep_rules
        (r"(?<!moe/)w_out$", P(None, axis)),
    )
