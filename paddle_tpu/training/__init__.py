from paddle_tpu.training.trainer import Trainer
from paddle_tpu.training import events, evaluators, checkpoint
from paddle_tpu.training.evaluators import (Evaluator, ClassificationError,
                                            ValueSum, PrecisionRecall, AUC,
                                            ChunkEvaluator, iob_decode)

__all__ = ["Trainer", "events", "evaluators", "checkpoint", "Evaluator",
           "ClassificationError", "ValueSum", "PrecisionRecall", "AUC",
           "ChunkEvaluator", "iob_decode"]
