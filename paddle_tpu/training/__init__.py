"""Trainer, evaluators, events, checkpointing, aux hygiene (the
ref:paddle/trainer twin)."""
from paddle_tpu.training.trainer import Trainer
from paddle_tpu.training import (events, evaluators, checkpoint,
                                 checkpoint_sharded, aux)
from paddle_tpu.training.aux import (parameter_stats,
                                     format_parameter_stats,
                                     enable_fp_checks, PreemptionHandler)
from paddle_tpu.training.evaluators import (Evaluator, ClassificationError,
                                            ValueSum, PrecisionRecall, AUC,
                                            ChunkEvaluator, iob_decode)

__all__ = ["Trainer", "events", "evaluators", "checkpoint",
           "checkpoint_sharded", "aux",
           "parameter_stats", "format_parameter_stats", "enable_fp_checks",
           "PreemptionHandler", "Evaluator",
           "ClassificationError", "ValueSum", "PrecisionRecall", "AUC",
           "ChunkEvaluator", "iob_decode"]
