"""Checkpoint save/restore.

Twin of the reference's three checkpoint paths (SURVEY.md §5): v1 per-pass
parameter dirs (``trainer/ParamUtil.h:58-111``), v2 ``Parameters.to_tar``
(``v2/parameters.py:324``) and the Go pserver's checkpoint+meta
(``go/pserver/service.go:272``).  One canonical format here:

``<dir>/pass-NNNNN/`` containing
  * ``arrays.npz``   — every leaf of every tree, flat-named ``tree:a/b/c``
  * ``meta.json``    — step counters, data cursor, user metadata, md5 of the
                       npz (the Go path's integrity check)

plus a ``latest`` symlink-style marker file.  Multi-host sharded arrays
should be saved via orbax instead; this format covers the single-host /
replicated case and is the interchange format of the merge/export tool.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import tempfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.core.errors import enforce
from paddle_tpu.nn.module import (escape_name, flatten_names,
                                  unescape_name, unflatten_names)


def _flatten_trees(trees: Dict[str, Any]) -> Dict[str, np.ndarray]:
    flat: Dict[str, np.ndarray] = {}
    for tree_name, tree in trees.items():
        if tree is None:
            continue
        for k, v in flatten_names(_to_plain(tree)).items():
            flat[f"{tree_name}:{k}"] = np.asarray(v)
    return flat


# Empty containers must survive the flatten/unflatten round-trip: a chained
# optimizer's state is a tuple like ((), {"v": ...}) and dropping the empty
# slot would silently misalign transforms with their state after restore.
_EMPTY_DICT = "__empty_dict__"
_EMPTY_TUPLE = "__empty_tuple__"


def _to_plain(tree):
    """Convert tuples in optimizer-state pytrees to indexed dicts."""
    if isinstance(tree, dict):
        if not tree:
            return {_EMPTY_DICT: np.zeros(0, np.int8)}
        return {str(k): _to_plain(v) for k, v in tree.items()}
    if isinstance(tree, (tuple, list)):
        if not tree:
            return {_EMPTY_TUPLE: np.zeros(0, np.int8)}
        return {f"#{i}": _to_plain(v) for i, v in enumerate(tree)}
    return tree


def _from_plain(tree):
    if isinstance(tree, dict):
        keys = list(tree.keys())
        if keys == [_EMPTY_DICT]:
            return {}
        if keys == [_EMPTY_TUPLE]:
            return ()
        if keys and all(k.startswith("#") for k in keys):
            items = sorted(tree.items(), key=lambda kv: int(kv[0][1:]))
            return tuple(_from_plain(v) for _, v in items)
        return {k: _from_plain(v) for k, v in tree.items()}
    return tree


def save(directory: str, pass_id: int, trees: Dict[str, Any],
         metadata: Optional[Dict[str, Any]] = None) -> str:
    """Save trees (e.g. {"params":…, "state":…, "opt":…}) for a pass."""
    pass_dir = os.path.join(directory, f"pass-{pass_id:05d}")
    os.makedirs(pass_dir, exist_ok=True)
    flat = _flatten_trees(trees)
    npz_path = os.path.join(pass_dir, "arrays.npz")
    # atomic-ish write: temp file then rename (pserver checkpoint pattern)
    # suffix must end in .npz or np.savez silently writes to <tmp>.npz
    fd, tmp = tempfile.mkstemp(dir=pass_dir, suffix=".tmp.npz")
    os.close(fd)
    np.savez(tmp, **flat)
    os.replace(tmp, npz_path)
    with open(npz_path, "rb") as f:
        md5 = hashlib.md5(f.read()).hexdigest()
    meta = {
        "pass_id": pass_id,
        "tree_names": sorted({k.split(":", 1)[0] for k in flat}),
        "md5": md5,
        "metadata": metadata or {},
    }
    with open(os.path.join(pass_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    with open(os.path.join(directory, "latest"), "w") as f:
        f.write(f"pass-{pass_id:05d}")
    return pass_dir


# Reference v1 trained-model artifact: ``pass-%05d/`` holding one binary
# file PER PARAMETER, named by parameter name, each = 16-byte header
# (``Parameter.h:263-267``: int32 format, uint32 valueSize, uint64 size,
# little-endian) + raw float32 payload (``Parameter.cpp:286-313``), next
# to a ``done`` marker and a saved config (``ParamUtil.cpp:84-112``).
# Dims are NOT in the file — they come from the model config, so the
# caller reshapes each vector against its own parameter tree.
_V1_HEADER = struct.Struct("<iIQ")
_V1_FORMAT_ORIGINAL = 0
_V1_FORMAT_MKLDNN_OI = 1  # OI-major weight layout — rejected, see below


class V1PassDir(dict):
    """``name -> flat <f4 vector`` mapping read from a pass dir, plus the
    set of file names header validation rejected (``skipped``).  The
    appliers consult ``skipped`` so a truncated/corrupted parameter file
    is reported as corruption, not as an absent parameter."""

    skipped: frozenset = frozenset()


def load_v1_pass_dir(directory: str) -> "V1PassDir":
    """Read every parameter file of a reference ``pass-%05d/`` dir into a
    flat ``name -> 1-D float32 array`` dict.

    Non-parameter files (the ``done`` marker, the saved config copy) are
    recognized and skipped by header validation: a parameter file's
    declared payload size must exactly account for the bytes after the
    header (``Parameter.cpp:343-357`` checks the same invariants on
    load).  Skipped names are collected on the result's ``skipped`` set —
    a corrupted parameter file fails validation the same way the markers
    do, and only the caller knows which names the model expects."""
    enforce(os.path.isdir(directory),
            "load_v1_pass_dir: %s is not a directory", directory)
    out = V1PassDir()
    skipped = set()
    for fn in sorted(os.listdir(directory)):
        path = os.path.join(directory, fn)
        if not os.path.isfile(path):
            continue
        size = os.path.getsize(path)
        if size < _V1_HEADER.size:
            skipped.add(unescape_name(fn))
            continue
        with open(path, "rb") as f:
            fmt, value_size, count = _V1_HEADER.unpack(
                f.read(_V1_HEADER.size))
            if (fmt not in (_V1_FORMAT_ORIGINAL, _V1_FORMAT_MKLDNN_OI)
                    or value_size != 4
                    or _V1_HEADER.size + 4 * count != size):
                skipped.add(unescape_name(fn))
                continue  # done marker / config copy / foreign file
            # MKLDNN_OI stores fc weights output-major; loading the raw
            # vector would silently transpose every matrix.  The MKLDNN
            # backend is a documented drop (PARITY.md) — fail loudly.
            enforce(fmt == _V1_FORMAT_ORIGINAL,
                    "v1 parameter %r uses PARAM_FORMAT_MKLDNN_OI; "
                    "re-save it from a non-MKLDNN build (OI layout is "
                    "not converted here)", fn)
            # Our parameter names are module paths ("fc_0/w"); "/" cannot
            # appear in a file name, so dirs we write escape it the same
            # way ``Parameters.to_tar`` does.  Reference-written dirs have
            # flat names ("_hidden1.w0") and pass through untouched.
            out[unescape_name(fn)] = np.frombuffer(
                f.read(4 * count), "<f4").copy()
    enforce(out, "load_v1_pass_dir: no reference-format parameter files "
            "in %s", directory)
    out.skipped = frozenset(skipped)
    return out


def apply_v1_params(params, loaded: Dict[str, np.ndarray],
                    name_map: Optional[Dict[str, str]] = None):
    """Reshape ``load_v1_pass_dir`` vectors into a parameter pytree.

    Iterates the MODEL's parameters (as ``Parameter::load`` does — files
    the config doesn't mention are ignored, a parameter without a file is
    an error, a size mismatch is an error with both sizes named).
    ``name_map`` translates OUR parameter name -> the artifact's file
    name, for importing models whose reference layer names don't line up
    with this framework's module paths."""
    name_map = name_map or {}
    flat = flatten_names(params)
    skipped = getattr(loaded, "skipped", frozenset())
    for name, leaf in flat.items():
        key = name_map.get(name, name)
        enforce(key not in skipped or key in loaded,
                "v1 parameter file %r exists but failed header "
                "validation (truncated or corrupted; Parameter.cpp:343 "
                "invariants)", key)
        enforce(key in loaded,
                "v1 pass dir is missing parameter %r (reference "
                "load_missing_parameter_strategy=fail; have %s)",
                key, sorted(loaded)[:10])
        leaf_arr = np.asarray(leaf)
        vec = loaded[key]
        enforce(vec.size == leaf_arr.size,
                "v1 parameter %r: file has %d values, model needs %d",
                name, vec.size, leaf_arr.size)
        flat[name] = vec.reshape(leaf_arr.shape).astype(leaf_arr.dtype)
    return unflatten_names(flat)


def save_v1_pass_dir(directory: str, params, net_state=None,
                     name_map: Optional[Dict[str, str]] = None) -> str:
    """Write parameters (and BN-style state leaves) as a reference
    ``pass-%05d/``-layout dir — the EXPORT converter of hard-part #5, so
    models trained here deploy back onto a reference install.  Byte
    layout per ``Parameter::save`` (16-byte header + raw ``<f4``).

    ``name_map`` (our name -> file name) mirrors the import direction:
    a reference install looks parameters up by ITS config's names
    (``_hidden1.w0``, BN stats ``.w1``/``.w2``), so deploying to one
    requires the mapping; without it, file names are our escaped module
    paths, which only this framework's importer reads back.

    The target directory must be empty (a re-export over stale files
    would leave obsolete parameters next to a fresh ``done`` marker,
    which every reader accepts silently).  Only float leaves export —
    f32/bf16/f16 convert exactly-or-widening to the format's f32;
    f64/integer leaves fail loudly rather than silently narrowing.
    Writes the ``done`` marker last, as ``ParamUtil.cpp:106-112``
    does."""
    name_map = name_map or {}
    if os.path.isdir(directory):
        enforce(not os.listdir(directory),
                "save_v1_pass_dir: %s is not empty (stale parameter "
                "files would survive next to a fresh done marker)",
                directory)
    os.makedirs(directory, exist_ok=True)
    flat = flatten_names(params)
    if net_state:
        flat.update(flatten_names(net_state))
    for name, value in flat.items():
        arr = np.asarray(value)
        enforce(arr.dtype.kind == "f" and arr.dtype.itemsize <= 4,
                "save_v1_pass_dir: leaf %r has dtype %s — the reference "
                "format is float32-only and narrowing would be silent",
                name, arr.dtype)
        vec = arr.astype("<f4").ravel()
        path = os.path.join(directory,
                            escape_name(name_map.get(name, name)))
        with open(path, "wb") as f:
            f.write(_V1_HEADER.pack(_V1_FORMAT_ORIGINAL, 4, vec.size))
            f.write(vec.tobytes())
    with open(os.path.join(directory, "done"), "w") as f:
        f.write("PaddlePaddle\n")
    return directory


def apply_v1_state(net_state, loaded: Dict[str, np.ndarray],
                   name_map: Optional[Dict[str, str]] = None):
    """Fill network STATE leaves (BatchNorm moving mean/var) from a v1
    pass dir.  In the reference these statistics are static parameters
    saved like any other (BatchNormBaseLayer's .w1/.w2); here they live
    in the state collection, so they import by name match — strictness
    differs from :func:`apply_v1_params`: a state leaf with no file
    keeps its fresh init (with a warning), since our state names never
    coincide with reference file names without a ``name_map``.

    Returns (new_state, matched_count)."""
    import warnings
    name_map = name_map or {}
    flat = flatten_names(net_state) if net_state else {}
    matched = 0
    missing = []
    skipped = getattr(loaded, "skipped", frozenset())
    for name, leaf in flat.items():
        key = name_map.get(name, name)
        if key not in loaded:
            # A file of this exact name that failed header validation is
            # corruption, not absence — fresh-initing moving statistics
            # from it would silently change eval numbers.
            enforce(key not in skipped,
                    "v1 state file %r exists but failed header "
                    "validation (truncated or corrupted)", key)
            missing.append(name)
            continue
        leaf_arr = np.asarray(leaf)
        vec = loaded[key]
        enforce(vec.size == leaf_arr.size,
                "v1 state %r: file has %d values, model needs %d",
                key, vec.size, leaf_arr.size)
        flat[name] = vec.reshape(leaf_arr.shape).astype(leaf_arr.dtype)
        matched += 1
    if missing:
        # Silently-fresh moving statistics produce wrong eval numbers —
        # say so.  Reference BN artifacts name these files .w1/.w2 under
        # the layer name; pass name_map to wire them up.
        warnings.warn(
            f"v1 pass dir: no files for state leaves {missing[:5]} — "
            "moving statistics keep fresh init (map reference BN .w1/.w2 "
            "files with name_map)", stacklevel=2)
    return (unflatten_names(flat) if flat else net_state), matched


def latest_pass(directory: str) -> Optional[int]:
    marker = os.path.join(directory, "latest")
    if not os.path.exists(marker):
        return None
    with open(marker) as f:
        return int(f.read().strip().split("-")[-1])


def load(directory: str, pass_id: Optional[int] = None,
         verify_md5: bool = True) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load trees; returns (trees, metadata).  pass_id=None -> latest."""
    if pass_id is None:
        pass_id = latest_pass(directory)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    pass_dir = os.path.join(directory, f"pass-{pass_id:05d}")
    with open(os.path.join(pass_dir, "meta.json")) as f:
        meta = json.load(f)
    npz_path = os.path.join(pass_dir, "arrays.npz")
    if verify_md5:
        with open(npz_path, "rb") as f:
            md5 = hashlib.md5(f.read()).hexdigest()
        if md5 != meta["md5"]:
            raise IOError(f"checkpoint md5 mismatch in {pass_dir}")
    data = np.load(npz_path)
    trees: Dict[str, Dict[str, np.ndarray]] = {}
    for key in data.files:
        tree_name, path = key.split(":", 1)
        trees.setdefault(tree_name, {})[path] = data[key]
    out = {name: _from_plain(unflatten_names(flat))
           for name, flat in trees.items()}
    return out, meta
