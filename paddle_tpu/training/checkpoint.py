"""Checkpoint save/restore.

Twin of the reference's three checkpoint paths (SURVEY.md §5): v1 per-pass
parameter dirs (``trainer/ParamUtil.h:58-111``), v2 ``Parameters.to_tar``
(``v2/parameters.py:324``) and the Go pserver's checkpoint+meta
(``go/pserver/service.go:272``).  One canonical format here:

``<dir>/pass-NNNNN/`` containing
  * ``arrays.npz``   — every leaf of every tree, flat-named ``tree:a/b/c``
  * ``meta.json``    — step counters, data cursor, user metadata, md5 of the
                       npz (the Go path's integrity check)

plus a ``latest`` symlink-style marker file.  Multi-host sharded arrays
should be saved via orbax instead; this format covers the single-host /
replicated case and is the interchange format of the merge/export tool.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.nn.module import flatten_names, unflatten_names


def _flatten_trees(trees: Dict[str, Any]) -> Dict[str, np.ndarray]:
    flat: Dict[str, np.ndarray] = {}
    for tree_name, tree in trees.items():
        if tree is None:
            continue
        for k, v in flatten_names(_to_plain(tree)).items():
            flat[f"{tree_name}:{k}"] = np.asarray(v)
    return flat


# Empty containers must survive the flatten/unflatten round-trip: a chained
# optimizer's state is a tuple like ((), {"v": ...}) and dropping the empty
# slot would silently misalign transforms with their state after restore.
_EMPTY_DICT = "__empty_dict__"
_EMPTY_TUPLE = "__empty_tuple__"


def _to_plain(tree):
    """Convert tuples in optimizer-state pytrees to indexed dicts."""
    if isinstance(tree, dict):
        if not tree:
            return {_EMPTY_DICT: np.zeros(0, np.int8)}
        return {str(k): _to_plain(v) for k, v in tree.items()}
    if isinstance(tree, (tuple, list)):
        if not tree:
            return {_EMPTY_TUPLE: np.zeros(0, np.int8)}
        return {f"#{i}": _to_plain(v) for i, v in enumerate(tree)}
    return tree


def _from_plain(tree):
    if isinstance(tree, dict):
        keys = list(tree.keys())
        if keys == [_EMPTY_DICT]:
            return {}
        if keys == [_EMPTY_TUPLE]:
            return ()
        if keys and all(k.startswith("#") for k in keys):
            items = sorted(tree.items(), key=lambda kv: int(kv[0][1:]))
            return tuple(_from_plain(v) for _, v in items)
        return {k: _from_plain(v) for k, v in tree.items()}
    return tree


def save(directory: str, pass_id: int, trees: Dict[str, Any],
         metadata: Optional[Dict[str, Any]] = None) -> str:
    """Save trees (e.g. {"params":…, "state":…, "opt":…}) for a pass."""
    pass_dir = os.path.join(directory, f"pass-{pass_id:05d}")
    os.makedirs(pass_dir, exist_ok=True)
    flat = _flatten_trees(trees)
    npz_path = os.path.join(pass_dir, "arrays.npz")
    # atomic-ish write: temp file then rename (pserver checkpoint pattern)
    # suffix must end in .npz or np.savez silently writes to <tmp>.npz
    fd, tmp = tempfile.mkstemp(dir=pass_dir, suffix=".tmp.npz")
    os.close(fd)
    np.savez(tmp, **flat)
    os.replace(tmp, npz_path)
    with open(npz_path, "rb") as f:
        md5 = hashlib.md5(f.read()).hexdigest()
    meta = {
        "pass_id": pass_id,
        "tree_names": sorted({k.split(":", 1)[0] for k in flat}),
        "md5": md5,
        "metadata": metadata or {},
    }
    with open(os.path.join(pass_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    with open(os.path.join(directory, "latest"), "w") as f:
        f.write(f"pass-{pass_id:05d}")
    return pass_dir


def latest_pass(directory: str) -> Optional[int]:
    marker = os.path.join(directory, "latest")
    if not os.path.exists(marker):
        return None
    with open(marker) as f:
        return int(f.read().strip().split("-")[-1])


def load(directory: str, pass_id: Optional[int] = None,
         verify_md5: bool = True) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load trees; returns (trees, metadata).  pass_id=None -> latest."""
    if pass_id is None:
        pass_id = latest_pass(directory)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    pass_dir = os.path.join(directory, f"pass-{pass_id:05d}")
    with open(os.path.join(pass_dir, "meta.json")) as f:
        meta = json.load(f)
    npz_path = os.path.join(pass_dir, "arrays.npz")
    if verify_md5:
        with open(npz_path, "rb") as f:
            md5 = hashlib.md5(f.read()).hexdigest()
        if md5 != meta["md5"]:
            raise IOError(f"checkpoint md5 mismatch in {pass_dir}")
    data = np.load(npz_path)
    trees: Dict[str, Dict[str, np.ndarray]] = {}
    for key in data.files:
        tree_name, path = key.split(":", 1)
        trees.setdefault(tree_name, {})[path] = data[key]
    out = {name: _from_plain(unflatten_names(flat))
           for name, flat in trees.items()}
    return out, meta
