"""Training event objects.

Twin of ``python/paddle/v2/event.py``: the trainer invokes a user callback
with typed events; handlers do logging/plotting/checkpointing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass:
    pass_id: int
    evaluator_results: Dict[str, float] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


@dataclasses.dataclass
class EndIteration:
    pass_id: int
    batch_id: int
    cost: float
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: Latest training-health summary (global grad norm, update ratio,
    #: overflow headroom, fired anomaly rules) when the trainer runs
    #: with ``health=`` — ``HealthMonitor.summary()`` shape; None when
    #: health is off or no cadence point has been observed yet.
    health: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class EndTestPeriod:
    pass_id: int
    batch_id: int
    cost: float
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
