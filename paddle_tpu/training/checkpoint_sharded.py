"""Sharded (multi-host) checkpointing via orbax.

The single-host format (``training/checkpoint.py``) stores replicated
views in one npz.  For genuinely sharded trees — ZeRO-sharded optimizer
state, tensor-parallel weights, multi-host meshes — each host must write
only its shards and restore must re-lay arrays onto the target mesh.
That is orbax's job; this module binds it to the framework's checkpoint
conventions (pass-numbered directories, step metadata, latest marker),
matching the guarantees of the Go pserver's per-shard checkpoint files +
etcd metadata (``go/pserver/service.go:272``) without a parameter server.

Layout::

    <dir>/pass-NNNNN/state/...   (orbax array store, one subdir per tree)
    <dir>/pass-NNNNN/meta.json   (step + user metadata)
    <dir>/latest

Use when params/opt state carry NamedShardings; the npz format stays the
interchange format for export/serving.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax

from paddle_tpu.core.errors import enforce
from paddle_tpu.training.checkpoint import latest_pass

__all__ = ["save_sharded", "load_sharded", "restore_args_like"]


def _pass_dir(directory: str, pass_id: int) -> str:
    return os.path.join(directory, f"pass-{pass_id:05d}")


def save_sharded(directory: str, pass_id: int, trees: Dict[str, Any],
                 metadata: Optional[Dict[str, Any]] = None) -> str:
    """Write sharded trees with orbax; every process must call this
    (collective).  Returns the pass directory."""
    import orbax.checkpoint as ocp

    path = _pass_dir(directory, pass_id)
    os.makedirs(path, exist_ok=True)
    # Keep EVERY tree, including empty containers: dropping an empty slot
    # silently misaligns transforms with their state after restore (same
    # invariant as checkpoint.py's _to_plain).  Only None trees are absent.
    trees = {k: v for k, v in trees.items() if v is not None}
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(os.path.join(path, "state"), trees, force=True)
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"pass_id": pass_id, "trees": sorted(trees),
                       "metadata": metadata or {}}, f)
        with open(os.path.join(directory, "latest"), "w") as f:
            f.write(f"pass-{pass_id:05d}")   # same marker as checkpoint.py
    if jax.process_count() > 1:
        # Peers must not return before process 0's metadata lands (a
        # restart on another host would miss meta.json / read stale
        # ``latest``).
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_ckpt_sharded_save")
    return path


def restore_args_like(trees: Dict[str, Any]) -> Dict[str, Any]:
    """Abstract restore target preserving each leaf's sharding/dtype/shape
    (build it from the live trees of an initialized Trainer)."""
    return {k: jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else x, v)
        for k, v in trees.items() if v is not None}


def load_sharded(directory: str, like: Dict[str, Any],
                 pass_id: Optional[int] = None
                 ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Restore trees onto the shardings described by ``like`` (the live
    trees or :func:`restore_args_like` output).  Returns (trees, meta)."""
    import orbax.checkpoint as ocp

    if pass_id is None:
        pass_id = latest_pass(directory)
        enforce(pass_id is not None, "no checkpoint passes under %r",
                directory)
    path = _pass_dir(directory, pass_id)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    target = restore_args_like(like)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        trees = ckptr.restore(os.path.join(path, "state"), target)
    return trees, meta
