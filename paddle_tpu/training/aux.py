"""Training auxiliaries: parameter stats, FP hygiene, preemption handling.

Twins of the reference's observability/safety knobs (SURVEY.md §5):

* ``--show_parameter_stats_period`` — per-parameter value/gradient
  abs-max/avg dumps (``TrainerInternal.cpp:80-110,155``);
* ``feenableexcept(FE_INVALID|FE_DIVBYZERO|FE_OVERFLOW)`` at trainer start
  (``TrainerMain.cpp:48``) — here ``jax.config.debug_nans``, which raises
  on the first NaN-producing op under jit;
* preemption-safe checkpointing — the elastic-recovery contract the Go
  stack provided via task re-dispatch; for an SPMD job the equivalent is
  save-on-SIGTERM + restore-latest (docs/design/checkpoint.md).
"""

from __future__ import annotations

import signal
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from paddle_tpu.nn.module import flatten_names


def parameter_stats(params, grads=None) -> Dict[str, Dict[str, float]]:
    """Per-parameter stats dict: {name: {max_abs, avg_abs, min, max}}
    (+ grad_* when grads given) — the show_parameter_stats dump."""
    out: Dict[str, Dict[str, float]] = {}
    flat_p = flatten_names(params)
    flat_g = flatten_names(grads) if grads is not None else {}
    for name, v in flat_p.items():
        a = np.asarray(v, np.float32)
        s = {"max_abs": float(np.abs(a).max()) if a.size else 0.0,
             "avg_abs": float(np.abs(a).mean()) if a.size else 0.0,
             "min": float(a.min()) if a.size else 0.0,
             "max": float(a.max()) if a.size else 0.0}
        if name in flat_g:
            g = np.asarray(flat_g[name], np.float32)
            s["grad_max_abs"] = float(np.abs(g).max()) if g.size else 0.0
            s["grad_avg_abs"] = float(np.abs(g).mean()) if g.size else 0.0
        out[name] = s
    return out


def format_parameter_stats(stats: Dict[str, Dict[str, float]]) -> str:
    """Human-readable table (the log_period print twin); gradient columns
    appear when the stats carry them (GradientPrinter path)."""
    with_grads = any("grad_max_abs" in s for s in stats.values())
    header = (f"{'parameter':<40} {'max_abs':>12} {'avg_abs':>12} "
              f"{'min':>12} {'max':>12}")
    if with_grads:
        header += f" {'grad_max_abs':>13} {'grad_avg_abs':>13}"
    lines = [header]
    for name, s in sorted(stats.items()):
        row = (f"{name:<40} {s['max_abs']:>12.6g} "
               f"{s['avg_abs']:>12.6g} {s['min']:>12.6g} "
               f"{s['max']:>12.6g}")
        if with_grads:
            row += (f" {s.get('grad_max_abs', 0.0):>13.6g}"
                    f" {s.get('grad_avg_abs', 0.0):>13.6g}")
        lines.append(row)
    return "\n".join(lines)


def enable_fp_checks(enable: bool = True) -> None:
    """Raise on NaN production anywhere under jit
    (the feenableexcept twin; debug_nans re-runs the offending op eagerly
    to locate it, so keep this off in production runs)."""
    jax.config.update("jax_debug_nans", enable)


class PreemptionHandler:
    """Save a checkpoint on SIGTERM/SIGINT, then re-raise the default
    behavior.  Usage::

        handler = PreemptionHandler(trainer, save_dir)
        handler.install()
        ...training loop...

    The trainer's ``pass_id`` is recorded as ``pass-<current>`` with a
    ``preempted`` marker in the metadata; ``Trainer.restore(save_dir)``
    resumes from it (step counter + data cursor included).
    """

    def __init__(self, trainer, save_dir: str,
                 on_save: Optional[Callable[[str], None]] = None):
        self.trainer = trainer
        self.save_dir = save_dir
        self.on_save = on_save
        self.triggered = False
        self._signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        trainer._preemption_handler = self

    def _save(self) -> None:
        if self.trainer.params is None:
            return
        path = self.trainer.save(
            self.save_dir,
            pass_id=getattr(self.trainer, "current_pass", 0),
            metadata={"preempted": True, "signal": int(self._signum or 0)})
        if self.on_save:
            self.on_save(path)

    def _exit(self, frame=None) -> None:
        signum = self._signum
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif signum == signal.SIGINT:
            raise KeyboardInterrupt
        else:
            raise SystemExit(128 + (signum or 0))

    def save_and_exit(self) -> None:
        """Checkpoint then re-raise the signal's behavior — called by the
        Trainer at the next batch boundary after a mid-step signal."""
        self._save()
        self._exit()

    def _handle(self, signum, frame):
        self.triggered = True
        self._signum = int(signum)
        if getattr(self.trainer, "_in_step", False):
            # The jitted step donated the previous params/opt_state
            # buffers; saving here would read deleted arrays.  Defer to
            # the batch boundary (train_batch checks ``triggered``).
            return
        self._save()
        self._exit(frame)

    def install(self, signals=(signal.SIGTERM, signal.SIGINT)) -> None:
        for s in signals:
            self._prev[s] = signal.signal(s, self._handle)

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
