"""Evaluators: streaming metrics over batches.

Twin of ``paddle/gserver/evaluators/Evaluator.{h,cpp}`` (base contract
start/evalImp/updateSamplesNum/finish, ``Evaluator.h:42``; zoo at
``Evaluator.cpp:172-1346``): an evaluator accumulates sufficient statistics
over batches and reports at pass end.  The ``distributeEval`` merge of the
reference maps to summing the statistic pytrees across hosts (they are all
sums, so one all-gather + sum merges them — ``distribute_eval`` below,
wired into ``Trainer.test(distributed=True)``).

Evaluators consume a dict of batch outputs (device arrays ok) — keys are
chosen by the model ("logits", "label", "weight", ...).
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.core.errors import enforce


class Evaluator:
    name = "evaluator"

    #: Names of the instance attributes holding the sufficient
    #: statistics between ``start()`` and ``finish()``.  Every declared
    #: statistic is a SUM over samples, so summing them across workers
    #: is the cross-trainer merge (``distributeEval``, Evaluator.h:42).
    #: Evaluators whose state is not a sum (printers, detection mAP's
    #: per-image match lists) leave this empty and stay local.
    STATS: Tuple[str, ...] = ()

    def start(self) -> None:
        raise NotImplementedError

    def update(self, outputs: Dict[str, np.ndarray]) -> None:
        raise NotImplementedError

    def finish(self) -> float:
        raise NotImplementedError

    def partials(self) -> Dict[str, np.ndarray]:
        """The ``STATS`` attributes as float64 arrays — the unit
        ``distribute_eval`` sums across processes."""
        out = {}
        for k in self.STATS:
            v = getattr(self, k)
            enforce(v is not None,
                    "evaluator %s: statistic %r is unset — start() must "
                    "give every STATS attribute its full shape (zeros), "
                    "not defer to the first update(): a process with an "
                    "empty eval shard never updates, and an abort here "
                    "leaves the other processes hanging in the "
                    "collective merge", self.name, k)
            out[k] = np.asarray(v, np.float64)
        return out

    def set_partials(self, merged: Dict[str, np.ndarray]) -> None:
        for k in self.STATS:
            v = merged[k]
            setattr(self, k,
                    float(v) if np.ndim(v) == 0 else np.asarray(v))


#: Collective-call counter for the coordination-service fallback below.
#: allgather_sum_f64 is collective (every process calls it the same
#: number of times in the same order), so the counter advances in
#: lockstep and gives each exchange a distinct key namespace.
_KV_ROUND = itertools.count()


def _kv_allgather_u32(wire):
    """All-gather over the distributed COORDINATION SERVICE's key-value
    store, for backends with no cross-process collective runtime: the
    CPU backend raises "Multiprocess computations aren't implemented on
    the CPU backend" from ``multihost_utils.process_allgather``, but the
    coordinator (which ``distributed.initialize`` always brings up) can
    still move bytes.  Evaluator partials are a handful of scalars and
    small histograms once per eval pass, so a KV round-trip is plenty.

    Each process publishes its leaves as one base64 blob keyed by
    (round, rank) and blocking-reads every peer's blob; leaf shapes are
    identical across processes (same STATS pytree), so the local byte
    layout slices every peer blob too."""
    import base64

    import jax
    from jax._src import distributed

    client = distributed.global_state.client
    enforce(client is not None,
            "evaluator all-gather fallback needs the distributed "
            "coordination service — call distributed.initialize() (or "
            "paddle_tpu.distributed.runtime.initialize()) first")
    nproc = jax.process_count()
    rank = jax.process_index()
    rid = next(_KV_ROUND)
    blob = b"".join(np.ascontiguousarray(w).tobytes() for w in wire)
    client.key_value_set(f"paddle_tpu/evalgather/{rid}/{rank}",
                         base64.b64encode(blob).decode("ascii"))
    blobs = []
    for p in range(nproc):
        if p == rank:
            blobs.append(blob)
        else:
            s = client.blocking_key_value_get(
                f"paddle_tpu/evalgather/{rid}/{p}", 120_000)
            blobs.append(base64.b64decode(s))
    out = []
    off = 0
    for w in wire:
        nb = w.nbytes
        out.append(np.stack([np.frombuffer(b[off:off + nb], np.uint32)
                             for b in blobs]))
        off += nb
    return out


def allgather_sum_f64(tree):
    """Sum a pytree of float64 arrays across all JAX processes without
    precision loss: x32-mode JAX downcasts float64 transfers to float32,
    so values travel as uint32 bit-pattern views and are reassembled
    before the float64 sum.  On the CPU backend (no collective runtime)
    the transfer rides the coordination-service KV store instead."""
    import jax
    from jax.experimental import multihost_utils

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    wire = [np.ascontiguousarray(
        np.atleast_1d(np.asarray(leaf, np.float64))).view(np.uint32)
        for leaf in leaves]
    if jax.process_count() > 1 and jax.default_backend() == "cpu":
        gathered = _kv_allgather_u32(wire)
    else:
        gathered = multihost_utils.process_allgather(wire)
    out = []
    for leaf, g in zip(leaves, gathered):
        f = np.ascontiguousarray(np.asarray(g, np.uint32)).view(np.float64)
        s = f.sum(axis=0)                    # (nproc, n) -> (n,)
        out.append(float(s[0]) if np.ndim(leaf) == 0 else s)
    return jax.tree_util.tree_unflatten(treedef, out)


def distribute_eval(evaluators: Sequence["Evaluator"]) -> None:
    """Merge evaluator statistics across all JAX processes — the twin of
    the reference's ``distributeEval`` (``Evaluator.h:42``, merged
    through ParameterClient2 in ``Evaluator.cpp:172``); here the stats
    are sums, so ONE host all-gather + sum replaces the pserver
    round-trip.  Collective: every process must call it with the same
    evaluator list, after its update() loop and before finish().
    Evaluators with empty ``STATS`` are left local."""
    import jax

    if jax.process_count() <= 1:
        return
    mergeable = [e for e in evaluators if e.STATS]
    if not mergeable:
        return
    merged = allgather_sum_f64([e.partials() for e in mergeable])
    for e, g in zip(mergeable, merged):
        e.set_partials(g)


class ClassificationError(Evaluator):
    """Twin of ClassificationErrorEvaluator (Evaluator.cpp:172)."""

    STATS = ("wrong", "total")

    def __init__(self, logits_key: str = "logits", label_key: str = "label",
                 name: str = "classification_error"):
        self.logits_key = logits_key
        self.label_key = label_key
        self.name = name

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def update(self, outputs):
        logits = np.asarray(outputs[self.logits_key])
        labels = np.asarray(outputs[self.label_key])
        mask = outputs.get(self.label_key + "_mask")
        pred = logits.argmax(-1)
        wrong = (pred != labels)
        if mask is not None:
            m = np.asarray(mask)
            self.wrong += float((wrong & m).sum())
            self.total += float(m.sum())
        else:
            self.wrong += float(wrong.sum())
            self.total += float(wrong.size)

    def finish(self):
        return self.wrong / max(self.total, 1.0)


class ValueSum(Evaluator):
    """Twin of SumEvaluator / column_sum (Evaluator.cpp:225-330)."""

    STATS = ("total", "count")

    def __init__(self, key: str, name: Optional[str] = None,
                 average: bool = False):
        self.key = key
        self.name = name or f"sum({key})"
        self.average = average

    def start(self):
        self.total = 0.0
        self.count = 0.0

    def update(self, outputs):
        v = np.asarray(outputs[self.key])
        self.total += float(v.sum())
        self.count += float(v.shape[0]) if v.ndim else 1.0

    def finish(self):
        return self.total / max(self.count, 1.0) if self.average else self.total


class PrecisionRecall(Evaluator):
    """Binary/multiclass positive-class P/R/F1
    (twin of PrecisionRecallEvaluator, Evaluator.cpp:580)."""

    STATS = ("tp", "fp", "fn")

    def __init__(self, logits_key: str = "logits", label_key: str = "label",
                 positive_class: int = 1, name: str = "precision_recall"):
        self.logits_key = logits_key
        self.label_key = label_key
        self.positive = positive_class
        self.name = name

    def start(self):
        self.tp = 0.0
        self.fp = 0.0
        self.fn = 0.0

    def update(self, outputs):
        pred = np.asarray(outputs[self.logits_key]).argmax(-1)
        label = np.asarray(outputs[self.label_key])
        p = pred == self.positive
        t = label == self.positive
        self.tp += float((p & t).sum())
        self.fp += float((p & ~t).sum())
        self.fn += float((~p & t).sum())

    def finish(self):
        precision = self.tp / max(self.tp + self.fp, 1.0)
        recall = self.tp / max(self.tp + self.fn, 1.0)
        f1 = 2 * precision * recall / max(precision + recall, 1e-8)
        return f1


class AUC(Evaluator):
    """Streaming ROC-AUC via score histogram
    (twin of RankAucEvaluator / AucEvaluator, Evaluator.cpp:334-570)."""

    STATS = ("pos", "neg")

    def __init__(self, score_key: str = "prob", label_key: str = "label",
                 num_bins: int = 4096, name: str = "auc"):
        self.score_key = score_key
        self.label_key = label_key
        self.num_bins = num_bins
        self.name = name

    def start(self):
        self.pos = np.zeros(self.num_bins)
        self.neg = np.zeros(self.num_bins)

    def update(self, outputs):
        score = np.asarray(outputs[self.score_key]).reshape(-1)
        label = np.asarray(outputs[self.label_key]).reshape(-1)
        bins = np.clip((score * self.num_bins).astype(int), 0,
                       self.num_bins - 1)
        self.pos += np.bincount(bins[label == 1], minlength=self.num_bins)
        self.neg += np.bincount(bins[label == 0], minlength=self.num_bins)

    def finish(self):
        # trapezoid over descending-score sweep
        pos_cum = np.cumsum(self.pos[::-1])
        neg_cum = np.cumsum(self.neg[::-1])
        total_pos = max(pos_cum[-1], 1.0)
        total_neg = max(neg_cum[-1], 1.0)
        tpr = np.concatenate([[0.0], pos_cum / total_pos])
        fpr = np.concatenate([[0.0], neg_cum / total_neg])
        return float(np.trapezoid(tpr, fpr))


class ChunkEvaluator(Evaluator):
    """Chunk (NER-style) F1 over IOB tag sequences
    (twin of ChunkEvaluator.cpp, scheme=IOB).

    Expects integer tag ids where tag%2==1 means B-type and tag%2==0 (and
    nonzero... configurable) — to stay scheme-agnostic, callers pass a
    ``decode_chunks(tags) -> set[(start, end, type)]`` function.
    """

    STATS = ("correct", "n_pred", "n_label")

    def __init__(self, pred_key: str, label_key: str, decode_chunks,
                 mask_key: Optional[str] = None, name: str = "chunk_f1"):
        self.pred_key = pred_key
        self.label_key = label_key
        self.mask_key = mask_key
        self.decode = decode_chunks
        self.name = name

    def start(self):
        self.correct = 0.0
        self.n_pred = 0.0
        self.n_label = 0.0

    def update(self, outputs):
        preds = np.asarray(outputs[self.pred_key])
        labels = np.asarray(outputs[self.label_key])
        if self.mask_key:
            masks = np.asarray(outputs[self.mask_key])
        else:
            masks = np.ones(preds.shape, bool)
        for p_row, l_row, m_row in zip(preds, labels, masks):
            n = int(m_row.sum())
            pc = self.decode(list(p_row[:n]))
            lc = self.decode(list(l_row[:n]))
            self.correct += len(pc & lc)
            self.n_pred += len(pc)
            self.n_label += len(lc)

    def finish(self):
        precision = self.correct / max(self.n_pred, 1.0)
        recall = self.correct / max(self.n_label, 1.0)
        return 2 * precision * recall / max(precision + recall, 1e-8)


def iob_decode(tags):
    """Decode IOB1-coded int tags (odd=B, even-nonneg... simple scheme:
    0=O, odd=B-k, even=I-k with type k=(tag+1)//2) into chunk triples."""
    chunks = set()
    start = None
    ctype = None
    for i, t in enumerate(tags):
        t = int(t)
        if t == 0:
            if start is not None:
                chunks.add((start, i, ctype))
                start = None
        elif t % 2 == 1:  # B-
            if start is not None:
                chunks.add((start, i, ctype))
            start = i
            ctype = (t + 1) // 2
        else:  # I-
            if start is None or ctype != t // 2:
                if start is not None:
                    chunks.add((start, i, ctype))
                start = i
                ctype = t // 2
    if start is not None:
        chunks.add((start, len(tags), ctype))
    return chunks


class ColumnSum(Evaluator):
    """Per-column sums of an output matrix (twin of ColumnSumEvaluator,
    ``Evaluator.cpp:225``).

    The column count is lazy (first update) by default, which means an
    EMPTY data shard has no stats to contribute — so the evaluator only
    participates in the distributed merge when ``size`` is given (then a
    zero-batch process contributes zeros instead of desynchronizing the
    collective)."""

    def __init__(self, key: str, name: Optional[str] = None,
                 size: Optional[int] = None):
        self.key = key
        self.name = name or f"column_sum({key})"
        self.size = size
        self.STATS = ("total",) if size is not None else ()

    def start(self):
        self.total = (np.zeros(self.size, np.float64)
                      if self.size is not None else None)

    def update(self, outputs):
        v = np.asarray(outputs[self.key], np.float64)
        v = v.reshape(-1, v.shape[-1])
        s = v.sum(axis=0)
        self.total = s if self.total is None else self.total + s

    def finish(self):
        return 0.0 if self.total is None else self.total


class CTCError(Evaluator):
    """Sequence edit-distance rate (twin of CTCErrorEvaluator.cpp):
    sum(editdist(pred, label)) / sum(len(label)) over greedy-decoded,
    blank/dup-collapsed predictions."""

    STATS = ("dist", "len")

    def __init__(self, pred_key: str = "decoded", label_key: str = "label",
                 pred_len_key: Optional[str] = None,
                 label_len_key: Optional[str] = None, name: str = "ctc_error"):
        self.pred_key = pred_key
        self.label_key = label_key
        self.pred_len_key = pred_len_key
        self.label_len_key = label_len_key
        self.name = name

    @staticmethod
    def _edit_distance(a, b):
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[-1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]

    def start(self):
        self.dist = 0.0
        self.len = 0.0

    def update(self, outputs):
        preds = np.asarray(outputs[self.pred_key])
        labels = np.asarray(outputs[self.label_key])
        plens = (np.asarray(outputs[self.pred_len_key])
                 if self.pred_len_key else
                 np.full(preds.shape[0], preds.shape[1]))
        llens = (np.asarray(outputs[self.label_len_key])
                 if self.label_len_key else
                 np.full(labels.shape[0], labels.shape[1]))
        for p, l, pl, ll in zip(preds, labels, plens, llens):
            self.dist += self._edit_distance(list(p[:int(pl)]),
                                             list(l[:int(ll)]))
            self.len += float(ll)

    def finish(self):
        return self.dist / max(self.len, 1.0)


class PnPair(Evaluator):
    """Positive/negative pair ordering within query groups (twin of
    PnpairEvaluator, ``Evaluator.cpp``): over all pairs in a query with
    different labels, the fraction where the higher-labelled one scored
    higher.  Reports pos/neg ratio like the reference."""

    def __init__(self, score_key: str = "score", label_key: str = "label",
                 query_key: str = "query_id", name: str = "pnpair"):
        self.score_key = score_key
        self.label_key = label_key
        self.query_key = query_key
        self.name = name

    def start(self):
        self.rows = []

    def update(self, outputs):
        score = np.asarray(outputs[self.score_key]).reshape(-1)
        label = np.asarray(outputs[self.label_key]).reshape(-1)
        query = np.asarray(outputs[self.query_key]).reshape(-1)
        self.rows.append((query, label, score))

    def finish(self):
        if not self.rows:
            return 0.0
        query = np.concatenate([r[0] for r in self.rows])
        label = np.concatenate([r[1] for r in self.rows])
        score = np.concatenate([r[2] for r in self.rows])
        pos = neg = 0.0
        for q in np.unique(query):
            sel = query == q
            l, s = label[sel], score[sel]
            dl = l[:, None] - l[None, :]
            ds = s[:, None] - s[None, :]
            upper = np.triu(np.ones_like(dl, bool), 1)
            pairs = upper & (dl != 0)
            good = np.sign(dl) == np.sign(ds)
            tie = (ds == 0) & pairs
            pos += float((pairs & good & ~tie).sum()) + 0.5 * float(tie.sum())
            neg += float((pairs & ~good & ~tie).sum()) + 0.5 * float(tie.sum())
        return pos / max(neg, 1e-8)


class ValuePrinter(Evaluator):
    """Debug printer (twin of ValuePrinter/GradientPrinter,
    ``Evaluator.cpp:1009-1046``): logs summary stats of chosen outputs."""

    def __init__(self, keys, log_fn=print, name: str = "printer"):
        self.keys = list(keys)
        self.log_fn = log_fn
        self.name = name

    def start(self):
        self.batches = 0

    def update(self, outputs):
        self.batches += 1
        for k in self.keys:
            if k in outputs:
                v = np.asarray(outputs[k])
                self.log_fn(f"[{self.name}] batch {self.batches} {k}: "
                            f"shape={v.shape} absmax={np.abs(v).max():.6g} "
                            f"mean={v.mean():.6g}")

    def finish(self):
        return float(self.batches)


class DetectionMAP(Evaluator):
    """Detection mean-AP (twin of DetectionMAPEvaluator.cpp), fed with
    per-image decoded detections and ground truths."""

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 mode: str = "11point", name: str = "detection_map"):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold
        self.mode = mode
        self.name = name

    def start(self):
        self.dets = []
        self.gts = []

    def update(self, outputs):
        """Expects per-image lists: ``det_boxes``/``det_scores``/
        ``det_labels`` and ``gt_boxes``/``gt_labels`` (arrays or lists)."""
        for i in range(len(outputs["det_boxes"])):
            self.dets.append((np.asarray(outputs["det_boxes"][i]),
                              np.asarray(outputs["det_scores"][i]),
                              np.asarray(outputs["det_labels"][i])))
            self.gts.append((np.asarray(outputs["gt_boxes"][i]),
                             np.asarray(outputs["gt_labels"][i])))

    def finish(self):
        from paddle_tpu.ops.detection import detection_map
        return detection_map(self.dets, self.gts, self.num_classes,
                             self.iou_threshold, self.mode)


def iob_chunks(tags, num_chunk_types: int):
    """Decode an IOB tag-id sequence into chunks (the reference's default
    ChunkEvaluator encoding, ``ChunkEvaluator.cpp``): tag = type*2 + {B:0,
    I:1}; the "outside" tag is ``num_chunk_types*2``.  Returns a set of
    (start, end_exclusive, type)."""
    tags = list(tags)
    chunks = set()
    start = None
    ctype = None
    for i, tag in enumerate(tags):
        tag = int(tag)
        is_o = tag >= num_chunk_types * 2
        t, b_or_i = (None, None) if is_o else divmod(tag, 2)
        begins = (not is_o) and (b_or_i == 0)
        continues = (not is_o) and (b_or_i == 1) and ctype == t
        if start is not None and not continues:
            chunks.add((start, i, ctype))
            start, ctype = None, None
        if begins:
            start, ctype = i, t
        elif not is_o and not continues:
            # I-tag opening a chunk (IOB allows this as a new chunk)
            start, ctype = i, t
    if start is not None:
        chunks.add((start, len(tags), ctype))
    return chunks


class GradientPrinter(Evaluator):
    """Per-parameter gradient statistics printer (twin of GradientPrinter,
    ``Evaluator.cpp:1029-1046``, config api gradient_printer_evaluator).

    Declares ``wants_gradients``: the Trainer's batch loop computes the
    gradient tree for each batch (an extra forward+backward — a debug
    path, exactly as spammy as the reference's) and passes it via
    ``outputs["__gradients__"]`` with the pre-update params."""

    wants_gradients = True

    def __init__(self, keys=None, log_fn=print, name: str = "grad_printer"):
        self.keys = list(keys) if keys is not None else None
        self.log_fn = log_fn
        self.name = name

    def start(self):
        self.batches = 0

    def update(self, outputs):
        from paddle_tpu.training.aux import (format_parameter_stats,
                                             parameter_stats)
        grads = outputs.get("__gradients__")
        params = outputs.get("__params__")
        if grads is None or params is None:
            # e.g. an eval pass reusing the evaluator list: only the
            # train loop supplies gradients; count printed batches only.
            return
        self.batches += 1
        stats = parameter_stats(params, grads)
        if self.keys is not None:
            stats = {k: v for k, v in stats.items()
                     if any(k.startswith(p) for p in self.keys)}
        self.log_fn(f"[{self.name}] batch {self.batches}\n"
                    + format_parameter_stats(stats))

    def finish(self):
        return float(self.batches)


class RankAUC(Evaluator):
    """Per-sequence weighted rank AUC averaged over sequences (twin of
    RankAucEvaluator, ``Evaluator.cpp:502-580``): scores ranked
    descending within each sequence; clicks are positives and
    (pv - click) the negatives, tied scores sharing trapezoid credit.

    update() consumes ``outputs[score_key]`` [b, t], ``click_key`` [b, t]
    and the sequence mask ``score_key + "_mask"`` (or ``mask_key``);
    ``pv_key`` defaults to 1 per position like the reference's filled
    pv vector."""

    STATS = ("total", "sequences")

    def __init__(self, score_key: str = "score", click_key: str = "click",
                 pv_key: Optional[str] = None,
                 mask_key: Optional[str] = None, name: str = "rank_auc"):
        self.score_key = score_key
        self.click_key = click_key
        self.pv_key = pv_key
        self.mask_key = mask_key or score_key + "_mask"
        self.name = name

    def start(self):
        self.total = 0.0
        self.sequences = 0

    @staticmethod
    def _seq_auc(score, click, pv):
        order = np.argsort(-score, kind="stable")
        auc = click_sum = old_click_sum = 0.0
        no_click = no_click_sum = 0.0
        last = np.inf
        for i in order:
            if score[i] != last:
                auc += (click_sum + old_click_sum) * no_click / 2.0
                old_click_sum = click_sum
                no_click = 0.0
                last = score[i]
            no_click += pv[i] - click[i]
            no_click_sum += no_click
            click_sum += click[i]
        auc += (click_sum + old_click_sum) * no_click / 2.0
        denom = click_sum * no_click_sum
        return 0.0 if denom == 0.0 else auc / denom

    def update(self, outputs):
        score = np.asarray(outputs[self.score_key], np.float64)
        click = np.asarray(outputs[self.click_key], np.float64)
        mask = np.asarray(outputs.get(self.mask_key,
                                      np.ones_like(score, bool)), bool)
        pv = (np.asarray(outputs[self.pv_key], np.float64)
              if self.pv_key else np.ones_like(score))
        for b in range(score.shape[0]):
            m = mask[b]
            if not m.any():
                continue
            self.total += self._seq_auc(score[b][m], click[b][m], pv[b][m])
            self.sequences += 1

    def finish(self):
        return self.total / max(self.sequences, 1)
