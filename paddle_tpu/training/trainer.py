"""The training driver.

Twin of the v2 ``SGD`` trainer (``python/paddle/v2/trainer.py:24`` —
SGD.__init__/train/test) over the v1 engine stack
(``Trainer::train`` ``paddle/trainer/Trainer.cpp:261``,
``TrainerInternal::trainOneBatch`` ``TrainerInternal.cpp:66``): pass loop →
batch loop → forwardBackward+update → events/evaluators → per-pass
checkpoint.  The C++ GradientMachine/updater pipeline collapses into ONE
jitted train_step (value_and_grad + optimizer transform) that XLA fuses and,
when a mesh is given, shards data-parallel over ``dp`` with gradient psum
compiled onto ICI — replacing both MultiGradientMachine's thread ring and
the RemoteParameterUpdater/pserver sync path.

The model callable has signature ``model_fn(batch: dict) -> (loss, outputs)``
where ``loss`` is a scalar and ``outputs`` is a dict fed to evaluators; it
uses ``paddle_tpu.nn`` modules (wrapped with ``nn.transform`` internally).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim as optim_lib
from paddle_tpu import telemetry
from paddle_tpu.core.errors import enforce
from paddle_tpu.telemetry import health as health_lib
from paddle_tpu.nn import transform
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.training import checkpoint as ckpt_lib
from paddle_tpu.training import events as ev
from paddle_tpu.training.evaluators import Evaluator


class Trainer:
    def __init__(self, model_fn: Callable[[Dict[str, Any]], Any],
                 optimizer: optim_lib.Transform,
                 seed: int = 0,
                 mesh=None,
                 param_rules=None,
                 average_window: int = 0,
                 zero_axis: Optional[str] = None,
                 batch_spec=None,
                 metrics=None,
                 health=None):
        """``batch_spec`` — PartitionSpec for batch leaves under a mesh
        (default: leading axis over ``dp``).  Non-dp-first topologies set
        it explicitly: ``P(None, "sp")`` shards sequence for a
        ring-attention trainer on an (sp, ep) mesh; ``P()`` replicates
        (pipeline trainers split microbatches internally).

        ``metrics`` — a :class:`~paddle_tpu.telemetry.MetricsRegistry`
        (default: the process-wide one).  The trainer feeds a
        ``train_step_seconds`` histogram (``path=batch`` per-dispatch,
        ``path=scan`` amortized per scanned step), batch/example/token
        counters, a ``train_tokens_per_s`` gauge, and ``trainer/eval`` /
        ``trainer/checkpoint`` spans.  All observations are host-side,
        around — never inside — the jitted step.  Caveat: under JAX's
        async dispatch a per-batch time measures dispatch unless the
        caller syncs; the differential protocol in ``utils/timing.py``
        remains the benchmark truth (``docs/design/telemetry.md``).

        ``health`` — ``True`` or a
        :class:`~paddle_tpu.telemetry.health.HealthConfig` turns on the
        training health monitor: the jitted step additionally returns
        one packed f32 statistics vector (grad/weight/update norms per
        layer group, non-finite counts, logits abs-max — pure in-graph
        ``jnp`` reductions, donation and ``compiles==1`` unchanged) and
        a host-side :class:`~paddle_tpu.telemetry.health.HealthMonitor`
        decodes it every ``cadence`` steps, feeding ``train_health_*``
        metrics and firing anomaly / NaN-precursor alarms.  The cadence
        sync is the only added device->host transfer."""
        self.model = transform(model_fn)
        self.optimizer = optimizer
        self.seed = seed
        self.mesh = mesh
        self.param_rules = param_rules
        self.zero_axis = zero_axis
        self.batch_spec = batch_spec
        self.average_window = average_window
        self.params = None
        self.net_state = None
        self.opt_state = None
        self.avg_state = None
        self.step = 0
        self._train_step = None
        self._eval_step = None
        self.metrics = (metrics if metrics is not None
                        else telemetry.get_registry())
        self._m_step = self.metrics.histogram(
            "train_step_seconds",
            "host wall time per train step (path=batch: one dispatch; "
            "path=scan: scan wall time / k)")
        self._m_batches = self.metrics.counter(
            "train_batches_total", "train steps run")
        self._m_examples = self.metrics.counter(
            "train_examples_total", "examples consumed (leading batch dim)")
        self._m_tokens = self.metrics.counter(
            "train_tokens_total", "token positions consumed (ids elements)")
        self._m_tps = self.metrics.gauge(
            "train_tokens_per_s",
            "tokens/s of the most recent step or scan chunk")
        if health is True:
            health = health_lib.HealthConfig()
        self._health_cfg = health or None
        self.health_monitor = None

    # ``step`` is plain-int bookkeeping (checkpoints, logs); the jitted
    # step receives a DEVICE-RESIDENT twin incremented with a lazy add.
    # Uploading a fresh host scalar every batch costs a full transport
    # round trip per step on tunneled attachments — measured 4-16 ms,
    # several times the 2 ms compute of the bench model.
    @property
    def step(self) -> int:
        return self._step

    @step.setter
    def step(self, value: int) -> None:
        self._step = int(value)
        self._step_dev = None

    def _step_array(self):
        if self._step_dev is None:
            self._step_dev = jnp.asarray(self._step, jnp.int32)
        return self._step_dev

    # ---- initialization ----

    def init(self, sample_batch: Dict[str, Any]) -> None:
        batch = {k: jnp.asarray(v) for k, v in sample_batch.items()}
        self.params, self.net_state = self.model.init(
            jax.random.key(self.seed), batch)
        if self.mesh is not None:
            from paddle_tpu.parallel import sharding as sharding_lib
            # shard params by rule (tensor parallel) before deriving
            # optimizer state, so the state inherits the same layout
            self.params = sharding_lib.apply_rules(self.params, self.mesh,
                                                   self.param_rules)
            self.net_state = mesh_lib.replicate(self.net_state, self.mesh)
        self.opt_state = self.optimizer.init(self.params)
        if self.mesh is not None and self.zero_axis:
            from paddle_tpu.parallel import zero as zero_lib
            self.opt_state = zero_lib.shard_opt_state(
                self.opt_state, self.mesh, self.zero_axis)
        if self.average_window:
            self.avg_state = optim_lib.average.init(self.params)
        self._build_steps()

    def _build_steps(self):
        model, optimizer = self.model, self.optimizer
        if self._health_cfg is not None and self.health_monitor is None:
            # the spec needs concrete param names; built here (post-init/
            # restore) and closed over by the step so device and host
            # agree on the packed-vector layout by construction
            spec = health_lib.build_spec(self.params,
                                         group_fn=self._health_cfg.group_fn)
            self.health_monitor = health_lib.HealthMonitor(
                spec, self._health_cfg, metrics=self.metrics)
        health_spec = (self.health_monitor.spec
                       if self.health_monitor is not None else None)
        # Sharded params cannot flow through Pallas kernels (GSPMD cannot
        # partition a pallas_call), so rule-sharded runs trace with kernel
        # fusion disabled — the mechanism-level twin of picking the XLA
        # scan schedule under tensor parallelism.
        if self.param_rules is not None:
            from paddle_tpu.ops.pallas_kernels import fusion_disabled
            fusion_ctx = fusion_disabled
        else:
            import contextlib
            fusion_ctx = contextlib.nullcontext

        def train_step(params, net_state, opt_state, batch, step):
            # tpu-lint: disable=dead-code — rng liveness is model-dependent: dead only for dropout-free configs, one fold_in either way
            rng = jax.random.fold_in(jax.random.key(self.seed), step)

            def loss_fn(p):
                with fusion_ctx():
                    (loss, outputs), new_state = model.apply(
                        p, net_state, rng, batch, train=True)
                from paddle_tpu.nn.module import collect_aux_losses
                loss = loss + collect_aux_losses(new_state)
                return loss, (outputs, new_state)

            (loss, (outputs, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = optimizer.update(grads, opt_state, params,
                                                step)
            new_params = optim_lib.apply_updates(params, updates)
            if health_spec is not None:
                # in-graph health statistics: jnp reductions XLA fuses
                # into the step, packed into ONE [n] f32 vector — the
                # update-ratio numerator reads the updates at the
                # transform boundary, post-chain (what actually lands)
                hvec = health_lib.health_vector(
                    health_spec, loss=loss, grads=grads, params=params,
                    updates=updates, new_params=new_params,
                    outputs=outputs)
                return new_params, new_state, new_opt, loss, outputs, hvec
            return new_params, new_state, new_opt, loss, outputs

        def eval_step(params, net_state, batch):
            with fusion_ctx():
                (loss, outputs), _ = model.apply(params, net_state, None,
                                                 batch, train=False)
            return loss, outputs

        def grads_step(params, net_state, batch, step):
            # Gradient tree only (GradientPrinter support): the same
            # loss_fn as train_step, without the optimizer update.
            rng = jax.random.fold_in(jax.random.key(self.seed), step)

            def loss_fn(p):
                with fusion_ctx():
                    (loss, _), new_state = model.apply(
                        p, net_state, rng, batch, train=True)
                from paddle_tpu.nn.module import collect_aux_losses
                return loss + collect_aux_losses(new_state)

            return jax.grad(loss_fn)(params)

        def train_scan(params, net_state, opt_state, batch_stack, step0):
            # K train steps in ONE compiled program: the device-side
            # training loop (twin of the reference's C++ batch loop —
            # TrainerBenchmark.cpp runs batches with no interpreter in
            # between).  Per-step outputs are dropped; per-step losses
            # return stacked.
            def body(carry, batch):
                p, ns, os_, step = carry
                out = train_step(p, ns, os_, batch, step)
                p, ns, os_, loss = out[:4]
                ys = (loss, out[5]) if health_spec is not None else loss
                return (p, ns, os_, step + 1), ys

            (p, ns, os_, _), ys = jax.lax.scan(
                body, (params, net_state, opt_state, step0), batch_stack)
            if health_spec is not None:
                losses, hvecs = ys     # hvecs stacked [k, n]
                return p, ns, os_, losses, hvecs
            return p, ns, os_, ys

        # params/opt_state buffers are dead after the step — donate them,
        # EXCEPT under debug_nans: its diagnostic re-run needs the original
        # arguments, which donation would have deleted.
        if jax.config.jax_debug_nans:
            self._train_step = jax.jit(train_step)
            self._train_scan = jax.jit(train_scan)
        else:
            self._train_step = jax.jit(train_step, donate_argnums=(0, 2))
            self._train_scan = jax.jit(train_scan, donate_argnums=(0, 2))
        self._eval_step = jax.jit(eval_step)
        self._grads_step = jax.jit(grads_step)

    def jitted_steps(self):
        """The trainer's compiled programs, by name — the lint surface
        (``analysis/entrypoints.py`` traces these for the tpu-lint
        self-check) and the :class:`~paddle_tpu.analysis.CompileWatcher`
        handle for retrace pins.  Call after :meth:`init`."""
        enforce(self._train_step is not None,
                "jitted_steps: call init() first — the steps are built "
                "against the model's concrete shapes")
        return {"train_step": self._train_step,
                "train_scan": self._train_scan,
                "eval_step": self._eval_step,
                "grads_step": self._grads_step}

    # ---- training ----

    def gradients(self, batch: Dict[str, Any]):
        """Per-parameter gradient tree for ``batch`` at the CURRENT params
        (pre-update) — the GradientPrinter/debug hook.  Costs an extra
        forward+backward; a diagnostics path, not the training path."""
        if self.params is None:
            self.init(batch)
        return self._grads_step(self.params, self.net_state,
                                self._put(batch), self._step_array())

    def _observe_step(self, batch, dt: float, k: int, path: str) -> None:
        """Feed the step telemetry.  Shapes are static metadata — reading
        them never syncs the device; only already-host timings flow in."""
        leaves = jax.tree_util.tree_leaves(batch)
        shape = tuple(leaves[0].shape) if leaves else ()
        if not shape:
            examples = 0
        elif k > 1:          # stacked [k, B, ...] chunk
            examples = int(np.prod(shape[:2]))
        else:
            examples = int(shape[0])
        ids = batch.get("ids") if isinstance(batch, dict) else None
        tokens = int(np.prod(np.shape(ids))) if ids is not None else 0
        self._m_step.observe(dt / k, path=path)
        self._m_batches.inc(k)
        from paddle_tpu.telemetry.trace import get_tracer
        tracer = get_tracer()
        if tracer is not None:
            t1 = time.perf_counter()
            tracer.complete(f"train/{path}", t1 - dt, t1,
                            track="trainer", k=k, tokens=tokens)
        if examples:
            self._m_examples.inc(examples)
        if tokens:
            self._m_tokens.inc(tokens)
            if dt > 0:
                self._m_tps.set(tokens / dt)

    def _observe_health(self, hvecs, step0: int, k: int) -> None:
        """Feed cadence-aligned health vectors to the monitor.  ONE
        ``np.asarray`` transfer per call covers all ``k`` steps (the
        scan path hands a stacked ``[k, n]`` array); steps off the
        cadence grid never reach the host."""
        mon = self.health_monitor
        if mon is None:
            return
        cadence = mon.config.cadence
        aligned = [i for i in range(k) if (step0 + i) % cadence == 0]
        if not aligned:
            return
        host = np.asarray(hvecs)
        if k == 1:
            host = host.reshape(1, -1)
        for i in aligned:
            mon.observe(host[i], step=step0 + i)

    def train_batch(self, batch: Dict[str, Any]):
        if self.params is None:
            self.init(batch)
        batch = self._put(batch)
        self._in_step = True
        step_arr = self._step_array()
        t0 = time.perf_counter()
        try:
            res = self._train_step(self.params, self.net_state,
                                   self.opt_state, batch, step_arr)
            (self.params, self.net_state, self.opt_state, loss,
             outputs) = res[:5]
        finally:
            self._in_step = False
        self._observe_step(batch, time.perf_counter() - t0, 1, "batch")
        if self.health_monitor is not None:
            self._observe_health(res[5], self._step, 1)
        if self.average_window:
            self.avg_state = optim_lib.average.accumulate(
                self.avg_state, self.params)
        self._step += 1
        self._step_dev = step_arr + 1       # device add, no host transfer
        handler = getattr(self, "_preemption_handler", None)
        if handler is not None and handler.triggered:
            # A signal arrived mid-step (buffers were donated then);
            # checkpoint now at the batch boundary, then stop.
            handler.save_and_exit()
        return loss, outputs

    def train_batches(self, batch_stack: Dict[str, Any]):
        """Run K train steps in one device dispatch: every leaf of
        ``batch_stack`` carries a leading ``[k, ...]`` axis and the steps
        execute as a compiled ``lax.scan`` — no host round trip between
        batches.  Returns the per-batch losses ``[k]``.

        This is the throughput path (the reference's C++ batch loop /
        ``--job=time`` twin); single-batch ``train_batch`` remains the
        step-by-step path for event hooks and evaluators.
        """
        enforce(not self.average_window,
                "train_batches: per-step model averaging needs the "
                "step-by-step train_batch path")
        if self.params is None:
            self.init(jax.tree_util.tree_map(lambda x: x[0], batch_stack))
        batch_stack = self._put(batch_stack, stacked=True)
        k = jax.tree_util.tree_leaves(batch_stack)[0].shape[0]
        step_arr = self._step_array()
        self._in_step = True
        t0 = time.perf_counter()
        try:
            res = self._train_scan(self.params, self.net_state,
                                   self.opt_state, batch_stack, step_arr)
            (self.params, self.net_state, self.opt_state,
             losses) = res[:4]
        finally:
            self._in_step = False
        self._observe_step(batch_stack, time.perf_counter() - t0, int(k),
                           "scan")
        if self.health_monitor is not None:
            self._observe_health(res[4], self._step, int(k))
        self._step += int(k)
        self._step_dev = step_arr + k
        handler = getattr(self, "_preemption_handler", None)
        if handler is not None and handler.triggered:
            handler.save_and_exit()
        return losses

    _FAST_CHUNK = 16

    def _train_pass_fast(self, reader) -> List[float]:
        """One pass through the device-side loop: buffer same-shape
        batches into chunks of up to ``_FAST_CHUNK``, run each chunk as
        one ``train_batches`` scan, and transfer all losses at pass end.
        A shape change (e.g. a last partial batch) flushes the buffer and
        starts a new chunk."""
        device_losses = []
        buf: List[Dict[str, Any]] = []
        buf_key = None

        def flush():
            nonlocal buf, buf_key
            if not buf:
                return
            if len(buf) == 1:
                loss, _ = self.train_batch(buf[0])
                device_losses.append(jnp.reshape(loss, (1,)))
            else:
                stack = {k: jnp.stack([b[k] for b in buf])
                         for k in buf[0]}
                device_losses.append(self.train_batches(stack))
            buf, buf_key = [], None

        def batch_key(batch):
            # shape AND dtype: same-shape batches of different dtypes
            # must not stack (jnp.stack would silently promote, diverging
            # from the per-batch path).  Attribute reads only — no
            # materializing copies of device-resident values.
            return {k: (np.shape(v), getattr(v, "dtype", None))
                    for k, v in batch.items()}

        for batch in reader():
            key = batch_key(batch)
            if buf and (key != buf_key or len(buf) >= self._FAST_CHUNK):
                flush()
            if not buf:
                buf_key = key
            buf.append(batch)
        flush()
        return [float(v) for chunk in device_losses
                for v in np.asarray(chunk)]

    def train_scan_flops(self, batch_stack: Dict[str, Any]):
        """XLA's FLOP count for ONE batch of the compiled multi-batch
        loop (the while-loop body is counted once, trip-count-invariant)
        — the numerator of MFU.  None when the backend reports no cost
        analysis or no peak is known for the device."""
        from paddle_tpu.utils import mfu as mfu_mod
        if mfu_mod.peak_flops() is None:
            return None          # MFU undefined here; skip the compile
        return mfu_mod.compiled_flops(
            self._train_scan, self.params, self.net_state, self.opt_state,
            self._put(batch_stack, stacked=True), self._step_array())

    def mfu_report(self, batch_stack: Dict[str, Any]) -> Optional[dict]:
        """Model-FLOPs-utilization from XLA's cost analysis of the
        compiled scan body and the OBSERVED ``train_step_seconds``
        average (scan path preferred — it amortizes dispatch; per-batch
        otherwise).  Feeds the ``train_mfu`` / ``train_flops_per_batch``
        gauges and returns ``{"flops_per_batch", "seconds_per_step",
        "mfu"}``, or None when the backend reports no cost analysis /
        no peak (CPU) or nothing has been timed yet."""
        from paddle_tpu.utils import mfu as mfu_mod
        flops = self.train_scan_flops(batch_stack)
        if flops is None:
            return None
        summ = self._m_step.summary(path="scan")
        if not summ["count"]:
            summ = self._m_step.summary(path="batch")
        if not summ["count"]:
            return None
        self.metrics.gauge(
            "train_flops_per_batch",
            "XLA cost-analysis FLOPs of one scanned batch").set(flops)
        value = mfu_mod.mfu(flops, summ["avg"])
        if value is not None:
            self.metrics.gauge(
                "train_mfu",
                "achieved fraction of peak matmul throughput").set(value)
        return {"flops_per_batch": flops,
                "seconds_per_step": summ["avg"], "mfu": value}

    def _put(self, batch, stacked: bool = False):
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self.mesh is not None:
            batch = mesh_lib.shard_batch(batch, self.mesh,
                                         spec=self.batch_spec,
                                         stacked=stacked)
        return batch

    def train(self, reader: Callable[[], Iterable[Dict[str, Any]]],
              num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              evaluators: Sequence[Evaluator] = (),
              test_reader: Optional[Callable] = None,
              save_dir: Optional[str] = None,
              log_period: int = 0,
              stats_period: int = 0) -> Dict[str, Any]:
        """Pass/batch loop with events (SGD.train twin, v2/trainer.py:117).

        Returns the final pass's metrics: mean ``loss`` plus each
        evaluator's result (and ``test_*`` metrics when a test_reader is
        given)."""
        handler = event_handler or (lambda e: None)
        # With no per-batch host consumer (events, evaluators, printing),
        # run each pass through the device-side scan loop: batches chunk
        # into stacks and dispatch as ONE lax.scan call each, and the
        # per-batch float(loss) host sync defers to pass end — the two
        # costs that dominate a tight training loop on remote
        # attachments.
        fast = (event_handler is None and not evaluators
                and log_period == 0 and stats_period == 0
                and not self.average_window)
        results: Dict[str, Any] = {}
        for pass_id in range(num_passes):
            self.current_pass = pass_id
            handler(ev.BeginPass(pass_id))
            for e in evaluators:
                e.start()
            if fast:
                costs = self._train_pass_fast(reader)
            else:
                costs = []
                wants_grads = any(getattr(e, "wants_gradients", False)
                                  for e in evaluators)
                for batch_id, batch in enumerate(reader()):
                    handler(ev.BeginIteration(pass_id, batch_id))
                    if wants_grads:
                        if self.params is None:
                            self.init(batch)
                        # Host snapshot: train_batch donates the param
                        # buffers, which would delete a device alias.
                        params_before = jax.tree_util.tree_map(
                            np.asarray, self.params)
                        grads = self.gradients(batch)
                    loss, outputs = self.train_batch(batch)
                    if wants_grads:
                        outputs = {**outputs, "__gradients__": grads,
                                   "__params__": params_before}
                    for e in evaluators:
                        e.update({**outputs,
                                  **{k: batch[k] for k in batch}})
                    cost = float(loss)
                    costs.append(cost)
                    if log_period and (batch_id + 1) % log_period == 0:
                        print(f"pass {pass_id} batch {batch_id + 1} "
                              f"cost {cost:.6f}", flush=True)
                    if stats_period and (batch_id + 1) % stats_period == 0:
                        # --show_parameter_stats_period twin
                        from paddle_tpu.training import aux as aux_lib
                        print(aux_lib.format_parameter_stats(
                            aux_lib.parameter_stats(self.params)),
                            flush=True)
                    handler(ev.EndIteration(
                        pass_id, batch_id, cost,
                        health=(self.health_monitor.summary()
                                if self.health_monitor is not None
                                else None)))
            results = {e.name: e.finish() for e in evaluators}
            results["loss"] = float(np.mean(costs)) if costs else 0.0
            if test_reader is not None:
                with telemetry.span("trainer/eval", registry=self.metrics,
                                    pass_id=str(pass_id)):
                    results.update(self.test(test_reader, evaluators))
            if save_dir is not None:
                with telemetry.span("trainer/checkpoint",
                                    registry=self.metrics,
                                    pass_id=str(pass_id)):
                    self.save(save_dir, pass_id)
            handler(ev.EndPass(pass_id, results))
        return results

    def test(self, reader, evaluators: Sequence[Evaluator] = (),
             distributed: bool = False):
        """One evaluation pass (Tester::testOnePeriod twin).

        Without evaluators (nothing consumes per-batch outputs on the
        host) the per-batch ``float(loss)`` syncs defer to the end of
        the pass — losses accumulate as device values and transfer once.

        ``distributed=True`` merges each evaluator's statistics AND the
        test cost across all JAX processes before ``finish()`` — the
        reference's ``distributeEval`` (``Evaluator.h:42``) without the
        pserver round-trip.  It is collective: every process must call
        ``test`` with the same evaluator list, each feeding its own
        shard of the eval data.

        Empty-shard hazard: custom evaluators must give every ``STATS``
        attribute its full shape in ``start()`` (zeros are fine, as all
        built-ins do) — NOT lazily on first ``update()``.  A process
        whose eval shard is empty never calls ``update()``; a
        still-``None`` statistic there raises before the collective
        all-gather, and the surviving processes would hang in it.
        """
        for e in evaluators:
            e.start()
        losses = []
        for batch in reader():
            batch = self._put(batch)
            loss, outputs = self._eval_step(self.params, self.net_state,
                                            batch)
            if evaluators:
                losses.append(float(loss))
                for e in evaluators:
                    e.update({**outputs, **{k: batch[k] for k in batch}})
            else:
                losses.append(loss)          # device value; sync below
        has_losses = bool(losses)
        if has_losses and not evaluators:
            losses = np.asarray(jnp.stack(losses))   # ONE host transfer
        if distributed and jax.process_count() > 1:
            from paddle_tpu.training.evaluators import (allgather_sum_f64,
                                                        distribute_eval)
            distribute_eval(evaluators)
            total, count = allgather_sum_f64(np.asarray(
                [float(np.sum(np.asarray(losses, np.float64)))
                 if has_losses else 0.0, float(len(losses))], np.float64))
            results = {f"test_{e.name}": e.finish() for e in evaluators}
            results["test_cost"] = (float(total / count) if count else 0.0)
            return results
        results = {f"test_{e.name}": e.finish() for e in evaluators}
        # float64 mean on both paths (the evaluator path averages Python
        # floats, which numpy accumulates in float64)
        results["test_cost"] = (float(np.mean(losses, dtype=np.float64))
                                if has_losses else 0.0)
        return results

    # ---- persistence (ParamUtil twin) ----

    def save(self, directory: str, pass_id: int,
             metadata: Optional[Dict[str, Any]] = None) -> str:
        trees = {"params": self.params, "net_state": self.net_state,
                 "opt_state": self.opt_state}
        if self.avg_state is not None:
            trees["avg_state"] = self.avg_state
        meta = {"step": self.step, **(metadata or {})}
        return ckpt_lib.save(directory, pass_id, trees, meta)

    def restore(self, directory: str, pass_id: Optional[int] = None) -> int:
        trees, meta = ckpt_lib.load(directory, pass_id)
        as_jnp = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
        self.params = as_jnp(trees["params"])
        self.net_state = as_jnp(trees.get("net_state", {}))
        self.opt_state = as_jnp(trees.get("opt_state", ()))
        if "avg_state" in trees:
            self.avg_state = as_jnp(trees["avg_state"])
        if self.mesh is not None:
            from paddle_tpu.parallel import sharding as sharding_lib
            self.params = sharding_lib.apply_rules(self.params, self.mesh,
                                                   self.param_rules)
            self.net_state = mesh_lib.replicate(self.net_state, self.mesh)
            if self.zero_axis:
                from paddle_tpu.parallel import zero as zero_lib
                self.opt_state = zero_lib.shard_opt_state(
                    self.opt_state, self.mesh, self.zero_axis)
            else:
                self.opt_state = mesh_lib.replicate(self.opt_state, self.mesh)
        self.step = int(meta["metadata"].get("step", meta.get("step", 0)))
        if self._train_step is None:
            self._build_steps()
        return meta["pass_id"]

    def load_v1_params(self, directory: str, name_map=None) -> None:
        """Initialize parameter VALUES from a reference ``pass-%05d/`` dir
        (the v1 trainer's ``--init_model_path`` / ``--start_pass`` artifact,
        ``ParamUtil.h:96-111``).  The trainer must already be ``init``-ed —
        dims live in the config, not the files, so the parameter tree
        supplies the shapes.  Optimizer state is NOT in a v1 pass dir and
        keeps its fresh init.  ``name_map`` (our name -> file name) covers
        artifacts whose reference layer names differ from ours.

        BatchNorm moving statistics — static PARAMETERS in a reference
        pass dir (BatchNormBaseLayer .w1/.w2) but state leaves here —
        import by name match against the same dir; unmatched state warns
        and keeps fresh init (see ``checkpoint.apply_v1_state``)."""
        enforce(self.params is not None,
                "load_v1_params: trainer not initialized — call init() "
                "with a sample batch first (shapes come from the config)")
        loaded = ckpt_lib.load_v1_pass_dir(directory)
        params = ckpt_lib.apply_v1_params(self.params, loaded, name_map)
        new_state, matched = ckpt_lib.apply_v1_state(
            self.net_state, loaded, name_map)
        if matched:
            self.net_state = jax.tree_util.tree_map(jnp.asarray, new_state)
            if self.mesh is not None:
                self.net_state = mesh_lib.replicate(self.net_state,
                                                    self.mesh)
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if self.mesh is not None:
            from paddle_tpu.parallel import sharding as sharding_lib
            params = sharding_lib.apply_rules(params, self.mesh,
                                              self.param_rules)
        self.params = params

    def averaged_params(self):
        if self.avg_state is None:
            return self.params
        return optim_lib.average.averaged_params(self.avg_state, self.params)
