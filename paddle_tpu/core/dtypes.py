"""Dtype policy for TPU execution.

The reference framework is float32-only (optionally float64 via
``WITH_DOUBLE``, ``paddle/math/Matrix.h``).  On TPU the MXU natively consumes
bfloat16, so the idiomatic policy is: *parameters and optimizer state in
float32, matmul/conv compute in bfloat16, reductions and losses in float32*.

A :class:`Policy` bundles the three dtypes.  ``get_policy()`` returns the
process-wide default, switchable with :func:`set_policy` or the
``mixed_precision`` context manager.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator

import jax.numpy as jnp

Dtype = type(jnp.float32)  # loose alias; jnp dtypes are numpy dtype-likes


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: object = jnp.float32
    compute_dtype: object = jnp.float32
    output_dtype: object = jnp.float32

    def cast_to_compute(self, x):
        if x.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
            return x.astype(self.compute_dtype)
        return x

    def cast_to_output(self, x):
        if x.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
            return x.astype(self.output_dtype)
        return x


FLOAT32 = Policy()
# bf16 end-to-end activations: layer outputs STAY bf16 so layer-boundary
# tensors cost half the HBM traffic and no convert passes.  f32 lives in
# islands where numerics demand it — params/optimizer state, BN/LN batch
# statistics, softmax and the loss zoo (each upcasts internally).  An
# f32-output mixed policy was measured 22% MFU on ResNet-50/v5e: every
# layer boundary materialized an f32 copy (15% of step time was standalone
# converts; docs/design/kernels.md has the trace analysis).
MIXED_BF16 = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                    output_dtype=jnp.bfloat16)

_policy: Policy = FLOAT32


def get_policy() -> Policy:
    return _policy


def set_policy(policy: Policy) -> None:
    global _policy
    _policy = policy


@contextlib.contextmanager
def mixed_precision(enabled: bool = True) -> Iterator[None]:
    """Run the enclosed model construction under the bf16 compute policy."""
    global _policy
    prev = _policy
    _policy = MIXED_BF16 if enabled else FLOAT32
    try:
        yield
    finally:
        _policy = prev
