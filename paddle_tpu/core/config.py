"""Typed configuration objects.

TPU-native twin of the reference's protobuf config tier
(``proto/TrainerConfig.proto:21-160``, ``proto/ModelConfig.proto``,
``OptimizerConfig.proto``): plain dataclasses with dict round-tripping so they
serialize into checkpoints (msgpack/json) the way the protos serialized into
model files.  The Python layer DSL builds models directly (no proto
indirection — XLA is the IR), so these configs carry *run* settings rather
than the layer graph.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple


def _asdict(obj) -> Dict[str, Any]:
    return dataclasses.asdict(obj)


@dataclasses.dataclass
class OptimizationConfig:
    """Twin of OptimizationConfig in TrainerConfig.proto + settings() kwargs
    (python/paddle/trainer_config_helpers/optimizers.py:358)."""

    batch_size: int = 32
    learning_rate: float = 0.01
    learning_method: str = "sgd"  # sgd|momentum|adagrad|adadelta|rmsprop|decayed_adagrad|adam|adamax
    momentum: float = 0.0
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    l1_rate: float = 0.0
    l2_rate: float = 0.0
    gradient_clipping_threshold: float = 0.0
    average_window: int = 0
    # Row-lazy sparse updates for embedding-like tables (the reference's
    # sparse_update=True on param attrs + OptimizerWithRegularizerSparse):
    # params matching sparse_patterns get per-row lazy decay + updates.
    sparse_update: bool = False
    sparse_patterns: tuple = ("emb",)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    to_dict = _asdict

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OptimizationConfig":
        return cls(**d)


@dataclasses.dataclass
class TrainerConfig:
    """Run-level settings (twin of TrainerConfig.proto + utils/Flags.cpp)."""

    num_passes: int = 1
    log_period: int = 100
    test_period: int = 0
    saving_period: int = 1
    save_dir: Optional[str] = None
    start_pass: int = 0
    seed: int = 0
    use_bf16: bool = False
    mesh_shape: Tuple[int, ...] = ()
    mesh_axes: Tuple[str, ...] = ()

    to_dict = _asdict

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainerConfig":
        d = dict(d)
        d["mesh_shape"] = tuple(d.get("mesh_shape", ()))
        d["mesh_axes"] = tuple(d.get("mesh_axes", ()))
        return cls(**d)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())
