"""RNG key plumbing.

The reference seeds a global generator per thread (``paddle/math/MathUtils``,
``utils/Util.cpp``).  JAX RNG is explicit and splittable; this module provides
a tiny ``KeySeq`` so imperative-looking code (module init, dropout) can draw
fresh keys deterministically from one root seed.
"""

from __future__ import annotations

import jax


class KeySeq:
    """A mutable stream of PRNG keys derived from one root key."""

    def __init__(self, key_or_seed):
        if isinstance(key_or_seed, int):
            key_or_seed = jax.random.key(key_or_seed)
        self._key = key_or_seed

    def next(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def __next__(self) -> jax.Array:
        return self.next()


def as_key(key_or_seed) -> jax.Array:
    if isinstance(key_or_seed, int):
        return jax.random.key(key_or_seed)
    return key_or_seed
