"""Error checking utilities.

TPU-native twin of the reference's ``PADDLE_ENFORCE`` macro family
(``paddle/platform/enforce.h:62-230`` and ``paddle/utils/Error.h``): a single
``enforce`` callable that raises a rich, framework-branded exception carrying
the failing condition and a formatted message.  Unlike the C++ original there
is no demangled stack trace machinery — Python tracebacks already provide it.
"""

from __future__ import annotations

from typing import Any, NoReturn


class EnforceError(RuntimeError):
    """Raised when an ``enforce`` condition fails (PADDLE_ENFORCE twin)."""


class ConfigError(ValueError):
    """Raised for invalid model/optimizer/trainer configuration."""


def enforce(condition: Any, msg: str = "", *fmt_args: Any) -> None:
    """Raise :class:`EnforceError` unless ``condition`` is truthy.

    ``fmt_args`` are lazily ``%``-formatted into ``msg`` only on failure, so
    hot paths pay nothing for message construction.
    """
    if not condition:
        _fail(msg, *fmt_args)


def _fail(msg: str, *fmt_args: Any) -> NoReturn:
    if fmt_args:
        try:
            msg = msg % fmt_args
        except Exception:  # pragma: no cover - formatting is best effort
            msg = f"{msg} {fmt_args}"
    raise EnforceError(msg or "enforce failed")


def enforce_eq(a: Any, b: Any, msg: str = "") -> None:
    if a != b:
        _fail(f"enforce_eq failed: {a!r} != {b!r}. {msg}")


def enforce_in(value: Any, options: Any, msg: str = "") -> None:
    if value not in options:
        _fail(f"enforce_in failed: {value!r} not in {options!r}. {msg}")


def enforce_rank(x: Any, rank: int, name: str = "tensor") -> None:
    if x.ndim != rank:
        _fail(f"{name} must have rank {rank}, got shape {tuple(x.shape)}")
