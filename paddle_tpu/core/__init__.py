from paddle_tpu.core.errors import EnforceError, ConfigError, enforce, enforce_eq, enforce_in, enforce_rank
from paddle_tpu.core.dtypes import Policy, get_policy, set_policy, mixed_precision, FLOAT32, MIXED_BF16
from paddle_tpu.core.rng import KeySeq, as_key
from paddle_tpu.core.config import OptimizationConfig, TrainerConfig

__all__ = [
    "EnforceError", "ConfigError", "enforce", "enforce_eq", "enforce_in",
    "enforce_rank", "Policy", "get_policy", "set_policy", "mixed_precision",
    "FLOAT32", "MIXED_BF16", "KeySeq", "as_key", "OptimizationConfig",
    "TrainerConfig",
]
