"""Model-FLOPs-utilization instrumentation (SURVEY.md §7 stage 10).

The reference reported throughput (samples/s via ``--job=time``); the
TPU-native quality bar is MFU — the fraction of the chip's peak matmul
throughput the compiled step actually sustains.  FLOP counts come from
XLA's own cost analysis of the compiled executable, so fusion and
rematerialization are accounted for exactly as executed.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

# Peak dense matmul throughput per chip, by device_kind substring.
# bf16 numbers (the compute dtype of the mixed policy); f32 on MXU-less
# paths is not what MFU is about.
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,      # v5e
    "TPU v5": 459e12,           # v5p (checked after the lite variant)
    "TPU v6 lite": 918e12,      # v6e / Trillium
}


def peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s for ``device`` (default: first local device), or
    None when the device kind is unknown (CPU, new TPU generations)."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    # longest match wins so "TPU v5 lite" beats "TPU v5"
    best = None
    for name, flops in _PEAK_FLOPS.items():
        if name in kind and (best is None or len(name) > len(best[0])):
            best = (name, flops)
    return best[1] if best else None


def compiled_cost(fn: Callable, *args, **kwargs) -> dict:
    """``{"flops": float|None, "bytes_accessed": float|None}`` from ONE
    ``lower().compile()`` of ``fn`` — both read from the same XLA cost
    analysis, so callers never pay a second multi-minute compile just
    for the bytes."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    try:
        analyses = compiled.cost_analysis()
    except Exception:
        analyses = None
    # cost_analysis returns one dict (or a per-device list on older jax)
    if isinstance(analyses, (list, tuple)):
        analyses = analyses[0] if analyses else {}
    analyses = analyses or {}
    flops = analyses.get("flops")
    nbytes = analyses.get("bytes accessed")
    return {"flops": float(flops) if flops else None,
            "bytes_accessed": float(nbytes) if nbytes else None}


def compiled_flops(fn: Callable, *args, **kwargs) -> Optional[float]:
    """FLOPs of one execution of ``jit(fn)(*args)`` per XLA's cost
    analysis of the compiled executable; None if the backend does not
    report it."""
    return compiled_cost(fn, *args, **kwargs)["flops"]


def mfu(flops_per_step: float, seconds_per_step: float,
        device=None) -> Optional[float]:
    """Achieved fraction of peak: (FLOPs/step) / (s/step) / peak."""
    peak = peak_flops(device)
    if not peak or seconds_per_step <= 0:
        return None
    return flops_per_step / seconds_per_step / peak


