"""Build-on-demand loader for the native (C++) runtime libraries.

One place owns the compile-if-stale logic for every ``csrc/*.cc`` →
``lib*.so`` pair (recordio, master) so the g++ flags exist exactly once in
Python (mirroring ``csrc/Makefile``) and loading is thread-safe.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_FLAGS = ["-O2", "-fPIC", "-std=c++17", "-pthread", "-shared"]
_lock = threading.Lock()
_cache: Dict[str, ctypes.CDLL] = {}


def python_embed_flags() -> list:
    """Compile/link flags for csrc sources that embed CPython (capi.cc).

    Single source of truth — ``csrc/Makefile`` shells out to this function
    for the same flags, so `make` and the auto-rebuild path link alike.
    """
    import sysconfig

    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return [f"-I{inc}", f"-L{libdir}", f"-Wl,-rpath,{libdir}",
            f"-lpython{ver}", "-ldl", "-lm"]


def load_library(src_name: str, lib_path: str,
                 embed_python: bool = False) -> ctypes.CDLL:
    """Load ``lib_path``, rebuilding from ``csrc/<src_name>`` if the source
    is newer (or the .so is missing).  Cached per path, thread-safe."""
    with _lock:
        if lib_path in _cache:
            return _cache[lib_path]
        src = os.path.join(_CSRC, src_name)
        if (not os.path.exists(lib_path)
                or (os.path.exists(src)
                    and os.path.getmtime(src) > os.path.getmtime(lib_path))):
            extra = python_embed_flags() if embed_python else []
            subprocess.run(["g++", *_FLAGS, "-o", lib_path, src, *extra],
                           check=True, capture_output=True)
        lib = ctypes.CDLL(lib_path)
        _cache[lib_path] = lib
        return lib
