"""Fail-fast guard for device attachment at process entry points.

A wedged TPU attachment blocks inside native PJRT client creation,
where Python signal handlers never run — neither SIGTERM nor a timeout
context can interrupt it, so a daemon timer + ``os._exit`` is the only
clean exit.  Standalone scripts (``bench.py``, ``tpu_smoke.py``) arm
this around their first ``jax.devices()`` call; importing this module
creates no JAX backend.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Union


def attach_watchdog(seconds: float,
                    payload: Union[Dict, List[Dict]]
                    ) -> Callable[[], None]:
    """Print ``payload`` (plus an ``error`` field) as one JSON line — or
    one line per dict when ``payload`` is a list — and hard-exit with
    code 3 unless the returned ``disarm()`` runs within ``seconds``.
    The payload should match the caller's normal output schema so
    downstream parsers see well-formed failure records."""
    armed = threading.Event()
    armed.set()
    payloads = payload if isinstance(payload, list) else [payload]

    def bark():
        if armed.is_set():
            for p in payloads:
                print(json.dumps({
                    **p,
                    "error": f"device attachment did not complete within "
                             f"{seconds:.0f}s"}), flush=True)
            os._exit(3)

    timer = threading.Timer(seconds, bark)
    timer.daemon = True
    timer.start()

    def disarm() -> None:
        armed.clear()
        timer.cancel()

    return disarm
