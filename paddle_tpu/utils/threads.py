"""Per-thread crash backstop over the process-wide ``threading.excepthook``.

A daemon worker that dies to an exception its own try/except never saw
(a raise inside the handler itself, interpreter-teardown races, a
poisoned lock) otherwise prints to stderr and vanishes — the frontend
keeps routing to a seat nobody is pumping.  ``threading.excepthook``
is the only hook Python offers and it is process-global, so this
module owns ONE chained installation: components register a handler
per thread object (``watch_thread``), the hook dispatches to the
owner's handler, then always falls through to whatever hook was
installed before (default: the stderr traceback — the crash stays
visible, it just stops being *silent*).

Handlers run on the dying thread, in exception context: they must not
raise (the dispatcher swallows, so a broken handler cannot eat the
traceback) and should do bounded work — bump a counter, fire the
flight recorder — not resurrection.  Entries are weak: a collected
Thread object drops its handler with it.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable

__all__ = ["watch_thread", "watched_threads"]

_state_lock = threading.Lock()
_handlers: "weakref.WeakKeyDictionary[threading.Thread, Callable]" = \
    weakref.WeakKeyDictionary()
_prev_hook = None
_installed = False


def _hook(args):
    handler = None
    try:
        with _state_lock:
            if args.thread is not None:
                handler = _handlers.get(args.thread)
    except Exception:
        handler = None
    if handler is not None:
        try:
            handler(args)
        except Exception:
            pass          # never shadow the original traceback
    prev = _prev_hook if _prev_hook is not None \
        else threading.__excepthook__
    prev(args)


def watch_thread(thread: threading.Thread,
                 on_crash: Callable) -> None:
    """Arm ``on_crash(args)`` for an uncaught exception escaping
    ``thread`` (``args`` is ``threading.ExceptHookArgs``).  Installs
    the chained process hook on first use; re-registering a thread
    replaces its handler."""
    global _installed, _prev_hook
    with _state_lock:
        if not _installed:
            _prev_hook = threading.excepthook
            threading.excepthook = _hook
            _installed = True
        _handlers[thread] = on_crash


def watched_threads():
    """Live registered threads (tests / introspection)."""
    with _state_lock:
        return list(_handlers.keys())
