"""Device profiling hooks.

Twin of ``hl_profiler_start/end`` (``cuda/include/hl_cuda.h:338-343``, which
gated nvprof capture): thin wrappers over the JAX/XLA profiler producing
XPlane traces viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


def start(logdir: str) -> None:
    jax.profiler.start_trace(logdir)


def stop() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    start(logdir)
    try:
        yield
    finally:
        stop()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region in the device trace (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield
