"""Device profiling hooks — DEPRECATED shim over ``paddle_tpu.telemetry``.

The original twin of ``hl_profiler_start/end``
(``cuda/include/hl_cuda.h:338-343``) lives on in
``paddle_tpu.telemetry.spans``: ``annotate`` is now ``telemetry.span``
(same context-manager contract, plus the region's wall time lands in the
``span_seconds`` histogram), and ``start``/``stop``/``trace`` re-export
the XPlane capture wrappers unchanged.  Import from
``paddle_tpu.telemetry`` in new code; this module stays only so existing
call sites keep working.
"""

from __future__ import annotations

import warnings

from paddle_tpu.telemetry.spans import span as annotate
from paddle_tpu.telemetry.spans import start, stop, trace

__all__ = ["start", "stop", "trace", "annotate"]

warnings.warn(
    "paddle_tpu.utils.profiler is deprecated; import span/start/stop/"
    "trace from paddle_tpu.telemetry instead",
    DeprecationWarning, stacklevel=2)
