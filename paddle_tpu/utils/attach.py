"""Device-attachment probe with a hard-kill timeout and one retry.

A wedged PJRT attach (libtpu held by a dying process, a mid-repair
pod) hangs ``jax.devices()`` forever and ignores SIGTERM — BENCH_r04
was lost to exactly this.  The cure, proven in ``bench.py`` (VERDICT
r4 #2): probe the attach in a SUBPROCESS first, SIGKILL it past the
timeout, back off once, retry once.  Only after the probe succeeds
does the caller touch the device from its own process.

Shared here so every benchmark harness (``bench.py``,
``benchmark/lm_decode.py``) uses the identical protocol instead of
each growing its own — the ROADMAP measurement item asks for this
reuse by name.
"""

from __future__ import annotations

import subprocess
import sys
import time

ATTACH_TIMEOUT = 240.0   # seconds before the probe is hard-killed
RETRY_BACKOFF = 30.0     # seconds between the two attempts


def attach_probe_with_retry(*, require_tpu: bool,
                            timeout: float = ATTACH_TIMEOUT,
                            backoff: float = RETRY_BACKOFF) -> bool:
    """Probe ``jax.devices()`` in a subprocess; retry once after
    ``backoff`` seconds.  Returns True when a probe attached in time.

    ``require_tpu=True`` additionally demands the tpu backend: a silent
    CPU fallback during an outage must NOT count as attached, or
    chipless numbers would be recorded as TPU results.  Harnesses whose
    rows carry the backend explicitly (``lm_decode``) pass False.
    """
    for attempt in (1, 2):
        p = subprocess.Popen(
            [sys.executable, "-c",
             "import paddle_tpu, jax, sys; jax.devices(); "
             "sys.exit(0 if jax.default_backend() == 'tpu' "
             f"or {not require_tpu} else 4)"])
        try:
            if p.wait(timeout=timeout) == 0:
                return True
        except subprocess.TimeoutExpired:
            p.kill()         # SIGKILL: a blocked PJRT attach ignores TERM
            p.wait()
        if attempt == 1:
            # stderr: stdout carries only schema-conforming rows
            print("attach probe failed; retrying once after "
                  f"{backoff:.0f}s backoff", file=sys.stderr, flush=True)
            time.sleep(backoff)
    return False
