"""Training-curve plotting (``python/paddle/v2/plot/plot.py`` twin).

The reference's ``Ploter`` collects per-step costs and redraws a matplotlib
figure from event handlers; headless runs fall back to appending values to
a log.  Same shape here: matplotlib is optional (this image has no display),
and the data is always retained so tests and notebooks can read it back.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["Ploter"]


class PlotData:
    def __init__(self):
        self.step: List[int] = []
        self.value: List[float] = []

    def append(self, step: int, value: float) -> None:
        self.step.append(int(step))
        self.value.append(float(value))

    def reset(self) -> None:
        self.step = []
        self.value = []


class Ploter:
    """``Ploter("train_cost", "test_cost")`` — call ``append(title, step,
    value)`` from event handlers and ``plot()`` to draw/save."""

    def __init__(self, *titles: str):
        self.__args__ = titles
        self.__plot_data__: Dict[str, PlotData] = {t: PlotData()
                                                   for t in titles}
        self._disabled = bool(os.environ.get("DISABLE_PLOT"))
        try:  # headless-safe matplotlib import
            import matplotlib
            if not os.environ.get("DISPLAY"):
                # Only force the file-only backend when there is no
                # display; never hijack an interactive/notebook backend.
                matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            self._plt = plt
        except Exception:
            self._plt = None

    def append(self, title: str, step: int, value: float) -> None:
        assert title in self.__plot_data__, (
            f"unknown curve {title!r}; have {list(self.__plot_data__)}")
        self.__plot_data__[title].append(step, value)

    def data(self, title: str) -> PlotData:
        return self.__plot_data__[title]

    def plot(self, path: Optional[str] = None) -> None:
        """Draw all curves; save to ``path`` when given (headless mode
        without a path is a no-op beyond data retention)."""
        if self._plt is None or self._disabled:
            return
        self._plt.clf()
        for title, d in self.__plot_data__.items():
            if d.step:
                self._plt.plot(d.step, d.value, label=title)
        self._plt.legend()
        self._plt.xlabel("step")
        if path:
            self._plt.savefig(path)
        elif os.environ.get("DISPLAY"):
            self._plt.draw()
            self._plt.pause(0.001)

    def reset(self) -> None:
        for d in self.__plot_data__.values():
            d.reset()
