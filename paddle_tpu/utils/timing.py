"""Differential throughput timing (the --job=time measurement core).

Why differential: ``block_until_ready`` is not a trustworthy execution
barrier on every transport (remote/tunneled TPU attachments may report
readiness before execution finishes), and a host transfer per run pays a
constant control-channel round trip.  Timing N and 4N batches, each ended
by ONE host transfer of the final loss, and reporting
``(T(4N) - T(N)) / 3N`` cancels every constant cost and measures the
marginal execution time of one training batch — on a directly-attached
chip this equals device step time.  Used by both ``bench.py`` and the
CLI's ``time`` job so the protocol cannot drift between them.
"""

from __future__ import annotations

import statistics
import time
from typing import Callable, Iterator, Tuple


def timed_run(step_fn: Callable[[], object], n: int) -> float:
    """Wall time of ``n`` calls of ``step_fn`` ended by a host sync on the
    last returned loss.  ``n`` == 0 times just the sync when a loss is
    available (returns ~0 otherwise)."""
    t0 = time.perf_counter()
    loss = None
    for _ in range(n):
        loss = step_fn()
    if loss is not None:
        float(loss)  # host transfer: provably waits for execution
    return time.perf_counter() - t0


def marginal_ms_per_batch(step_fn: Callable[[], object], n: int = 10,
                          repeats: int = 3) -> float:
    """Differential timing: median over ``repeats`` of paired
    ``(T(4n) - T(n)) / 3n`` ms.

    The arms of each difference run back-to-back (paired) so slow-drifting
    transport congestion cancels; taking independent minima per arm would
    let a lucky window on one arm fabricate an arbitrarily small (or
    large) difference.  Negative per-pair diffs (jitter spikes on the
    small arm) stay in the sample so they cancel in the median; only the
    final result is floored.  Odd default ``repeats`` keeps the median a
    real order statistic."""
    return marginal_ms_with_spread(step_fn, n, repeats)[0]


def marginal_ms_with_spread(step_fn: Callable[[], object], n: int = 10,
                            repeats: int = 3) -> tuple:
    """(median, half-RANGE) of the paired differences — a conservative
    noise quote for the benchmark tables ((max-min)/2 over the repeats;
    None with a single repeat, where no spread was measured)."""
    n = max(n, 1)
    diffs = []
    for _ in range(max(repeats, 1)):
        t_small = timed_run(step_fn, n)
        t_large = timed_run(step_fn, 4 * n)
        diffs.append((t_large - t_small) / (3 * n) * 1000.0)
    med = max(statistics.median(diffs), 1e-9)
    # Half-range for every sample count (scale-consistent across
    # --repeats values); None when a single repeat measured no spread.
    spread = ((max(diffs) - min(diffs)) / 2.0
              if len(diffs) >= 2 else None)
    return med, spread
