"""Scoped timers with global aggregation.

Twin of the reference's ``REGISTER_TIMER``/``StatSet`` profiling
(``paddle/utils/Stat.h:63-234``, dumped by
``globalStat.printSegTimerStatus()``; used by ``--job=time``): named scope
timers accumulate count/total/max/min into a process-global registry, and
``print_status()`` dumps the table.  On-device time is covered by the JAX
profiler (see ``paddle_tpu.utils.profiler``); these timers measure host-side
phases (data feed, step dispatch, checkpoint IO).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator


class _TimerStat:
    __slots__ = ("count", "total", "max", "min")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")

    def add(self, dt: float) -> None:
        self.count += 1
        self.total += dt
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)


class StatSet:
    def __init__(self, name: str = "global"):
        self.name = name
        self._stats: Dict[str, _TimerStat] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats.setdefault(name, _TimerStat()).add(dt)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def status(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {"count": s.count, "total_ms": s.total * 1e3,
                       "avg_ms": s.total / max(s.count, 1) * 1e3,
                       "max_ms": s.max * 1e3, "min_ms": s.min * 1e3}
                for name, s in self._stats.items()
            }

    def print_status(self) -> None:
        rows = self.status()
        if not rows:
            return
        width = max(len(n) for n in rows)
        print(f"===== StatSet[{self.name}] =====")
        print(f"{'name':<{width}}  {'count':>8} {'total(ms)':>12} "
              f"{'avg(ms)':>10} {'max(ms)':>10} {'min(ms)':>10}")
        for name, s in sorted(rows.items()):
            print(f"{name:<{width}}  {s['count']:>8} {s['total_ms']:>12.2f} "
                  f"{s['avg_ms']:>10.3f} {s['max_ms']:>10.3f} "
                  f"{s['min_ms']:>10.3f}")


global_stat = StatSet()
timer = global_stat.timer
