from paddle_tpu.utils.stat import StatSet, global_stat, timer

__all__ = ["StatSet", "global_stat", "timer", "profiler"]


def __getattr__(name):
    # profiler is a deprecated shim (warns on import) — load it lazily
    # so merely importing paddle_tpu.utils stays warning-free.
    if name == "profiler":
        import importlib
        return importlib.import_module("paddle_tpu.utils.profiler")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
