from paddle_tpu.utils.stat import StatSet, global_stat, timer
from paddle_tpu.utils import profiler

__all__ = ["StatSet", "global_stat", "timer", "profiler"]
