"""SLO-aware serving front-end: deadlines, shedding, engine supervision.

The reference Paddle snapshot's cloud runtime (go/master + go/pserver
over etcd) is organized around one idea: WORK OUTLIVES WORKERS.  The
master journals task leases; a dead trainer's pending tasks go back on
the todo queue and are retried with backoff; the service degrades under
load instead of falling over.  :class:`ServingFrontend` is that idea
applied to our serving stack — it turns "a
:class:`~paddle_tpu.serving.PagedServingEngine`" into "a service":

* **Deadlines + priorities.**  Every request carries an optional
  completion deadline and an integer priority class.  Admission
  predicts the queue delay of the best engine from live telemetry
  (queue-wait / TTFT / per-token histograms each engine already
  records) and REJECTS a request that cannot meet its deadline instead
  of queuing it to die (``SubmitRejected(reason="deadline_unmeetable")``).
  A bounded frontend queue sheds the LOWEST-priority queued request to
  make room for a higher-priority arrival, and rejects equal-or-lower
  arrivals with ``reason="queue_full"``.
* **Supervision.**  Each engine runs on its own worker thread (a seat).
  A watchdog in the supervisor loop reads each seat's heartbeat (the
  engine's ``host_state()['last_step_wall']`` twin lives on the seat)
  and a ``step()``-in-progress timestamp: an engine exception or a
  step that exceeds ``hang_timeout_s`` fires the flight recorder (the
  frontend's tracer dumps the hung engine's ``host_state()``), takes
  the seat down, and schedules a replacement engine with CAPPED
  EXPONENTIAL BACKOFF.  A replacement failing to construct (the
  ``attach`` fault point) just reschedules — repeated-restart chaos is
  a tested scenario, not an outage.
* **Journal + replay.**  The frontend journals every request's prompt,
  sampling parameters and priority at submit.  When a seat dies, its
  non-terminal requests are REQUEUED from the journal (attempts capped
  by ``max_retries``, then ``FAILED``) and rerun from scratch on a
  replacement engine built with the same config and seed.  Greedy
  decode (``temperature=0``) is a pure argmax — the engine's rng key
  never touches the stream — so a retried greedy request's tokens are
  BIT-IDENTICAL to a fault-free run (the chaos gate pins this).
  Sampled streams depend on the engine rng's slot interleaving, so
  replay determinism is only guaranteed for greedy decode.
* **Exactly-once terminal status.**  Every submitted request ends in
  exactly one of ``completed`` / ``shed`` / ``failed``.  Completions
  from a replaced engine generation are discarded (the requeued copy
  is the one that counts), and ``_finalize`` asserts a request is
  never terminated twice — the invariant the seeded chaos property
  test (``tests/test_frontend.py``) sweeps fault schedules against.

The frontend is HOST CODE ONLY: it never touches a traced program, so
``compiles == {'step': 1}`` holds per engine with the frontend on,
and with one engine and no faults the per-request token streams are
byte-for-byte the direct-engine behavior.

Metrics land in ``frontend_*`` families (catalog:
``docs/design/telemetry.md``); each seat's engine gets its OWN
:class:`~paddle_tpu.telemetry.MetricsRegistry` (``engine0``,
``engine1``, ...) so per-engine telemetry never aliases across seats —
that per-seat registry is also what admission reads its predictions
from.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu import telemetry
from paddle_tpu.core.errors import enforce
from paddle_tpu.serving import PagedServingEngine, QueueFull
from paddle_tpu.utils.threads import watch_thread

__all__ = ["ServingFrontend", "SubmitRejected",
           "disaggregated_frontend",
           "QUEUED", "RUNNING", "COMPLETED", "SHED", "FAILED",
           "TERMINAL"]

# Request lifecycle.  QUEUED = journaled, waiting for a seat; RUNNING =
# handed to an engine (its inbox, queue or a slot); the rest are the
# three terminal states every request reaches EXACTLY ONCE.
QUEUED = "queued"
RUNNING = "running"
COMPLETED = "completed"
SHED = "shed"
FAILED = "failed"
TERMINAL = frozenset({COMPLETED, SHED, FAILED})

#: Reasons a submit() raises SubmitRejected / a queued request is shed.
REJECT_REASONS = ("queue_full", "deadline_unmeetable", "too_large")
SHED_REASONS = ("deadline", "preempted")


class SubmitRejected(RuntimeError):
    """Typed submit-time rejection — the load-shedding signal.

    ``reason`` is one of :data:`REJECT_REASONS`: ``queue_full`` (the
    bounded frontend queue is full of equal-or-higher priority work),
    ``deadline_unmeetable`` (predicted completion time exceeds the
    request's deadline), ``too_large`` (the request could never fit any
    engine's buckets / per-slot capacity / pool — rejecting here keeps
    an impossible request from crash-looping every seat)."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"submit rejected ({reason})"
                         + (f": {detail}" if detail else ""))


class _FrontendRequest:
    """One journaled request: everything needed to replay it from
    scratch on a replacement engine, plus its lifecycle bookkeeping."""

    __slots__ = ("rid", "prompt", "max_new", "temperature", "priority",
                 "deadline_s", "deadline_at", "submitted_at", "status",
                 "reason", "tokens", "attempts", "engine", "assigned_at",
                 "finished_at", "deadline_missed", "tenant", "adapter")

    def __init__(self, rid, prompt, max_new, temperature, priority,
                 deadline_s, tenant=None, adapter=None):
        self.rid = rid
        self.prompt = prompt              # np.int32 copy: THE journal
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.priority = int(priority)
        self.deadline_s = deadline_s
        # tenant/adapter routing rides the journal: a replay after an
        # engine restart re-submits with the SAME adapter, so the
        # replacement stream is still the original's bit-identical twin
        self.tenant = tenant
        self.adapter = adapter
        self.submitted_at = time.perf_counter()
        self.deadline_at = (None if deadline_s is None
                            else self.submitted_at + float(deadline_s))
        self.status = QUEUED
        self.reason = None                # terminal detail string
        self.tokens = None                # np.ndarray once COMPLETED
        self.attempts = 0                 # completed execution attempts
        self.engine = None                # seat index while RUNNING
        self.assigned_at = None
        self.finished_at = None
        self.deadline_missed = False

    def record(self) -> dict:
        """The JSON-ish view callers get back (tokens stay ndarray)."""
        return {"status": self.status, "tokens": self.tokens,
                "reason": self.reason, "attempts": self.attempts,
                "priority": self.priority, "engine": self.engine,
                "deadline_s": self.deadline_s,
                "deadline_missed": self.deadline_missed,
                "tenant": self.tenant, "adapter": self.adapter}


# Seat states.  A seat is the supervisor's stable handle on "engine
# slot i" — engines come and go (restarts), the seat persists.
_UP = "up"
_DOWN = "down"


class _Seat:
    __slots__ = ("index", "label", "state", "engine", "generation",
                 "thread", "inbox", "assigned", "wake", "crash",
                 "step_started_at", "last_beat", "restarts",
                 "restart_at", "registry", "avg_service_s",
                 "avg_tokens", "warmed", "adapters_seen")

    def __init__(self, index: int, registry):
        self.index = index
        self.label = f"engine{index}"
        self.state = _DOWN
        self.engine = None
        self.generation = 0               # bumped on every takedown
        self.thread = None
        self.inbox: deque = deque()       # assigned, not yet submitted
        self.assigned: set = set()        # frontend rids on this seat
        self.wake = threading.Event()
        self.crash = None                 # exception from the worker
        self.step_started_at = None       # perf_counter at step entry
        self.last_beat = 0.0              # perf_counter after any step
        self.restarts = 0
        self.restart_at = 0.0             # perf_counter gate for retry
        self.registry = registry          # per-seat MetricsRegistry
        # EMAs the router's prediction model falls back on (seconds per
        # completed request on this seat / tokens per completed stream)
        self.avg_service_s = None
        self.avg_tokens = None
        # a fresh engine's FIRST step jit-compiles (every restart
        # recompiles: new jit objects) — the watchdog widens its hang
        # bound until this flips
        self.warmed = False
        # adapter names this seat's engine has loaded (router affinity:
        # a request for a seen adapter prefers this seat — resident-hit
        # over a host-load miss).  Advisory only; the engine's own
        # registry LRU may have evicted it, in which case the engine
        # just re-loads (a miss, not an error).
        self.adapters_seen: set = set()


class ServingFrontend:
    """Supervise ``num_engines`` paged serving engines as ONE service.

    Construction mirrors :class:`~paddle_tpu.serving.PagedServingEngine`
    (``num_slots`` .. ``prefix_cache``, ``spec`` — a
    :class:`~paddle_tpu.serving.SpecConfig` turns on speculative
    decoding — and ``adapters``/``adapter_rank``/``adapter_source``
    for the multi-tenant LoRA pool are forwarded to every seat's
    engine, each built with the SAME ``seed`` so a replacement engine
    is the journal-replay twin of the one it replaces;
    deadline/admission prediction then reads each seat's live
    tokens-per-step rate, see :meth:`_service_estimate_locked`).
    Frontend-level knobs:

    ``tenant_slo``
        Per-tenant SLO classes, ``{tenant: {"priority": int,
        "deadline_s": float}}``: submit() defaults for requests that
        name the tenant but pass neither knob explicitly (explicit
        values always win).  Tenants not in the map behave exactly as
        before — priority 1, no deadline.

    ``max_queue``
        Bound on frontend-queued requests (``None`` = unbounded).  At
        the bound, a new arrival preempts the lowest-priority queued
        request if strictly lower-priority than itself (that victim is
        shed with ``reason="preempted"``); otherwise the arrival is
        rejected ``queue_full``.
    ``engine_max_queue``
        Forwarded per-engine submit bound (the engine's own typed
        :class:`~paddle_tpu.serving.QueueFull` backpressure); the
        worker catches it and bounces the request back to the frontend
        queue — it is flow control, not a failure.
    ``hang_timeout_s``
        Watchdog bound on a single ``step()``: a step in flight longer
        than this declares the engine hung.  A fresh engine's FIRST
        step jit-compiles (every restart recompiles — new jit
        objects), so until an engine completes a step the bound is
        ``max(hang_timeout_s, first_step_grace_s)``; a hang injected on
        a first step is instead unwound by the injector's
        ``max_hang_s`` and surfaces as a crash.
    ``restart_backoff_s`` / ``restart_backoff_cap_s``
        Capped exponential backoff between an engine's takedown and its
        replacement attempt (doubles per consecutive restart).
    ``max_retries``
        Execution attempts per request beyond the first; a request
        requeued more than this many times is ``FAILED``
        (``reason="retries_exhausted"``).
    ``faults``
        A :class:`~paddle_tpu.testing.faults.FaultInjector`; each seat's
        engine fires its injection points under the seat's scope label
        (``engine0``, ...), and a hang takedown releases injected hangs
        so the stale worker unwinds.

    Drive it like the engine: ``submit(...)`` then ``run()`` (the
    supervisor loop runs in the calling thread until every journaled
    request is terminal) — or call ``pump()`` yourself.  ``close()``
    stops the worker threads; the frontend is a context manager.
    """

    def __init__(self, cfg, params, *, num_engines: int = 1,
                 num_slots: int, num_blocks: int, block_size: int = 16,
                 max_blocks_per_slot: Optional[int] = None,
                 prompt_buckets=(64,), eos_id: Optional[int] = None,
                 top_k=None, top_p=None, attn_fn=None, seed: int = 0,
                 decode_kernel=None, prefix_cache: bool = False,
                 spec=None, adapters: Optional[int] = None,
                 adapter_rank: int = 8, adapter_source=None,
                 tenant_slo=None,
                 engine_max_queue: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 hang_timeout_s: float = 10.0,
                 first_step_grace_s: float = 120.0,
                 restart_backoff_s: float = 0.05,
                 restart_backoff_cap_s: float = 2.0,
                 max_retries: int = 3,
                 metrics=None, tracer=None,
                 flight_recorder: Optional[str] = None,
                 flight_window_s: float = 30.0,
                 http_port: Optional[int] = None,
                 faults=None):
        enforce(num_engines >= 1, "frontend needs at least one engine, "
                "got num_engines=%s", num_engines)
        enforce(max_queue is None or max_queue >= 1,
                "max_queue must be None (unbounded) or >= 1, got %s",
                max_queue)
        enforce(max_retries >= 0, "max_retries must be >= 0, got %s",
                max_retries)
        self.cfg = cfg
        self.params = params
        self.num_engines = int(num_engines)
        self.num_slots = int(num_slots)
        self.max_queue = max_queue
        self.hang_timeout_s = float(hang_timeout_s)
        self.first_step_grace_s = float(first_step_grace_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_cap_s = float(restart_backoff_cap_s)
        self.max_retries = int(max_retries)
        self._faults = faults
        # engine capacity contract, precomputed so submit() can reject
        # an impossible request as too_large instead of letting it
        # crash-loop every seat it is ever dispatched to
        self._buckets = tuple(sorted(prompt_buckets))
        maxb = (max_blocks_per_slot if max_blocks_per_slot
                else -(-cfg.max_len // block_size))
        self._cap = min(cfg.max_len, maxb * block_size)
        self._bs = int(block_size)
        self._nb = int(num_blocks)
        self._prefix = bool(prefix_cache)
        self._engine_kwargs = dict(
            num_slots=num_slots, num_blocks=num_blocks,
            block_size=block_size,
            max_blocks_per_slot=max_blocks_per_slot,
            prompt_buckets=prompt_buckets, eos_id=eos_id, top_k=top_k,
            top_p=top_p, attn_fn=attn_fn, seed=seed,
            decode_kernel=decode_kernel, prefix_cache=prefix_cache,
            spec=spec, adapters=adapters, adapter_rank=adapter_rank,
            adapter_source=adapter_source, max_queue=engine_max_queue)
        self._adapters_on = adapters is not None
        # per-tenant SLO classes: {tenant: {"priority": int,
        # "deadline_s": float}} defaults applied at submit when the
        # caller passes neither explicitly; unknown tenants fall back
        # to priority 1 / no deadline, same as before
        self._tenant_slo = {k: dict(v)
                            for k, v in (tenant_slo or {}).items()}

        self._lock = threading.RLock()
        self._requests: Dict[int, _FrontendRequest] = {}   # the journal
        self._queue: List[int] = []       # frontend-queued rids
        self._done_events: deque = deque()  # (gen, seat, rid, tokens)
        self._next_rid = 0
        self._stopping = False
        self._zombies: List[threading.Thread] = []

        self.metrics = (metrics if metrics is not None
                        else telemetry.get_registry())
        if tracer is None and flight_recorder is not None:
            tracer = telemetry.Tracer(
                name="frontend", flight_path=flight_recorder,
                flight_window_s=flight_window_s)
        elif tracer is not None and flight_recorder is not None:
            tracer.flight_path = flight_recorder
            tracer.flight_window_s = float(flight_window_s)
        self.tracer = tracer

        m = self.metrics
        self._m_submitted = m.counter(
            "frontend_submitted_total",
            help="requests accepted into the frontend journal")
        self._m_shed = m.counter(
            "frontend_shed_total",
            help="requests dropped by the frontend, by reason "
                 "(queue_full|deadline_unmeetable|too_large at submit; "
                 "deadline|preempted from the queue)")
        self._m_completed = m.counter(
            "frontend_completed_total", help="requests completed")
        self._m_failed = m.counter(
            "frontend_failed_total",
            help="requests terminally failed, by reason")
        self._m_retries = m.counter(
            "frontend_retries_total",
            help="journal-replay requeues after an engine takedown")
        self._m_restarts = m.counter(
            "frontend_engine_restarts_total",
            help="engine takedowns, by cause=crash|hang|attach and "
                 "engine seat")
        self._m_deadline_miss = m.counter(
            "frontend_deadline_miss_total",
            help="requests that COMPLETED after their deadline (shed "
                 "requests count under frontend_shed_total instead)")
        self._m_thread_crashes = m.counter(
            "frontend_thread_crashes_total",
            help="uncaught exceptions that escaped a worker thread "
                 "entirely (past its own crash parking) — each fires "
                 "the armed flight recorder via threading.excepthook")
        self._m_queue_g = m.gauge(
            "frontend_queue_depth", help="frontend-queued requests")
        self._m_live_g = m.gauge(
            "frontend_engines_live", help="seats with a live engine")
        self._m_predicted = m.histogram(
            "frontend_predicted_wait_seconds",
            help="admission's predicted completion time per accepted "
                 "request (queue delay + service estimate)")
        self._m_request = m.histogram(
            "frontend_request_seconds",
            help="submit -> terminal status, any outcome")

        # Seats last: engine construction can fire the attach fault,
        # and a seat that fails to come up must already have its
        # backoff/telemetry plumbing in place.
        self._seats = [
            _Seat(i, telemetry.MetricsRegistry(name=f"engine{i}"))
            for i in range(self.num_engines)]
        for seat in self._seats:
            self._seat_start(seat)
        # live scrape surface (telemetry/httpd.py): /metrics merges
        # the frontend registry with every seat's engine registry
        # under seat= labels; /healthz flips to 503 whenever any seat
        # is down (crash-parked or restarting).  Handler threads call
        # only locked/thread-safe methods — see each _http_* callback.
        self._httpd = None
        if http_port is not None:
            from paddle_tpu.telemetry.httpd import TelemetryHTTPD
            self._httpd = TelemetryHTTPD(
                port=int(http_port),
                metrics_fn=self._http_metrics,
                healthz_fn=self._http_healthz,
                traces_fn=self._http_traces,
                state_fn=self._http_state)

    # ------------------------------------------------------------ submit

    def submit(self, prompt_ids, max_new: int, temperature: float = 0.0,
               *, priority: Optional[int] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None) -> int:
        """Journal one request; returns its frontend rid.

        ``priority`` — larger is MORE important; it orders dispatch and
        decides who is shed under overload.  ``deadline_s`` — seconds
        from now by which the request should COMPLETE; admission
        rejects it if the predicted completion time already exceeds the
        deadline, and a queued request is shed the moment its deadline
        passes.  Once dispatched to an engine a request runs to
        completion — a late finish counts a deadline miss, not a shed.

        ``tenant`` names the request's SLO class: when ``priority`` /
        ``deadline_s`` are not passed explicitly, the tenant's defaults
        from the constructor's ``tenant_slo`` map apply (explicit
        always wins; unknown tenants get priority 1, no deadline).
        ``adapter`` routes the request through that LoRA adapter on
        the engine (requires ``adapters=`` at construction); routing
        prefers a seat that has already loaded it.  Both ride the
        journal, so replay after an engine restart preserves them.

        Raises :class:`SubmitRejected` (``reason`` in
        :data:`REJECT_REASONS`) instead of queuing work it already
        knows it will drop."""
        enforce(adapter is None or self._adapters_on,
                "submit(adapter=%r) on a frontend built without an "
                "adapter pool — pass adapters= at construction",
                adapter)
        slo = self._tenant_slo.get(tenant, {})
        if priority is None:
            priority = slo.get("priority", 1)
        if deadline_s is None:
            deadline_s = slo.get("deadline_s")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1).copy()
        n = int(prompt.shape[0])
        reason = self._size_reject(n, max_new)
        if reason is not None:
            self._shed_metric("too_large")
            raise SubmitRejected("too_large", reason)
        with self._lock:
            if self._stopping:
                raise RuntimeError("frontend is closed")
            est = None
            if deadline_s is not None:
                est = self._predicted_completion_locked(int(max_new))
                if deadline_s <= 0 or est > float(deadline_s):
                    self._shed_metric("deadline_unmeetable")
                    if self.tracer is not None:
                        self.tracer.instant(
                            "shed", track="frontend",
                            reason="deadline_unmeetable",
                            predicted_s=est, deadline_s=deadline_s)
                    raise SubmitRejected(
                        "deadline_unmeetable",
                        f"predicted completion {est:.3f}s > deadline "
                        f"{deadline_s}s")
            if self.max_queue is not None \
                    and len(self._queue) >= self.max_queue:
                victim = min(
                    (self._requests[r] for r in self._queue),
                    key=lambda q: (q.priority, -q.rid), default=None)
                if victim is None or victim.priority >= int(priority):
                    self._shed_metric("queue_full")
                    if self.tracer is not None:
                        self.tracer.instant(
                            "shed", track="frontend",
                            reason="queue_full",
                            queued=len(self._queue))
                    raise SubmitRejected(
                        "queue_full",
                        f"{len(self._queue)} queued >= max_queue "
                        f"{self.max_queue}")
                # lowest priority goes first — the arrival outranks it
                self._queue.remove(victim.rid)
                self._finalize_locked(victim, SHED, reason="preempted")
            rid = self._next_rid
            self._next_rid += 1
            req = _FrontendRequest(rid, prompt, max_new, temperature,
                                   priority, deadline_s,
                                   tenant=tenant, adapter=adapter)
            self._requests[rid] = req
            self._queue.append(rid)
            self._m_submitted.inc()
            if est is not None:
                self._m_predicted.observe(est)
            if self.tracer is not None:
                extra = {}
                if tenant is not None:
                    extra["tenant"] = tenant
                if adapter is not None:
                    extra["adapter"] = adapter
                self.tracer.instant(
                    "submit", track="frontend", rid=rid,
                    prompt_len=n, max_new=int(max_new),
                    priority=int(priority), deadline_s=deadline_s,
                    **extra)
            return rid

    def _size_reject(self, n: int, max_new: int) -> Optional[str]:
        """The engine capacity contract, checked up front (None = ok)."""
        if n < 1:
            return "empty prompt"
        if not any(n <= w for w in self._buckets):
            return (f"prompt length {n} exceeds every prefill bucket "
                    f"{self._buckets}")
        if max_new < 1 or n + max_new > self._cap:
            return (f"prompt {n} + max_new {max_new} exceeds per-slot "
                    f"capacity {self._cap}")
        worst = -(-(n + max_new) // self._bs) + (1 if self._prefix
                                                else 0)
        if worst > self._nb:
            return (f"worst case {worst} blocks exceeds the pool "
                    f"({self._nb})")
        return None

    def _shed_metric(self, reason: str):
        self._m_shed.inc(reason=reason)

    # -------------------------------------------------------- prediction

    def _service_estimate_locked(self, seat: _Seat,
                                 max_new: int) -> float:
        """Expected on-engine seconds for one request on this seat,
        from its live telemetry: prefill ≈ avg(TTFT) - avg(queue wait),
        decode ≈ max_new × avg(per-token) (falling back to avg step
        time, then the seat's completed-request EMA).  Cold seats
        estimate 0 — admission stays open until there is evidence.

        SPECULATIVE engines commit more than one token per step, so
        the step-time fallback divides by the seat's LIVE
        tokens-per-step rate (``serving_spec_tokens_per_step``) —
        assuming 1 token/step would overshoot every estimate by the
        acceptance speedup and shed load the engine could serve.  The
        primary per-token signal (``serving_time_per_output_token``)
        is wall-time over tokens at retire, already spec-correct."""
        reg = seat.registry
        ttft = reg.histogram("serving_ttft_seconds").summary()
        qw = reg.histogram("serving_queue_wait_seconds").summary()
        tpot = reg.histogram(
            "serving_time_per_output_token_seconds").summary()
        step = reg.histogram("serving_step_seconds").summary()
        # reg.get, not reg.histogram: the engine registers this with
        # k-dependent buckets; a non-spec seat simply lacks it
        tps_h = reg.get("serving_spec_tokens_per_step")
        tps = (tps_h.summary()["avg"] if tps_h is not None
               else None) or 1.0
        prefill = max(0.0, (ttft["avg"] or 0.0) - (qw["avg"] or 0.0))
        per_tok = tpot["avg"] or ((step["avg"] or 0.0) / max(tps, 1.0))
        est = prefill + per_tok * max_new
        if est <= 0.0 and seat.avg_service_s is not None:
            est = seat.avg_service_s
        return est

    def _predicted_wait_locked(self, seat: _Seat) -> float:
        """Predicted queue delay for a NEW request on this seat: how
        many full service waves are already committed ahead of it.  A
        seat with a free slot predicts 0; a down seat predicts inf."""
        if seat.state != _UP:
            return math.inf
        depth = len(seat.assigned)
        if depth < self.num_slots:
            return 0.0
        waves = (depth - self.num_slots) // self.num_slots + 1
        tokens = seat.avg_tokens or 0.0
        return waves * self._service_estimate_locked(
            seat, int(tokens) or 1)

    def _predicted_completion_locked(self, max_new: int) -> float:
        """Best-case predicted completion time across seats (queue
        delay on the least-loaded live seat + its service estimate).
        With every seat down, predict from queue depth alone — the
        restart backoff is bounded, so queued work is not hopeless and
        deadline expiry handles the rest."""
        live = [s for s in self._seats if s.state == _UP]
        if not live:
            return 0.0
        best = min(live, key=lambda s: (self._predicted_wait_locked(s),
                                        len(s.assigned), s.index))
        return (self._predicted_wait_locked(best)
                + self._service_estimate_locked(best, max_new))

    def _route_locked(self, adapter=None) -> Optional[_Seat]:
        """Least predicted wait, ties to fewest assigned then lowest
        index — deterministic for a deterministic submit sequence.
        A request carrying an ``adapter`` prefers a seat whose engine
        has already loaded it (``adapters_seen``) — a resident-hit
        gather instead of a host-load miss — but only as the LEADING
        tie-break: a cold seat with a shorter predicted wait within the
        same affinity class still wins, and with no affine seat live
        the request routes like any other."""
        best, key = None, None
        for seat in self._seats:
            if seat.state != _UP:
                continue
            cap = self._engine_kwargs["max_queue"]
            if cap is not None \
                    and len(seat.assigned) >= self.num_slots + cap:
                continue                  # would just bounce QueueFull
            affine = (0 if adapter is not None
                      and adapter in seat.adapters_seen else 1)
            k = (affine, self._predicted_wait_locked(seat),
                 len(seat.assigned), seat.index)
            if key is None or k < key:
                best, key = seat, k
        return best

    # ------------------------------------------------------- worker side

    def _worker(self, seat: _Seat, generation: int,
                eng: PagedServingEngine):
        """One engine's drive loop: drain the seat inbox into
        ``engine.submit``, step while the seat has work, push finished
        streams to the supervisor.  Any engine exception parks on
        ``seat.crash`` for the watchdog; a stale generation (the seat
        was taken down around us) exits silently."""
        rid_of = {}                       # engine rid -> frontend rid
        try:
            while True:
                with self._lock:
                    if self._stopping or seat.generation != generation:
                        return
                    work = list(seat.inbox)
                    seat.inbox.clear()
                # the heartbeat beats every loop, idle or not — the
                # watchdog's staleness backstop must not fire on a seat
                # that was merely quiet before work arrived
                seat.last_beat = time.perf_counter()
                for req in work:
                    try:
                        erid = eng.submit(req.prompt, req.max_new,
                                          req.temperature,
                                          adapter=req.adapter,
                                          tenant=req.tenant)
                    except QueueFull:
                        # backpressure, not failure: bounce it back to
                        # the frontend queue for another seat
                        with self._lock:
                            if seat.generation == generation \
                                    and req.status == RUNNING:
                                seat.assigned.discard(req.rid)
                                req.status = QUEUED
                                req.engine = None
                                self._queue.append(req.rid)
                        continue
                    except Exception as exc:
                        # a request the engine itself refuses (size
                        # prechecks should make this unreachable) must
                        # not crash-loop the seat
                        with self._lock:
                            if seat.generation == generation \
                                    and req.status == RUNNING:
                                seat.assigned.discard(req.rid)
                                self._finalize_locked(
                                    req, FAILED,
                                    reason=f"submit_error: {exc}")
                        continue
                    # engine-rid -> frontend-rid map is LOCAL to this
                    # worker generation: a replaced engine's ids can
                    # never alias the replacement's
                    rid_of[erid] = req.rid
                stepped = False
                if seat.assigned:
                    seat.step_started_at = time.perf_counter()
                    try:
                        progressed = eng.step()
                    finally:
                        # a stale worker unwinding from a released hang
                        # must not clobber the REPLACEMENT engine's
                        # in-flight step timestamp
                        if seat.generation == generation:
                            seat.step_started_at = None
                    if seat.generation == generation:
                        seat.warmed = True
                    seat.last_beat = time.perf_counter()
                    stepped = True
                    done = eng.pop_results()
                    if done:
                        with self._lock:
                            for erid, toks in done.items():
                                self._done_events.append(
                                    (generation, seat.index,
                                     rid_of.pop(erid, None), toks))
                    if not progressed:
                        if not done \
                                and eng.host_state()["queue_depth"] > 0:
                            raise RuntimeError(
                                "engine deadlock: queued work but "
                                "nothing active")
                        # work is in flight at the supervisor; yield
                        time.sleep(0.001)
                if not stepped:
                    seat.wake.wait(0.002)
                    seat.wake.clear()
        except BaseException as exc:       # noqa: BLE001 — watchdog feed
            with self._lock:
                if seat.generation == generation:
                    seat.crash = exc

    # --------------------------------------------------- supervisor side

    def _seat_start(self, seat: _Seat):
        """(Re)build the seat's engine and worker thread.  Construction
        failure (the ``attach`` fault point) counts a restart and
        reschedules with backoff — never raises."""
        try:
            faults = (None if self._faults is None
                      else self._faults.scope(seat.label))
            eng = PagedServingEngine(
                self.cfg, self.params, metrics=seat.registry,
                faults=faults, **self._engine_kwargs)
        except Exception as exc:
            seat.restarts += 1
            seat.restart_at = (time.perf_counter()
                               + self._backoff(seat.restarts))
            self._m_restarts.inc(cause="attach", engine=seat.label)
            if self.tracer is not None:
                self.tracer.instant("engine_restart", track="frontend",
                                    engine=seat.label, cause="attach",
                                    restarts=seat.restarts,
                                    error=f"{type(exc).__name__}: "
                                          f"{exc}")
            return
        seat.engine = eng
        seat.state = _UP
        seat.crash = None
        seat.step_started_at = None
        seat.warmed = False
        seat.last_beat = time.perf_counter()
        seat.thread = threading.Thread(
            target=self._worker, args=(seat, seat.generation, eng),
            name=f"ptpu-frontend-{seat.label}", daemon=True)
        # backstop for an exception that escapes the worker's own
        # crash parking (a raise inside the handler, teardown races):
        # count it and fire the armed flight recorder instead of the
        # default stderr-only death leaving the seat silently unpumped
        watch_thread(seat.thread, self._thread_crash_backstop)
        seat.thread.start()

    def _thread_crash_backstop(self, args):
        """Runs on the dying thread via threading.excepthook; bounded
        work only — the hook dispatcher guarantees the original
        traceback still prints after this."""
        name = getattr(args.thread, "name", "?")
        self._m_thread_crashes.inc(thread=name)
        if self.tracer is not None:
            err = f"{args.exc_type.__name__}: {args.exc_value}"
            self.tracer.instant("thread_crash", track="frontend",
                                thread=name, error=err)
            if self.tracer.flight_path is not None:
                with self._lock:
                    snap = self._snapshot_locked()
                self.tracer.dump_flight(
                    reason=f"uncaught exception on {name}: {err}",
                    state={"frontend": snap})

    def _backoff(self, restarts: int) -> float:
        return min(self.restart_backoff_s * (2.0 ** max(0,
                                                        restarts - 1)),
                   self.restart_backoff_cap_s)

    def _seat_down_locked(self, seat: _Seat, cause: str, exc):
        """Take the seat down: flight-record it, bump the generation
        (in-flight worker output becomes discardable), release injected
        hangs, requeue the seat's journaled requests, schedule the
        replacement."""
        state = None
        if seat.engine is not None:
            try:
                state = seat.engine.host_state()
            except Exception:
                state = {"error": "host_state() raised"}
        if self.tracer is not None:
            self.tracer.instant(
                f"engine_{cause}", track="frontend", engine=seat.label,
                restarts=seat.restarts + 1,
                error=None if exc is None
                else f"{type(exc).__name__}: {exc}")
            if self.tracer.flight_path is not None:
                self.tracer.dump_flight(
                    reason=f"{cause} on {seat.label}"
                    + (f": {exc}" if exc is not None else ""),
                    state={"engine": seat.label,
                           "engine_host_state": state,
                           "frontend": self._snapshot_locked()})
        self._m_restarts.inc(cause=cause, engine=seat.label)
        seat.generation += 1
        seat.state = _DOWN
        seat.engine = None
        if seat.thread is not None:
            # the stale worker exits on its own (generation check /
            # released hang), but close() must still be able to wait
            # for it — a daemon thread dying inside an XLA call at
            # interpreter teardown takes the process with it
            self._zombies.append(seat.thread)
        seat.thread = None
        seat.crash = None
        seat.step_started_at = None
        seat.inbox.clear()
        # the replacement engine starts with an EMPTY adapter registry
        # — stale affinity would route misses at it as if they were
        # hits, so the hint resets with the engine
        seat.adapters_seen.clear()
        seat.restarts += 1
        seat.restart_at = (time.perf_counter()
                           + self._backoff(seat.restarts))
        if self._faults is not None and cause == "hang":
            self._faults.release_hangs()
        # journal replay: every non-terminal request on the seat goes
        # back to the queue (same prompt, same sampling params — greedy
        # streams replay bit-identically), or FAILED past the retry cap
        for rid in sorted(seat.assigned):
            req = self._requests[rid]
            if req.status in TERMINAL:
                continue
            req.attempts += 1
            req.engine = None
            if req.attempts > self.max_retries:
                self._finalize_locked(req, FAILED,
                                      reason="retries_exhausted")
                continue
            req.status = QUEUED
            self._queue.append(rid)
            self._m_retries.inc()
            if self.tracer is not None:
                self.tracer.instant("retry", track="frontend", rid=rid,
                                    attempt=req.attempts,
                                    engine=seat.label)
        seat.assigned.clear()

    def _finalize_locked(self, req: _FrontendRequest, status: str,
                         *, reason: Optional[str] = None, tokens=None):
        """The ONE place a request becomes terminal — exactly-once is
        asserted, not hoped for."""
        if req.status in TERMINAL:
            raise AssertionError(
                f"request {req.rid} finalized twice: {req.status} "
                f"then {status} (frontend bug)")
        req.status = status
        req.reason = reason
        req.finished_at = time.perf_counter()
        self._m_request.observe(req.finished_at - req.submitted_at)
        if status == COMPLETED:
            req.tokens = np.asarray(tokens, np.int32)
            self._m_completed.inc()
            if req.deadline_at is not None \
                    and req.finished_at > req.deadline_at:
                req.deadline_missed = True
                self._m_deadline_miss.inc()
                if self.tracer is not None:
                    self.tracer.instant(
                        "deadline_miss", track="frontend", rid=req.rid,
                        late_s=req.finished_at - req.deadline_at)
        elif status == SHED:
            self._shed_metric(reason or "deadline")
            if self.tracer is not None:
                self.tracer.instant("shed", track="frontend",
                                    rid=req.rid, reason=reason)
        else:
            self._m_failed.inc(reason=reason or "error")

    def pump(self):
        """One supervisor pass: collect completions, run the watchdog,
        restart due seats, expire deadlines, dispatch the queue.
        ``run()`` loops this; tests can call it directly."""
        to_start = []
        with self._lock:
            now = time.perf_counter()
            # 1. completions (stale generations are a replaced engine
            # finishing work the journal already re-owns — drop them)
            while self._done_events:
                gen, si, rid, toks = self._done_events.popleft()
                seat = self._seats[si]
                if rid is None or gen != seat.generation:
                    continue
                req = self._requests[rid]
                seat.assigned.discard(rid)
                if req.status in TERMINAL:
                    continue
                if req.assigned_at is not None:
                    dt = now - req.assigned_at
                    seat.avg_service_s = (
                        dt if seat.avg_service_s is None
                        else 0.7 * seat.avg_service_s + 0.3 * dt)
                ntok = float(len(toks))
                seat.avg_tokens = (
                    ntok if seat.avg_tokens is None
                    else 0.7 * seat.avg_tokens + 0.3 * ntok)
                self._finalize_locked(req, COMPLETED, tokens=toks)
            # 2. watchdog: crashes parked by workers, steps over the
            # hang bound, and a stale heartbeat with work on the seat
            for seat in self._seats:
                if seat.state != _UP:
                    continue
                started = seat.step_started_at
                limit = (self.hang_timeout_s if seat.warmed
                         else max(self.hang_timeout_s,
                                  self.first_step_grace_s))
                if seat.crash is not None:
                    self._seat_down_locked(seat, "crash", seat.crash)
                elif started is not None and now - started > limit:
                    self._seat_down_locked(seat, "hang", None)
                elif seat.assigned \
                        and now - seat.last_beat > 4 * max(limit, 0.25):
                    # heartbeat backstop: the worker owes us a step
                    self._seat_down_locked(seat, "hang", None)
            # 3. seats due for a restart (engines are BUILT outside the
            # lock — construction does device allocation and can fire
            # the attach fault)
            for seat in self._seats:
                if seat.state == _DOWN and now >= seat.restart_at:
                    to_start.append(seat)
            # 4. deadline expiry while frontend-queued
            for rid in list(self._queue):
                req = self._requests[rid]
                if req.deadline_at is not None \
                        and now > req.deadline_at:
                    self._queue.remove(rid)
                    self._finalize_locked(req, SHED, reason="deadline")
            # 5. dispatch: priority first, then arrival order
            self._queue.sort(key=lambda r:
                             (-self._requests[r].priority, r))
            remaining = []
            woken = set()
            for rid in self._queue:
                req = self._requests[rid]
                seat = self._route_locked(adapter=req.adapter)
                if seat is None:
                    remaining.append(rid)
                    continue
                req.status = RUNNING
                req.engine = seat.index
                req.assigned_at = now
                seat.assigned.add(rid)
                if req.adapter is not None:
                    seat.adapters_seen.add(req.adapter)
                seat.inbox.append(req)
                woken.add(seat.index)
            self._queue = remaining
            for si in woken:
                self._seats[si].wake.set()
            self._m_queue_g.set(float(len(self._queue)))
            self._m_live_g.set(float(sum(
                1 for s in self._seats if s.state == _UP)))
        for seat in to_start:
            self._seat_start(seat)

    def run(self, timeout_s: Optional[float] = None,
            poll_s: float = 0.001) -> Dict[int, dict]:
        """Drive the supervisor loop until every journaled request is
        terminal; returns ``{rid: record}`` (see
        :meth:`_FrontendRequest.record`).  ``timeout_s`` bounds the
        wait — on expiry the flight recorder (if armed) dumps the
        frontend snapshot and a ``TimeoutError`` raises."""
        t0 = time.perf_counter()
        while True:
            self.pump()
            with self._lock:
                if all(r.status in TERMINAL
                       for r in self._requests.values()):
                    return self.results()
            if timeout_s is not None \
                    and time.perf_counter() - t0 > timeout_s:
                with self._lock:
                    snap = self._snapshot_locked()
                if self.tracer is not None \
                        and self.tracer.flight_path is not None:
                    self.tracer.dump_flight(
                        reason=f"run() timeout after {timeout_s}s",
                        state=snap)
                raise TimeoutError(
                    f"frontend.run() exceeded {timeout_s}s; "
                    f"non-terminal: {snap['non_terminal']}")
            time.sleep(poll_s)

    # --------------------------------------------------------- reporting

    def results(self) -> Dict[int, dict]:
        """Every journaled request's record (terminal or not)."""
        with self._lock:
            return {rid: r.record()
                    for rid, r in self._requests.items()}

    def status(self, rid: int) -> str:
        with self._lock:
            return self._requests[rid].status

    def stats(self) -> dict:
        """Service-level rollup for benches and gates: counts, rates,
        restarts.  ``shed_rate`` / ``deadline_miss_rate`` are the two
        SLO numbers ``benchmark/lm_decode.py --frontend`` reports."""
        with self._lock:
            recs = list(self._requests.values())
            n = len(recs)
            shed = sum(1 for r in recs if r.status == SHED)
            completed = sum(1 for r in recs if r.status == COMPLETED)
            failed = sum(1 for r in recs if r.status == FAILED)
            missed = sum(1 for r in recs if r.deadline_missed)
            restarts = sum(s.restarts for s in self._seats)
            return {
                "submitted": n,
                "completed": completed,
                "shed": shed,
                "failed": failed,
                "queued": len(self._queue),
                "retries": sum(r.attempts for r in recs),
                "engine_restarts": restarts,
                "engines_live": sum(1 for s in self._seats
                                    if s.state == _UP),
                "deadline_misses": missed,
                "shed_rate": (shed / n) if n else 0.0,
                "deadline_miss_rate": (missed / completed)
                if completed else 0.0,
            }

    def engine_states(self) -> List[Optional[dict]]:
        """Each live seat's ``host_state()`` (None for a down seat)."""
        with self._lock:
            seats = [(s.state, s.engine) for s in self._seats]
        return [eng.host_state() if state == _UP and eng is not None
                else None for state, eng in seats]

    def compile_counts(self) -> List[Optional[dict]]:
        """Per-seat ``compile_counts()`` — the chaos gate's
        ``compiles == {'step': 1}`` check, per live engine."""
        with self._lock:
            engines = [s.engine if s.state == _UP else None
                       for s in self._seats]
        return [None if e is None else e.compile_counts()
                for e in engines]

    # ------------------------------------------------ live endpoint

    @property
    def http_url(self) -> Optional[str]:
        """Base URL of the live telemetry endpoint, or None when the
        frontend was built without ``http_port=``."""
        return None if self._httpd is None else self._httpd.url

    def _http_metrics(self) -> dict:
        """/metrics source: the frontend registry merged with every
        seat's engine registry under ``seat=`` labels
        (``merge_snapshots`` — frontend_* and serving_* families are
        disjoint, so nothing clashes).  Registries are thread-safe and
        the seat list is fixed at construction, so handler threads
        need no frontend lock here."""
        from paddle_tpu.telemetry.export import merge_snapshots
        pairs = [("frontend", self.metrics.snapshot())]
        pairs += [(s.label, s.registry.snapshot())
                  for s in self._seats]
        return merge_snapshots(pairs, label="seat",
                               registry="frontend")

    def _http_healthz(self):
        """/healthz source: 200 only when EVERY seat is up — a single
        crash-parked or restarting seat flips the probe to 503, which
        is exactly when a balancer should stop routing here."""
        with self._lock:
            states = {s.label: s.state for s in self._seats}
        live = sum(1 for v in states.values() if v == _UP)
        return live == len(states), {"engines_live": live,
                                     "engines": len(states),
                                     "seats": states}

    def _http_traces(self) -> dict:
        """/traces/recent source: the waterfall summary of the
        frontend tracer's ring (empty summary when tracing is off).
        ``Tracer.events()`` copies under the tracer's own lock."""
        if self.tracer is None:
            return {"requests": 0, "tracing": False}
        return telemetry.waterfall_summary(self.tracer.events())

    def _http_state(self) -> dict:
        """/state source: service rollup + per-seat supervision view.
        Engine ``host_state()`` is deliberately NOT walked here — a
        scrape must not race the owning worker thread's step; per-seat
        occupancy already rides /metrics via the seat registries."""
        with self._lock:
            snap = self._snapshot_locked()
        return {"stats": self.stats(), "supervision": snap}

    def _snapshot_locked(self) -> dict:
        return {
            "queue_depth": len(self._queue),
            "non_terminal": sorted(
                rid for rid, r in self._requests.items()
                if r.status not in TERMINAL),
            "seats": [{
                "label": s.label, "state": s.state,
                "generation": s.generation, "restarts": s.restarts,
                "assigned": sorted(s.assigned),
                "step_started_at": s.step_started_at,
                "last_beat": s.last_beat,
            } for s in self._seats],
            "stats": None,                # stats() re-locks; keep flat
        }

    # --------------------------------------------------------- lifecycle

    def close(self):
        """Stop every worker thread and take the seats down.  Queued
        and running requests stay journaled (non-terminal) — close is
        shutdown, not resolution."""
        if self._httpd is not None:
            self._httpd.close()
            self._httpd = None
        with self._lock:
            self._stopping = True
            for seat in self._seats:
                seat.generation += 1
                seat.state = _DOWN
                seat.engine = None
                seat.wake.set()
            threads = [s.thread for s in self._seats
                       if s.thread is not None] + self._zombies
        if self._faults is not None:
            self._faults.release_hangs()
        for t in threads:
            # generously: a worker mid-compile must come home before
            # the interpreter starts tearing down XLA under it
            t.join(timeout=30.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --------------------------------------------------- disaggregated entry


def disaggregated_frontend(cfg, params, *, prefill_workers: int = 1,
                           decode_workers: int = 1, **kw):
    """The process-isolated counterpart of :class:`ServingFrontend`:
    build a :class:`~paddle_tpu.cluster.ClusterController` whose
    workers are OS PROCESSES (prefill workers computing KV blocks and
    handing them to decode workers) instead of engine threads in this
    interpreter.  Same supervision story — heartbeat watchdog, SIGKILL
    takedown, generation-tagged backoff restart, journal-replay with
    greedy streams bit-identical — carried across the process
    boundary; see ``docs/design/serving.md`` (disaggregation section)
    for when each shape wins.

    ``kw`` passes through to the controller (engine geometry,
    ``kv_dtype``/``prefix_cache``, heartbeat/backoff/retry tuning,
    ``autoscaler=AutoscalePolicy(...)``, ``faults=``, ``metrics=``).
    The import lives inside the call so in-process serving never pays
    for the cluster machinery."""
    from paddle_tpu.cluster import ClusterController
    return ClusterController(cfg, params,
                             prefill_workers=prefill_workers,
                             decode_workers=decode_workers, **kw)
