"""Testing utilities: gradient checking, comparison, fault injection.

TPU-native twin of the reference's core correctness tooling —
``paddle/gserver/tests/LayerGradUtil.h:203-306`` (``testLayerGrad``) and the
new-IR ``python/paddle/v2/framework/tests/op_test.py:95``
(``get_numeric_gradient`` / ``check_grad``): central finite differences of a
scalarized function compared against ``jax.grad``, applied over whole
parameter pytrees.

``paddle_tpu.testing.faults`` is the deterministic fault-injection
harness the serving chaos tests drive — seeded schedules of
raise/delay/hang faults fired at named injection points threaded
through the serving engine (the runtime-robustness twin of the
reference's fault-tolerant go/master + go/pserver cloud runtime).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def numeric_gradient(f: Callable, x: jax.Array, eps: float = 1e-3) -> np.ndarray:
    """Central finite-difference gradient of scalar-valued f at x."""
    x = np.array(x, np.float64 if x.dtype == jnp.float64 else np.float32)
    grad = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        f_pos = float(f(jnp.asarray(x)))
        flat[i] = orig - eps
        f_neg = float(f(jnp.asarray(x)))
        flat[i] = orig
        gflat[i] = (f_pos - f_neg) / (2 * eps)
    return grad


def check_grad(f: Callable, x: jax.Array, eps: float = 1e-3,
               rtol: float = 1e-2, atol: float = 1e-3) -> None:
    """Assert jax.grad(f)(x) matches finite differences."""
    analytic = np.asarray(jax.grad(f)(x), np.float64)
    numeric = numeric_gradient(f, x, eps)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                               err_msg="analytic vs numeric gradient mismatch")


def check_grad_params(loss_fn: Callable, params, eps: float = 1e-3,
                      rtol: float = 1e-2, atol: float = 1e-3,
                      max_elems_per_leaf: int = 16,
                      seed: int = 0) -> None:
    """Gradcheck over a parameter pytree, sampling elements of big leaves.

    ``loss_fn(params) -> scalar``.  For each leaf, up to
    ``max_elems_per_leaf`` random elements are perturbed (the reference's
    testLayerGrad similarly spot-checks rather than perturbing every weight
    of every layer).

    Runs under ``jax.default_matmul_precision("highest")``: TPU matmuls
    default to bf16-tier precision, whose ~2^-8 quantization swallows the
    finite-difference perturbation entirely (the config-flag form of this
    setting is not honored by all backends; the context manager is).
    """
    with jax.default_matmul_precision("highest"):
        return _check_grad_params(loss_fn, params, eps, rtol, atol,
                                  max_elems_per_leaf, seed)


def _check_grad_params(loss_fn, params, eps, rtol, atol,
                       max_elems_per_leaf, seed) -> None:
    analytic = jax.grad(loss_fn)(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = jax.tree_util.tree_leaves(analytic)
    rng = np.random.RandomState(seed)

    for li, (leaf, g_leaf) in enumerate(zip(leaves, g_leaves)):
        leaf_np = np.array(leaf, np.float64)
        flat = leaf_np.reshape(-1)
        n = flat.size
        idxs = (np.arange(n) if n <= max_elems_per_leaf
                else rng.choice(n, max_elems_per_leaf, replace=False))
        for i in idxs:
            orig = flat[i]

            def eval_at(v):
                # Fresh ndarray per evaluation: some backends cache
                # host->device transfers by array identity, so mutating
                # one buffer in place re-reads the stale device copy.
                pert = leaf_np.copy()
                pert.reshape(-1)[i] = v
                new_leaves = list(leaves)
                new_leaves[li] = jnp.asarray(pert, leaf.dtype)
                return float(loss_fn(jax.tree_util.tree_unflatten(
                    treedef, new_leaves)))

            num = (eval_at(orig + eps) - eval_at(orig - eps)) / (2 * eps)
            ana = float(np.asarray(g_leaf).reshape(-1)[i])
            if not np.isclose(ana, num, rtol=rtol, atol=atol):
                raise AssertionError(
                    f"grad mismatch at leaf {li} elem {i}: "
                    f"analytic={ana:.6g} numeric={num:.6g}")


def assert_allclose(a, b, rtol: float = 1e-5, atol: float = 1e-6,
                    msg: Optional[str] = None) -> None:
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol,
                               atol=atol, err_msg=msg or "")
