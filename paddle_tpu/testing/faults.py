"""Deterministic fault injection for the serving stack.

The reference snapshot's headline systems property is that its cloud
runtime DEGRADES instead of dying: go/master journals task leases and
retries a dead worker's work with backoff.  Reproducing that robustness
loop needs a way to make things die ON DEMAND and REPRODUCIBLY — this
module is that tool.

Three pieces:

* :class:`Fault` — one scheduled failure: a named injection ``point``
  (the engine threads :data:`POINTS` through its host loop), the
  1-based invocation index ``at`` it fires on, an ``action``
  (``"raise"`` / ``"delay"`` / ``"hang"``), and an optional ``scope``
  restricting it to one engine.  Faults are one-shot unless ``every``
  repeats them.
* :class:`FaultSchedule` — an ordered set of faults.
  :meth:`FaultSchedule.seeded` derives a schedule from a seed through
  ``np.random.RandomState``, so a chaos property test can sweep seeds
  and every failure it finds replays exactly.
* :class:`FaultInjector` — the runtime: owns per-``(scope, point)``
  invocation counters, matches each :meth:`fire` call against the
  schedule, and performs the action.  ``fire`` is what the engine
  calls at each injection point; with no injector attached the call
  site is a single ``is None`` check.

Determinism contract: a fault fires on the N-th ``fire(point)`` call
within its scope — nothing is keyed on wall time.  The engine's host
loop is single-threaded per engine and its step/admission sequence is a
pure function of its submitted requests, so invocation counts (and
therefore fault timing) reproduce run-to-run even when several engine
workers run on threads.  Counters survive an engine restart (the scope
string names the engine SEAT, not the engine object), so a one-shot
fault cannot re-fire against the replacement engine.

Hangs are EVENT-RELEASED, never unbounded: a hanging ``fire`` blocks on
a ``threading.Event`` until :meth:`FaultInjector.release_hangs` (what
the supervisor calls as part of restarting a hung engine) or
``max_hang_s`` elapses, then raises :class:`FaultError` so the stuck
worker thread unwinds instead of leaking.  A test can therefore inject
a real observable hang — the watchdog sees a step that never returns —
without ever wedging the test process.

Injected failures raise :class:`FaultError` (a ``RuntimeError``
subclass) so supervisors and tests can tell injected chaos from real
engine bugs: the frontend restarts on ANY engine exception, but the
chaos gate asserts the failures it sees are its own.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["POINTS", "ACTIONS", "Fault", "FaultError", "FaultSchedule",
           "FaultInjector"]

#: The named injection points the serving engine threads through its
#: host loop (``serving.py``; catalog in docs/design/serving.md):
#: ``attach``      engine construction (device attach / jit build),
#: ``admit``       top of each admission attempt,
#: ``prefill``     before a prompt's prefill dispatch,
#: ``decode_step`` before each jitted decode step,
#: ``retire``      before a finished request's blocks are freed.
#:
#: Process-scope points, fired by the CLUSTER CONTROLLER (scope = the
#: worker label), both indexed per heartbeat RECEIVED from that worker
#: — so ``at=`` counts its heartbeats, the only reproducible clock a
#: real OS process exposes:
#: ``proc_kill``   ``raise`` SIGKILLs the worker's actual process;
#:                 detection still runs through the genuine
#:                 heartbeat-timeout machinery,
#: ``heartbeat``   ``raise`` drops the heartbeat, ``delay`` delivers
#:                 it late (watchdog-margin chaos).
POINTS = ("attach", "admit", "prefill", "decode_step", "retire",
          "proc_kill", "heartbeat")

#: What a fault does when it fires: ``raise`` throws :class:`FaultError`
#: (a crash), ``delay`` sleeps ``delay_s`` (latency chaos — deadline
#: and watchdog-margin tests), ``hang`` blocks until released (a wedged
#: device / deadlocked step).
ACTIONS = ("raise", "delay", "hang")


class FaultError(RuntimeError):
    """An injected failure.  ``point``/``scope``/``index`` identify the
    exact scheduled fault that fired, so a chaos test can assert the
    crash it observed is the crash it scheduled."""

    def __init__(self, point: str, scope: str, index: int,
                 detail: str = ""):
        self.point = point
        self.scope = scope
        self.index = index
        super().__init__(
            f"injected fault at {scope}:{point} call #{index}"
            + (f" ({detail})" if detail else ""))


class Fault:
    """One scheduled failure.  ``at`` is the 1-based invocation index of
    ``point`` (within ``scope``) the fault fires on; ``every`` repeats
    it each ``every`` further calls (``at=3, every=2`` fires on calls
    3, 5, 7, ...).  ``scope=None`` matches every scope — a single-
    engine test need not name its engine."""

    __slots__ = ("point", "at", "action", "scope", "every", "delay_s")

    def __init__(self, point: str, at: int, action: str = "raise", *,
                 scope: Optional[str] = None, every: Optional[int] = None,
                 delay_s: float = 0.0):
        if point not in POINTS:
            raise ValueError(f"unknown injection point {point!r}; "
                             f"catalog: {POINTS}")
        if action not in ACTIONS:
            raise ValueError(f"unknown action {action!r}; "
                             f"catalog: {ACTIONS}")
        if at < 1:
            raise ValueError(f"fault fires on a 1-based call index, "
                             f"got at={at}")
        if every is not None and every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.point = point
        self.at = int(at)
        self.action = action
        self.scope = scope
        self.every = every
        self.delay_s = float(delay_s)

    def matches(self, point: str, scope: str, index: int) -> bool:
        if point != self.point:
            return False
        if self.scope is not None and scope != self.scope:
            return False
        if index == self.at:
            return True
        return (self.every is not None and index > self.at
                and (index - self.at) % self.every == 0)

    def __repr__(self):
        where = self.point if self.scope is None \
            else f"{self.scope}:{self.point}"
        rep = f", every={self.every}" if self.every else ""
        return (f"Fault({where}@{self.at}, {self.action}"
                f"{rep})")


class FaultSchedule:
    """An ordered collection of :class:`Fault`.  Immutable once built —
    a schedule is a test INPUT, and replaying a seed must replay the
    exact schedule object state."""

    def __init__(self, faults: Sequence[Fault] = ()):
        self.faults: Tuple[Fault, ...] = tuple(faults)

    def __iter__(self):
        return iter(self.faults)

    def __len__(self):
        return len(self.faults)

    def __repr__(self):
        return f"FaultSchedule({list(self.faults)!r})"

    @classmethod
    def seeded(cls, seed: int, *, n_faults: int = 3,
               points: Sequence[str] = ("decode_step", "prefill",
                                        "admit"),
               max_at: int = 12,
               scopes: Sequence[Optional[str]] = (None,),
               actions: Sequence[str] = ("raise", "delay", "hang"),
               delay_s: float = 0.002) -> "FaultSchedule":
        """Derive a reproducible schedule from ``seed`` — the chaos
        property test's generator.  Every choice flows through one
        ``RandomState(seed)``, so the same seed always builds the same
        schedule (and a failing seed is a complete repro).  Duplicate
        ``(scope, point, at)`` draws collapse (the first wins), so a
        schedule never stacks two actions on one call."""
        rs = np.random.RandomState(seed)
        seen = set()
        faults: List[Fault] = []
        for _ in range(n_faults):
            point = points[int(rs.randint(len(points)))]
            at = int(rs.randint(1, max_at + 1))
            scope = scopes[int(rs.randint(len(scopes)))]
            action = actions[int(rs.randint(len(actions)))]
            key = (scope, point, at)
            if key in seen:
                continue
            seen.add(key)
            faults.append(Fault(point, at, action, scope=scope,
                                delay_s=delay_s))
        return cls(faults)


class _Scoped:
    """An injector view bound to one scope label — what the engine
    actually holds, so its call sites never repeat the engine name."""

    __slots__ = ("injector", "scope")

    def __init__(self, injector: "FaultInjector", scope: str):
        self.injector = injector
        self.scope = scope

    def fire(self, point: str) -> None:
        self.injector.fire(point, scope=self.scope)


class FaultInjector:
    """The runtime half: counts invocations per ``(scope, point)`` and
    performs scheduled faults.  Thread-safe — engine workers fire from
    their own threads while the supervisor reads counters and releases
    hangs.

    ``max_hang_s`` bounds every injected hang: a hang the supervisor
    never notices still unwinds (as a :class:`FaultError`) instead of
    leaking a blocked thread — tests stay bounded even when the
    watchdog under test is broken, which is exactly when it matters.
    """

    def __init__(self, schedule: FaultSchedule = FaultSchedule(), *,
                 max_hang_s: float = 30.0):
        self.schedule = schedule
        self.max_hang_s = float(max_hang_s)
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str], int] = {}
        self._fired: List[dict] = []
        self._release = threading.Event()
        self._hanging = 0

    # ----------------------------------------------------------- engine API

    def scope(self, label: str) -> _Scoped:
        """A view bound to one engine seat — restarted engines reuse
        their seat's scope so counters (and one-shot faults already
        spent) carry across the restart."""
        return _Scoped(self, str(label))

    def fire(self, point: str, scope: str = "engine0") -> None:
        """One invocation of ``point`` within ``scope``: bump the
        counter, then perform the first scheduled fault that matches.
        Raises :class:`FaultError` for ``raise`` (and for a released or
        timed-out ``hang``), sleeps for ``delay``, returns untouched
        otherwise."""
        if point not in POINTS:
            raise ValueError(f"unknown injection point {point!r}; "
                             f"catalog: {POINTS}")
        with self._lock:
            index = self._counts.get((scope, point), 0) + 1
            self._counts[(scope, point)] = index
            fault = next((f for f in self.schedule
                          if f.matches(point, scope, index)), None)
            if fault is not None:
                self._fired.append({"point": point, "scope": scope,
                                    "index": index,
                                    "action": fault.action})
            if fault is not None and fault.action == "hang":
                # capture the CURRENT release event while still inside
                # the lock: a release racing this fire must either see
                # the waiter or hand it the already-set event
                self._hanging += 1
                release = self._release
        if fault is None:
            return
        if fault.action == "raise":
            raise FaultError(point, scope, index)
        if fault.action == "delay":
            import time
            time.sleep(fault.delay_s)
            return
        # hang: block until the supervisor restarts us (release_hangs)
        # or the safety bound elapses, then unwind as an injected error
        # — the stale worker thread must exit, not resume into an
        # engine seat that has already been handed to its replacement.
        try:
            released = release.wait(self.max_hang_s)
        finally:
            with self._lock:
                self._hanging -= 1
        raise FaultError(point, scope, index,
                         "hang " + ("released" if released
                                    else "timed out"))

    # ------------------------------------------------------- supervisor API

    def release_hangs(self) -> None:
        """Unblock every currently injected hang (each unwinds as a
        :class:`FaultError` in its worker thread).  The supervisor
        calls this when restarting a hung engine; future hangs re-arm
        automatically."""
        with self._lock:
            # swap under the lock: every waiter captured the old event
            # inside this lock, so setting it after the swap reaches
            # exactly the hangs that existed at release time — later
            # hangs wait on the fresh, unset event
            released, self._release = self._release, threading.Event()
        released.set()

    @property
    def hanging(self) -> int:
        """How many threads are currently blocked in an injected hang."""
        with self._lock:
            return self._hanging

    def counts(self) -> Dict[Tuple[str, str], int]:
        """Invocation counts per ``(scope, point)`` — the reproducible
        clock fault schedules are written against."""
        with self._lock:
            return dict(self._counts)

    def fired(self) -> List[dict]:
        """The faults that actually fired, in order — what a chaos test
        asserts its observed failures against."""
        with self._lock:
            return [dict(f) for f in self._fired]
