"""Inference entry points.

Twin of the reference's serving surfaces: the C inference API
(``paddle/capi/gradient_machine.h:36-112`` — create-from-merged-model,
forward, shared-param clones for multithread serving) and ``paddle.v2.infer``
(``python/paddle/v2/inference.py:111``).

An :class:`InferenceMachine` binds (model_fn, params, state) into a jitted
forward; ``export_model``/``load_model`` is the ``paddle_merge_model`` twin
(one self-contained directory with weights + config metadata).  Thread-safe
shared-parameter serving falls out of JAX purity: one machine can serve from
many threads (the reference needed explicit shared-param clones).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn import transform
from paddle_tpu.training import checkpoint as ckpt_lib

__all__ = ["InferenceMachine", "serving_cast", "export_model",
           "load_model"]


def serving_cast(params, dtype=jnp.bfloat16):
    """One-time cast of float parameters to the serving dtype.

    Training keeps f32 master weights (the mixed-precision policy);
    inference needs no masters.  Casting once halves the parameter HBM
    footprint (800 -> 400 MB for the d1024 benchmark LM) — headroom
    for bigger serving batches or longer KV caches per chip.  Measured
    effect on decode THROUGHPUT is small (1.006 -> 0.975 ms/step at
    b8, none at b32): the v5e decode step is launch/latency-bound, not
    weight-streaming-bound (`docs/design/serving.md`).  Non-float
    leaves (int vocab tables, step counters) pass through untouched.
    Opt-in — bf16 weights round logits, so near-tie greedy picks can
    differ from the f32 reference (the usual quantized-serving
    contract).
    """
    def leaf(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(leaf, params)


class InferenceMachine:
    def __init__(self, model_fn: Callable, params, net_state=None):
        """model_fn(batch) -> outputs (any pytree; no loss needed)."""
        self.model = transform(model_fn)
        self.params = params
        self.net_state = net_state or {}
        self._fwd = jax.jit(
            lambda p, s, batch: self.model.apply(p, s, None, batch,
                                                 train=False)[0])

    def infer(self, batch: Dict[str, Any]):
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        return self._fwd(self.params, self.net_state, batch)

    def infer_batches(self, reader: Callable[[], Iterable[Dict[str, Any]]],
                      field: Optional[str] = None):
        """Stream inference over a batched reader (v2 infer semantics:
        concatenated outputs)."""
        outs = []
        for batch in reader():
            out = self.infer(batch)
            if field is not None:
                out = out[field]
            outs.append(np.asarray(out))
        return np.concatenate(outs, axis=0) if outs else np.empty((0,))


def export_model(directory: str, params, net_state=None,
                 config: Optional[Dict[str, Any]] = None) -> str:
    """Merge weights + config into one deployable dir
    (paddle_merge_model twin, ``trainer/MergeModel.cpp``)."""
    path = ckpt_lib.save(directory, 0, {"params": params,
                                        "net_state": net_state or {}},
                         metadata={"exported": True})
    with open(os.path.join(directory, "model_config.json"), "w") as f:
        json.dump(config or {}, f, indent=2)
    return path


def load_model(directory: str, model_fn: Callable) -> InferenceMachine:
    trees, _ = ckpt_lib.load(directory)
    as_jnp = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
    return InferenceMachine(model_fn, as_jnp(trees["params"]),
                            as_jnp(trees.get("net_state", {})))
