"""Inference entry points.

Twin of the reference's serving surfaces: the C inference API
(``paddle/capi/gradient_machine.h:36-112`` — create-from-merged-model,
forward, shared-param clones for multithread serving) and ``paddle.v2.infer``
(``python/paddle/v2/inference.py:111``).

An :class:`InferenceMachine` binds (model_fn, params, state) into a jitted
forward; ``export_model``/``load_model`` is the ``paddle_merge_model`` twin
(one self-contained directory with weights + config metadata).  Thread-safe
shared-parameter serving falls out of JAX purity: one machine can serve from
many threads (the reference needed explicit shared-param clones).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn import transform
from paddle_tpu.training import checkpoint as ckpt_lib


class InferenceMachine:
    def __init__(self, model_fn: Callable, params, net_state=None):
        """model_fn(batch) -> outputs (any pytree; no loss needed)."""
        self.model = transform(model_fn)
        self.params = params
        self.net_state = net_state or {}
        self._fwd = jax.jit(
            lambda p, s, batch: self.model.apply(p, s, None, batch,
                                                 train=False)[0])

    def infer(self, batch: Dict[str, Any]):
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        return self._fwd(self.params, self.net_state, batch)

    def infer_batches(self, reader: Callable[[], Iterable[Dict[str, Any]]],
                      field: Optional[str] = None):
        """Stream inference over a batched reader (v2 infer semantics:
        concatenated outputs)."""
        outs = []
        for batch in reader():
            out = self.infer(batch)
            if field is not None:
                out = out[field]
            outs.append(np.asarray(out))
        return np.concatenate(outs, axis=0) if outs else np.empty((0,))


def export_model(directory: str, params, net_state=None,
                 config: Optional[Dict[str, Any]] = None) -> str:
    """Merge weights + config into one deployable dir
    (paddle_merge_model twin, ``trainer/MergeModel.cpp``)."""
    path = ckpt_lib.save(directory, 0, {"params": params,
                                        "net_state": net_state or {}},
                         metadata={"exported": True})
    with open(os.path.join(directory, "model_config.json"), "w") as f:
        json.dump(config or {}, f, indent=2)
    return path


def load_model(directory: str, model_fn: Callable) -> InferenceMachine:
    trees, _ = ckpt_lib.load(directory)
    as_jnp = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
    return InferenceMachine(model_fn, as_jnp(trees["params"]),
                            as_jnp(trees.get("net_state", {})))
