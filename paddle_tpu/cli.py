"""Command-line entry point: ``python -m paddle_tpu <command>``.

Twin of the reference's CLI surface (``paddle`` shell →
``paddle_trainer --job=train|test|time`` ``trainer/TrainerMain.cpp:31``,
``paddle_merge_model`` ``trainer/MergeModel.cpp``, ``paddle version``):

    python -m paddle_tpu train       --config cfg.py --num-passes 5
    python -m paddle_tpu test        --config cfg.py --checkpoint-dir d/
    python -m paddle_tpu time        --config cfg.py --batches 50
    python -m paddle_tpu merge_model --config cfg.py --checkpoint-dir d/ -o m/
    python -m paddle_tpu version

A config file is plain Python (the reference's config DSL was too —
``config_parser.py`` ran user Python to emit protobuf) defining:

    model_fn(batch) -> (loss, outputs)      # required
    optimizer                               # optim.Transform | api optimizer
    train_reader() -> iterable of batches   # required for train/time
    test_reader()                           # optional
    evaluators = [...]                      # optional
    config_args(args_dict)                  # optional hook, receives
                                            # --config-args k=v,k=v pairs
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict

__version__ = "0.1.0"


def _load_config(path: str, config_args: str):
    from paddle_tpu.api.config import load_config_module, synthesize
    module = load_config_module(path, config_args)
    # v1-style configs (layers + outputs + settings +
    # define_py_data_sources2) synthesize the contract from recorded
    # DSL side effects.
    synthesize(module)
    if not hasattr(module, "model_fn"):
        raise SystemExit(f"{path}: config must define model_fn(batch) or "
                         "a declarative cost/outputs(...) network")
    return module


def _build_trainer(cfg):
    from paddle_tpu.training import Trainer
    if getattr(cfg, "mixed_precision", False):
        # bf16 compute policy for the whole run (the policy is read at
        # trace time, so it must be set process-wide before jit)
        from paddle_tpu.core import dtypes
        dtypes.set_policy(dtypes.MIXED_BF16)
    opt = getattr(cfg, "optimizer", None)
    if opt is None:
        from paddle_tpu import optim
        opt = optim.sgd(0.01)
    if hasattr(opt, "build"):
        opt = opt.build()
    return Trainer(cfg.model_fn, opt)


def cmd_train(args):
    cfg = _load_config(args.config, args.config_args)
    if getattr(args, "fp_checks", False):
        from paddle_tpu.training.aux import enable_fp_checks
        enable_fp_checks()
    trainer = _build_trainer(cfg)
    from paddle_tpu.training import checkpoint as _ckpt
    if (args.checkpoint_dir and args.resume
            and _ckpt.latest_pass(args.checkpoint_dir) is not None):
        trainer.restore(args.checkpoint_dir)
    elif getattr(args, "init_model_path", None):
        # tryLoadParametersFromConfig order (ParamUtil.h:101-111): a
        # resumable checkpoint wins; otherwise (including the FIRST
        # launch of a preemptible job, when --resume finds nothing yet)
        # init values come from the v1 pass dir (shapes come from the
        # config via a sample batch).
        from paddle_tpu.core.errors import enforce
        first = next(iter(cfg.train_reader()), None)
        enforce(first is not None,
                "--init-model-path needs one batch from the config's "
                "train_reader to shape-init the model, but it yielded "
                "none (empty train data source?)")
        trainer.init(first)
        trainer.load_v1_params(args.init_model_path)
    if args.checkpoint_dir:
        from paddle_tpu.training.aux import PreemptionHandler
        PreemptionHandler(trainer, args.checkpoint_dir).install()
    metrics = trainer.train(
        cfg.train_reader,
        num_passes=args.num_passes,
        evaluators=list(getattr(cfg, "evaluators", [])),
        test_reader=getattr(cfg, "test_reader", None),
        save_dir=args.checkpoint_dir,
        log_period=args.log_period,
        stats_period=getattr(args, "stats_period", 0))
    print(json.dumps(metrics))


def cmd_test(args):
    cfg = _load_config(args.config, args.config_args)
    trainer = _build_trainer(cfg)
    reader = getattr(cfg, "test_reader", None) or cfg.train_reader
    sample = next(iter(reader()))
    trainer.init(sample)
    if args.checkpoint_dir:
        trainer.restore(args.checkpoint_dir)
    elif getattr(args, "init_model_path", None):
        trainer.load_v1_params(args.init_model_path)
    results = trainer.test(reader, list(getattr(cfg, "evaluators", [])))
    print(json.dumps(results))


def cmd_time(args):
    """Throughput benchmark (TrainerBenchmark.cpp:27-66 twin: burn-in then
    timed batches).  Differential protocol — (T(4n)-T(n))/3n with a
    host-transfer sync — so constant overheads (incl. remote-attachment
    round trips) cancel; see bench.py's docstring for the rationale."""
    import itertools
    import jax.numpy as jnp
    from paddle_tpu.utils.timing import marginal_ms_with_spread, timed_run
    cfg = _load_config(args.config, args.config_args)
    trainer = _build_trainer(cfg)

    batches = list(itertools.islice(iter(cfg.train_reader()),
                                    max(args.batches, 1)))
    if not batches:
        raise SystemExit(f"{args.config}: train_reader() yielded no batches")
    # Device-resident batches: the reference's --job=time measured the
    # train step with the provider prefetched; host->device input
    # transfer is excluded the same way (it would dominate on remote
    # attachments with slow links).
    trainer.init(batches[0])
    if getattr(args, "init_model_path", None):
        # the reference --job=time honors init_model_path: time (and
        # numerically exercise) the TRAINED model, not a random init
        trainer.load_v1_params(args.init_model_path)
    batches = [{k: jnp.asarray(v) for k, v in b.items()} for b in batches]
    last = {}

    # Same protocol as bench.py (shared helper + shared step path, so the
    # two cannot drift): when the batches stack (uniform shapes), time
    # the compiled multi-batch loop — one dispatch per K batches — and
    # divide; otherwise fall back to per-dispatch train_batch.  Under a
    # mesh the stack shards P(None, dp): the scan axis stays whole, each
    # scanned batch is dp-sharded.
    shapes = {k: v.shape for k, v in batches[0].items()}
    stackable = (not trainer.average_window
                 and all({k: v.shape for k, v in b.items()} == shapes
                         for b in batches))
    n = max(args.batches, 1)
    trace_dir = getattr(args, "trace", None)
    if stackable:
        K = len(batches)
        stack = {k: jnp.stack([b[k] for b in batches])
                 for k in batches[0]}

        def step_fn():
            losses = trainer.train_batches(stack)
            last["cost"] = losses[-1]
            return losses[-1]

        # ceil-divide so any positive --burn-in warms at least one scan
        # call, while --burn-in 0 still times cold (as in the fallback)
        timed_run(step_fn, -(-args.burn_in // K))
        ms, spread = marginal_ms_with_spread(
            step_fn, n=max(1, n // K), repeats=args.repeats)
        ms = ms / K
        spread = spread / K if spread is not None else None
        protocol = "differential-scan"
        # MFU from XLA's FLOP count of the compiled scan (per batch —
        # the loop body is counted trip-count-invariantly).
        from paddle_tpu.utils import mfu as mfu_mod
        flops_batch = trainer.train_scan_flops(stack)
        mfu_val = (mfu_mod.mfu(flops_batch, ms / 1e3)
                   if flops_batch else None)
    else:
        cycle = itertools.cycle(batches)

        def step_fn():
            loss, _ = trainer.train_batch(next(cycle))
            last["cost"] = loss
            return loss

        timed_run(step_fn, args.burn_in)
        # --batches N sets the differential scale: arms of N and 4N.
        ms, spread = marginal_ms_with_spread(step_fn, n=n,
                                             repeats=args.repeats)
        protocol = "differential"
        mfu_val = None
    if trace_dir:
        # one traced, host-synced step AFTER timing (the profiler adds
        # overhead that must not contaminate the differential arms) —
        # the per-fusion attribution input for MFU campaigns.  A trace
        # failure must degrade to a missing trace, never discard the
        # measurement already taken.
        import jax
        try:
            jax.profiler.start_trace(trace_dir)
            timed_run(step_fn, 1)
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — report, keep the row
            print(f"trace capture failed ({type(e).__name__}: {e}); "
                  "timing row unaffected", file=sys.stderr)
            trace_dir = None
    out = {"ms_per_batch": ms, "batches": args.batches,
           "last_cost": float(last["cost"]), "protocol": protocol}
    if spread is not None:
        out["spread_ms"] = round(spread, 4)
    if mfu_val is not None:
        out["mfu"] = round(mfu_val, 4)
    if trace_dir:
        out["trace"] = trace_dir
    print(json.dumps(out))


def cmd_checkgrad(args):
    """Finite-difference gradient check of the configured model
    (--job=checkgrad twin, Trainer::checkGradient)."""
    from paddle_tpu import testing
    import paddle_tpu.nn as nn
    import jax
    # (check_grad_params forces f32-precision matmuls internally; the TPU
    # default bf16 tier would swamp the numeric gradient.)
    cfg = _load_config(args.config, args.config_args)
    if not hasattr(cfg, "train_reader"):
        raise SystemExit(f"{args.config}: checkgrad needs train_reader()")
    try:
        sample = next(iter(cfg.train_reader()))
    except StopIteration:
        raise SystemExit(f"{args.config}: train_reader() yielded no batches")
    import jax.numpy as jnp
    batch = {k: jnp.asarray(v) for k, v in sample.items()}
    model = nn.transform(lambda b: cfg.model_fn(b))
    params, state = model.init(jax.random.key(0), batch)
    if getattr(args, "init_model_path", None):
        # check gradients AT the trained point, as the reference job does
        from paddle_tpu.training import checkpoint as ckpt_lib
        params = ckpt_lib.apply_v1_params(
            params, ckpt_lib.load_v1_pass_dir(args.init_model_path))

    def loss_fn(p):
        (loss, _), _ = model.apply(p, state, None, batch)
        return loss

    testing.check_grad_params(loss_fn, params, eps=args.eps,
                              max_elems_per_leaf=args.elems)
    print(json.dumps({"checkgrad": "ok",
                      "params": len(jax.tree_util.tree_leaves(params))}))


def cmd_master(args):
    """Run the native task-dispatch master standalone (go/cmd/master twin):
    serves GetTask/TaskFinished/TaskFailed over TCP with timeout+retry
    queues and optional snapshot recovery."""
    import signal as _signal
    from paddle_tpu.distributed.master import Master, MasterServer

    # Master restores from snapshot_path in __init__ (and snapshots on its
    # own ack/interval cadence — not per tick, which would be constant IO).
    restored = bool(args.snapshot and os.path.exists(args.snapshot))
    master = Master(timeout_s=args.task_timeout,
                    max_failures=args.max_failures,
                    snapshot_path=args.snapshot,
                    snapshot_every=args.snapshot_every)
    if restored:
        print(json.dumps({"restored": args.snapshot}), flush=True)
    elif args.files:
        # set_tasks resets ALL queues — only on a fresh start, never after
        # a snapshot restore (it would wipe completed work).
        payloads = [p.encode() for p in args.files.split(",") if p]
        master.set_tasks(payloads)
    server = MasterServer(master, host=args.host, port=args.port)

    # Handlers BEFORE the readiness line: a supervisor may TERM us the
    # moment it has read the address, and the default action would skip
    # the final snapshot.
    stop = {"flag": False}

    def _on_term(signum, frame):
        stop["flag"] = True

    _signal.signal(_signal.SIGTERM, _on_term)
    _signal.signal(_signal.SIGINT, _on_term)

    host, port = server.address[0], server.address[1]
    print(json.dumps({"listening": f"{host}:{port}",
                      "tasks": master.counts()}), flush=True)
    try:
        while not stop["flag"]:
            time.sleep(1.0)
            master.tick()  # requeue timed-out tasks
    finally:
        if args.snapshot:
            master.snapshot(args.snapshot)  # final state on shutdown
        server.close()
        master.close()


def cmd_merge_model(args):
    from paddle_tpu import inference
    from paddle_tpu.training import checkpoint as ckpt_lib
    cfg = _load_config(args.config, args.config_args)
    trees, meta = ckpt_lib.load(args.checkpoint_dir)
    if args.format == "v1pass":
        # export back to the reference's pass-dir layout (the other
        # direction of --init-model-path)
        path = ckpt_lib.save_v1_pass_dir(
            args.output, trees["params"], trees.get("net_state"))
    else:
        path = inference.export_model(
            args.output, trees["params"], trees.get("net_state"),
            config={"source_checkpoint": args.checkpoint_dir,
                    "meta": meta})
    print(json.dumps({"exported": path}))


def main(argv=None):
    # JAX_PLATFORMS env is authoritative for the CLI.  force=True: the
    # CLI owns the process, so any pre-existing backend registry came
    # from an eager sitecustomize init, not user arrays.
    import paddle_tpu

    paddle_tpu._honor_env_platform(force=True)
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "lint":
        from paddle_tpu.analysis.cli import main as lint_main
        raise SystemExit(lint_main(argv[1:]))
    if argv and argv[0] == "telemetry":
        from paddle_tpu.telemetry.cli import main as telemetry_main
        raise SystemExit(telemetry_main(argv[1:]))
    parser = argparse.ArgumentParser(prog="paddle_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    def common(p, need_config=True):
        if need_config:
            p.add_argument("--config", required=True,
                           help="Python config file (see module docstring)")
            p.add_argument("--config-args", default="",
                           help="k=v,k=v passed to config_args() hook")
        p.add_argument("--checkpoint-dir", default=None)
        p.add_argument("--init-model-path", default=None,
                       help="reference v1 pass-%%05d dir of per-parameter "
                            "binary files to initialize from "
                            "(--init_model_path twin, ParamUtil.h:96-111)")

    p = sub.add_parser("train", help="train a model")
    common(p)
    p.add_argument("--num-passes", type=int, default=1)
    p.add_argument("--log-period", type=int, default=0)
    p.add_argument("--stats-period", type=int, default=0,
                   help="print per-parameter stats every N batches "
                        "(--show_parameter_stats_period twin)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--fp-checks", action="store_true",
                   help="raise on NaN under jit (feenableexcept twin)")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("test", help="evaluate a checkpoint")
    common(p)
    p.set_defaults(fn=cmd_test)

    p = sub.add_parser("time", help="benchmark ms/batch (--job=time twin)")
    common(p)
    p.add_argument("--batches", type=int, default=10,
                   help="differential scale n. Uniform-shape configs load "
                        "n batches, stack them, and time the compiled "
                        "multi-batch loop (arms of max(1, n//K) and "
                        "4*max(1, n//K) scan calls over the K=n stack); "
                        "otherwise arms run n and 4n per-dispatch batches")
    p.add_argument("--burn-in", type=int, default=10)
    p.add_argument("--repeats", type=int, default=3,
                   help="paired-difference repeats for the differential "
                        "protocol (odd keeps the median an order "
                        "statistic); raise for noisy CNN rows")
    p.add_argument("--trace", metavar="DIR", default=None,
                   help="capture a jax.profiler device trace of the "
                        "timed section into DIR (the per-fusion "
                        "attribution input for MFU campaigns; works "
                        "over the tunnel)")
    p.set_defaults(fn=cmd_time)

    p = sub.add_parser("checkgrad",
                       help="finite-difference grad check (--job=checkgrad)")
    common(p)
    p.add_argument("--eps", type=float, default=1e-3)
    p.add_argument("--elems", type=int, default=8)
    p.set_defaults(fn=cmd_checkgrad)

    p = sub.add_parser("master",
                       help="standalone task-dispatch master (go master twin)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--files", default="",
                   help="comma-separated task payloads (e.g. shard paths)")
    p.add_argument("--task-timeout", type=float, default=60.0)
    p.add_argument("--max-failures", type=int, default=3)
    p.add_argument("--snapshot", default=None,
                   help="snapshot file for crash recovery (put it on a "
                        "shared filesystem so a restarted master on "
                        "another host recovers, like the reference's "
                        "etcd store)")
    p.add_argument("--snapshot-every", type=int, default=32,
                   help="snapshot after this many task acks (1 = per ack, "
                        "the reference's per-state-change etcd cadence)")
    p.set_defaults(fn=cmd_master)

    # tpu-lint owns its own argparse surface — forward everything after
    # the subcommand verbatim (argparse.REMAINDER can't: it refuses to
    # start on an optional, so `lint --self-check` would bounce).
    sub.add_parser(
        "lint",
        help="tpu-lint static analyzer (python -m paddle_tpu.analysis "
             "twin); all arguments pass through, e.g. `lint --self-check`")

    # same forwarding scheme for the telemetry snapshot inspector
    sub.add_parser(
        "telemetry",
        help="inspect/diff telemetry JSONL snapshots (python -m "
             "paddle_tpu.telemetry twin); e.g. `telemetry show run.jsonl`")

    p = sub.add_parser("merge_model", help="export checkpoint for serving")
    common(p)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--format", choices=("merged", "v1pass"),
                   default="merged",
                   help="'merged' = serving dir (default); 'v1pass' = "
                        "reference pass-%%05d layout (deploy back onto "
                        "a reference install)")
    p.set_defaults(fn=cmd_merge_model)

    p = sub.add_parser("version")
    p.set_defaults(fn=lambda a: print(__version__))

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
