"""tpu-lint rule registry: the TPU-correctness traps this repo has hit.

Every rule is a class with a unique ``rule_id``, a ``severity``
(``error`` = correctness trap, CI-fatal; ``warn`` = perf/hygiene
advisory), a one-line ``doc``, and any of three hooks:

* ``check_eqn(eqn, state, ctx)`` — per equation, with walk state
  (loop depth, carry taint);
* ``check_jaxpr(jaxpr, state, ctx)`` — per (sub-)jaxpr, for rules that
  need def-use context;
* ``check_fn(fn, lowered, ctx, name)`` — per function, for rules that
  read jit metadata (donation) rather than equations.

Register with ``@register_rule``; ``active_rules()`` is what
:func:`paddle_tpu.analysis.lint` runs by default.  The shipped rules
are each grounded in a bug or hand-rolled guard from this repo's
history — see docs/design/analysis.md for the catalog.
"""

from __future__ import annotations

import inspect
from typing import Dict, List, Type

import jax
import numpy as np
from jax._src import core as jcore

__all__ = ["register_rule", "active_rules", "RULES", "Rule"]

_NARROW_FLOATS = ("bfloat16", "float16")


class Rule:
    rule_id: str = ""
    severity: str = "warn"
    family: str = "jaxpr"
    doc: str = ""


RULES: Dict[str, Type[Rule]] = {}


def register_rule(cls: Type[Rule]) -> Type[Rule]:
    assert cls.rule_id and cls.rule_id not in RULES, cls
    RULES[cls.rule_id] = cls
    return cls


def active_rules() -> List[Rule]:
    return [cls() for cls in RULES.values()]


def _is_var(v) -> bool:
    return isinstance(v, jcore.Var)


def _dtype_name(aval) -> str:
    try:
        return np.dtype(aval.dtype).name
    except TypeError:           # jax extended dtypes (PRNG keys, ...)
        return str(aval.dtype)


# ----------------------------------------------------------- accum-dtype


@register_rule
class AccumDtypeRule(Rule):
    """Generalizes PR 1's attention fix: a ``dot_general``/``conv`` on
    bf16/f16 operands whose result materializes in the narrow dtype
    accumulates partial sums in bf16 — silent precision loss that grows
    with the contraction size.  ``preferred_element_type=jnp.float32``
    keeps the MXU accumulator f32 and downcasts once, in the epilogue.
    """

    rule_id = "accum-dtype"
    severity = "error"
    doc = ("dot/einsum/conv accumulating in bf16/f16 without "
           "preferred_element_type=float32 (incl. dequant-matmul "
           "chains from int8 sources)")

    _PRIMS = ("dot_general", "conv_general_dilated")
    # elementwise/layout ops a dequantization chain is made of:
    # convert(int8) -> * scale -> (broadcast/reshape/transpose) -> dot
    _DEQUANT_CHAIN = ("convert_element_type", "mul", "add",
                      "broadcast_in_dim", "reshape", "transpose")

    def check_eqn(self, eqn, state, ctx):
        if eqn.primitive.name not in self._PRIMS:
            return
        in_dtypes = [_dtype_name(v.aval) for v in eqn.invars[:2]]
        out_dtype = _dtype_name(eqn.outvars[0].aval)
        if (all(d in _NARROW_FLOATS for d in in_dtypes)
                and out_dtype in _NARROW_FLOATS):
            ctx.report(
                self, f"{state.path}/{eqn.primitive.name}",
                f"{eqn.primitive.name} on {in_dtypes[0]} operands "
                f"accumulates in {out_dtype}",
                eqn=eqn,
                suggestion="pass preferred_element_type=jnp.float32 "
                           "(cast the result back if the policy wants "
                           "narrow outputs)")

    def check_jaxpr(self, jaxpr, state, ctx):
        # The DEQUANT-MATMUL face of the same trap (PR 12's int8 KV
        # pools): a dot whose operand IS (or traces, through a short
        # dequant chain, to) a quantized byte-wide int tensor, with the
        # result materializing in a narrow float — the dequantized
        # values lose their one recovery of precision in the
        # accumulator.  The all-narrow-operand form is check_eqn's;
        # this hook covers the dots that slip it because one operand's
        # dtype is integral.  Byte-wide int kinds only — bool masks and
        # int32 index math are not quantized data.
        producers = {}
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                producers[id(v)] = eqn

        def _quant_source(v, depth):
            if not _is_var(v):
                return None
            try:
                dt = np.dtype(v.aval.dtype)
            except TypeError:               # extended dtypes (PRNG, ...)
                return None
            if dt.kind in "iu" and dt.itemsize == 1:
                return v.aval
            prod = producers.get(id(v))
            if prod is None or depth >= 6 or \
                    prod.primitive.name not in self._DEQUANT_CHAIN:
                return None
            for iv in prod.invars:
                src = _quant_source(iv, depth + 1)
                if src is not None:
                    return src
            return None

        for eqn in jaxpr.eqns:
            if eqn.primitive.name not in self._PRIMS:
                continue
            out_dtype = _dtype_name(eqn.outvars[0].aval)
            if out_dtype not in _NARROW_FLOATS:
                continue
            in_dtypes = [_dtype_name(v.aval) for v in eqn.invars[:2]]
            if all(d in _NARROW_FLOATS for d in in_dtypes):
                continue            # check_eqn already reported this one
            for v in eqn.invars[:2]:
                src = _quant_source(v, 0)
                if src is not None:
                    ctx.report(
                        self, f"{state.path}/{eqn.primitive.name}",
                        f"dequant-matmul: {eqn.primitive.name} operand "
                        f"traces to a {_dtype_name(src)} quantized "
                        f"tensor but accumulates in {out_dtype}",
                        eqn=eqn,
                        suggestion="dequantize into f32 (scale in f32, "
                                   "preferred_element_type=jnp.float32)"
                                   " so the only rounding is the int8 "
                                   "grid itself")
                    break


# ---------------------------------------------------- weak-type-promotion


@register_rule
class WeakTypePromotionRule(Rule):
    """A Python/weak scalar operand silently rewriting an ARRAY's dtype:
    ``bf16_array * np.float32(2)`` upcasts the whole array to f32 (2x
    HBM on the hot path), ``int_array * 0.5`` floats an index tensor.
    Detected as a widening/kind-changing ``convert_element_type``
    inserted at the SAME source line as the binary op that consumes it
    against a scalar — an explicit ``.astype`` on its own line stays
    quiet."""

    rule_id = "weak-type-promotion"
    severity = "warn"
    doc = "Python scalar operand silently widening an array dtype"

    _BINOPS = ("add", "sub", "mul", "div", "max", "min", "pow", "rem",
               "atan2")

    def check_jaxpr(self, jaxpr, state, ctx):
        from paddle_tpu.analysis.core import _user_frame
        producers = {}
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                producers[id(v)] = eqn
        for eqn in jaxpr.eqns:
            if eqn.primitive.name not in self._BINOPS:
                continue
            if len(eqn.invars) != 2:
                continue

            def _scalarish(v):
                return (isinstance(v, jcore.Literal)
                        or getattr(v.aval, "shape", None) == ())

            for arr_side, other in (eqn.invars, eqn.invars[::-1]):
                if not _scalarish(other) or not _is_var(arr_side):
                    continue
                prod = producers.get(id(arr_side))
                if prod is None or prod.primitive.name != \
                        "convert_element_type":
                    continue
                src = prod.invars[0].aval
                dst = prod.outvars[0].aval
                if int(np.prod(src.shape)) <= 1:
                    continue
                widened = (np.dtype(dst.dtype).itemsize
                           > np.dtype(src.dtype).itemsize)
                kind_change = (np.dtype(src.dtype).kind
                               != np.dtype(dst.dtype).kind)
                if not (widened or kind_change):
                    continue
                # implicit promotion materializes the convert at the
                # binary op's own source line; explicit .astype lives
                # on its own line and is intentional
                if _user_frame(prod) != _user_frame(eqn):
                    continue
                ctx.report(
                    self, f"{state.path}/{eqn.primitive.name}",
                    f"array {tuple(src.shape)} silently promoted "
                    f"{_dtype_name(src)} -> {_dtype_name(dst)} by a "
                    f"scalar operand of {eqn.primitive.name}",
                    eqn=eqn,
                    suggestion="make the scalar's dtype explicit (e.g. "
                               "jnp.asarray(c, x.dtype)) or upcast "
                               "deliberately with .astype on its own "
                               "line")
                break


# --------------------------------------------------- host-callback-in-loop


@register_rule
class HostCallbackInLoopRule(Rule):
    """The serving decode loop must stay device-resident: a
    ``pure_callback``/``io_callback``/``debug.print`` inside a
    ``while``/``scan`` body forces a host round trip EVERY iteration —
    milliseconds per token on a tunneled attachment, and it serializes
    the loop."""

    rule_id = "host-callback-in-loop"
    severity = "error"
    doc = "host callback (pure/io/debug) inside a while/scan body"

    _PRIMS = ("pure_callback", "io_callback", "debug_callback",
              "callback", "outside_call")

    def check_eqn(self, eqn, state, ctx):
        if state.loop_depth < 1 or eqn.primitive.name not in self._PRIMS:
            return
        ctx.report(
            self, f"{state.path}/{eqn.primitive.name}",
            f"{eqn.primitive.name} at loop depth {state.loop_depth} — "
            "the loop body round-trips to the host every iteration",
            eqn=eqn,
            suggestion="move the callback outside the loop, or carry "
                       "the value out and print after the loop exits")


# ------------------------------------------------------- gather-in-decode


@register_rule
class GatherInDecodeRule(Rule):
    """A gather / dynamic_slice whose indices derive from a LOOP CARRY
    re-gathers every iteration — the paged-attention traffic pattern.
    Loop-invariant indices stay quiet (XLA hoists them).  With
    ``with_cost=True`` the finding carries the whole-program
    ``cost_analysis()`` flops/bytes — the static twin of the
    gather-vs-dense crossover measured by ``benchmark/lm_decode.py``.
    """

    rule_id = "gather-in-decode"
    severity = "warn"
    doc = "carry-dependent gather/dynamic_slice inside a decode loop"

    def check_eqn(self, eqn, state, ctx):
        if state.loop_depth < 1:
            return
        prim = eqn.primitive.name
        if prim == "gather":
            index_ops = eqn.invars[1:2]
        elif prim == "dynamic_slice":
            index_ops = eqn.invars[1:]
        else:
            return
        if not any(_is_var(v) and state.is_tainted(v) for v in index_ops):
            return
        operand = eqn.invars[0].aval
        ctx.report(
            self, f"{state.path}/{prim}",
            f"{prim} over {tuple(operand.shape)} "
            f"{_dtype_name(operand)} with carry-dependent indices runs "
            "every loop iteration",
            eqn=eqn, attach_cost=True,
            suggestion="fuse the gather into a kernel — the Pallas "
                       "paged decode kernel "
                       "(ops/pallas_paged_attention.py) is the worked "
                       "example; this XLA-HBM rule skips kernel "
                       "bodies (the kernel-scoped family in "
                       "kernel_rules.py checks them instead); "
                       "otherwise hoist the indices, or suppress "
                       "if the per-step gather is the op's contract "
                       "(free-list alloc, KV append)")


# ------------------------------------------------------------- dead-code


@register_rule
class DeadCodeRule(Rule):
    """Computed-but-unreturned equations (traced work XLA may or may
    not DCE — and the trace says intent is muddled either way) and
    threaded-but-unread loop carries (a carry passed through
    ``while``/``scan`` unchanged and never read costs carry bandwidth
    every iteration and hides a stale value)."""

    rule_id = "dead-code"
    severity = "warn"
    doc = "dead outputs / threaded-but-unread loop carries"

    def check_jaxpr(self, jaxpr, state, ctx):
        used = set()
        for eqn in jaxpr.eqns:
            used.update(id(v) for v in eqn.invars if _is_var(v))
        used.update(id(v) for v in jaxpr.outvars if _is_var(v))
        for eqn in jaxpr.eqns:
            if eqn.effects:
                continue
            if any(id(v) in used for v in eqn.outvars):
                continue
            ctx.report(
                self, f"{state.path}/{eqn.primitive.name}",
                f"result of {eqn.primitive.name} "
                f"({', '.join(_dtype_name(v.aval) + str(tuple(v.aval.shape)) for v in eqn.outvars[:1])}) "
                "is never used",
                eqn=eqn,
                suggestion="delete the computation or return it")

    def check_eqn(self, eqn, state, ctx):
        prim = eqn.primitive.name
        if prim == "while":
            body = eqn.params["body_jaxpr"].jaxpr
            cond = eqn.params["cond_jaxpr"].jaxpr
            bn = eqn.params["body_nconsts"]
            cn = eqn.params["cond_nconsts"]
            carries = body.invars[bn:]
            outs = body.outvars
            cond_carries = cond.invars[cn:]
            read = set()
            for e in list(body.eqns) + list(cond.eqns):
                read.update(id(v) for v in e.invars if _is_var(v))
            for i, cv in enumerate(carries):
                cond_cv = (cond_carries[i]
                           if i < len(cond_carries) else None)
                if id(cv) in read or (cond_cv is not None
                                      and id(cond_cv) in read):
                    continue
                if i < len(outs) and outs[i] is cv:
                    self._report_carry(ctx, state, eqn, i, cv, "while")
        elif prim == "scan":
            inner = eqn.params["jaxpr"].jaxpr
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            carries = inner.invars[nc:nc + ncar]
            outs = inner.outvars[:ncar]
            read = set()
            for e in inner.eqns:
                read.update(id(v) for v in e.invars if _is_var(v))
            for i, cv in enumerate(carries):
                if id(cv) in read:
                    continue
                if i < len(outs) and outs[i] is cv:
                    self._report_carry(ctx, state, eqn, i, cv, "scan")

    def _report_carry(self, ctx, state, eqn, i, cv, kind):
        ctx.report(
            self, f"{state.path}/{kind}",
            f"loop carry #{i} ({_dtype_name(cv.aval)}"
            f"{tuple(cv.aval.shape)}) is threaded through the {kind} "
            "but never read",
            eqn=eqn,
            suggestion="drop it from the carry (close over it instead) "
                       "— it costs carry bandwidth every iteration")


# --------------------------------------------------------- donation-audit


@register_rule
class DonationAuditRule(Rule):
    """A jitted step that RETURNS an updated version of a large buffer
    argument without donating it makes XLA keep both copies live — the
    trainer donates params/opt_state for exactly this reason, and the
    paged decode step's KV pool is the same shape of buffer.  Flags
    non-donated args at least ``min_bytes`` whose (shape, dtype)
    matches an output."""

    rule_id = "donation-audit"
    severity = "warn"
    doc = "large buffer arg returned updated but not donated"

    def __init__(self, min_bytes: int = 1 << 16):
        self.min_bytes = min_bytes

    def check_fn(self, fn, lowered, ctx, name):
        if lowered is None:
            return
        try:
            args_info = lowered.args_info
            out_info = lowered.out_info
        except Exception:
            return
        out_leaves = jax.tree_util.tree_leaves(
            out_info, is_leaf=lambda x: hasattr(x, "shape"))
        # multiset of output signatures: each donated arg ABSORBS one
        # matching output (that pair is already in-place), and each
        # finding consumes one — so N same-shaped args against one
        # updated output yield one finding, not N
        out_sigs: Dict = {}
        for o in out_leaves:
            sig = (tuple(o.shape), _dtype_name(o))
            out_sigs[sig] = out_sigs.get(sig, 0) + 1
        file = line = None
        try:
            src = inspect.unwrap(fn)
            code = getattr(src, "__wrapped__", src).__code__
            file, line = code.co_filename, code.co_firstlineno
        except Exception:
            pass
        flat, _ = jax.tree_util.tree_flatten_with_path(
            args_info, is_leaf=lambda x: hasattr(x, "donated"))

        def _sig(info):
            aval = getattr(info, "aval", info)
            return tuple(aval.shape), _dtype_name(aval)

        for _, info in flat:
            if info.donated and out_sigs.get(_sig(info), 0) > 0:
                out_sigs[_sig(info)] -= 1
        for path, info in flat:
            if info.donated:
                continue
            shape, dtype_name = _sig(info)
            try:
                itemsize = np.dtype(dtype_name).itemsize
            except TypeError:   # extended dtypes are never donation
                continue        # targets worth flagging
            nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize
            if nbytes < self.min_bytes:
                continue
            if out_sigs.get((shape, dtype_name), 0) <= 0:
                continue
            out_sigs[(shape, dtype_name)] -= 1
            ctx.report(
                self, name or "fn",
                f"arg {jax.tree_util.keystr(path)} ({dtype_name}"
                f"{shape}, {nbytes / 2**20:.1f} MiB) is returned "
                "updated but not donated — two live copies on device",
                file=file, line=line,
                suggestion="pass donate_argnums for it to jax.jit (the "
                           "old buffer is dead after the step)")
