"""tpu-lint core: trace a callable to a jaxpr and walk it with rules.

The serving and training contracts this repo enforces by hand — f32
matmul accumulation, device-resident decode loops, ``compiles == 1``,
donated step buffers — are all *whole-program* properties of the traced
jaxpr, which is exactly the artifact ``jax.make_jaxpr`` hands us for
free on any backend (the walk runs under ``JAX_PLATFORMS=cpu``; no
chip is touched).  :func:`lint` traces a callable, recurses through
every control-flow sub-jaxpr (``while``/``scan``/``cond``/``pjit``/
custom-derivative wrappers), and hands each equation to the registered
rules (``rules.py``), which emit structured :class:`Finding`s.

Walk state the rules key on:

* ``loop_depth`` — how many ``while``/``scan`` bodies enclose the
  equation (the serving hot path lives at depth >= 1);
* carry taint — the set of vars derived from loop carries / scanned
  inputs, i.e. values that CHANGE across iterations.  A gather whose
  indices are loop-invariant is hoistable; one fed by a carry is the
  real per-step gather traffic (``gather-in-decode``).

Suppressions are source comments, clang-tidy style::

    y = jnp.dot(a, b)  # tpu-lint: disable=accum-dtype
    # tpu-lint: disable=all            (line above also counts)

Findings carry the rule id, severity, the equation path through the
sub-jaxpr tree (``pjit:_pserve/while.body/gather``), the user source
location, a message, and a suggestion.
"""

from __future__ import annotations

import dataclasses
import linecache
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
from jax._src import core as jcore
from jax._src import source_info_util

__all__ = ["Finding", "LintTarget", "lint", "SEVERITIES", "severity_rank"]

# Severity policy (docs/design/analysis.md): "error" = a correctness
# trap (silent bf16 accumulation, host callback on the decode hot
# path) — CI fails on these; "warn" = a perf/hygiene advisory (gather
# traffic, dead code, missed donation); "info" = informational.
SEVERITIES = ("info", "warn", "error")


def severity_rank(severity: str) -> int:
    return SEVERITIES.index(severity)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule_id: str
    severity: str
    path: str                 # eqn path through the sub-jaxpr tree
    message: str
    suggestion: str = ""
    file: Optional[str] = None
    line: Optional[int] = None
    cost: Optional[Dict[str, float]] = None   # program-level, if computed
    family: str = "jaxpr"     # jaxpr | shard | kernel | host | pool
    # Suppressed findings are dropped at report time unless the run
    # asks to keep them (``--json`` artifacts show what WAS silenced);
    # gates/ratchets/summaries must filter on this flag.
    suppressed: bool = False

    def location(self) -> str:
        if self.file is None:
            return "<no source>"
        return f"{self.file}:{self.line}"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintTarget:
    """A lintable entrypoint: a callable plus example arguments.

    ``fn`` may be a ``jax.jit`` product (then donation metadata and —
    with ``with_cost`` — XLA cost analysis are available via
    ``.lower()``) or any traceable callable.  ``args``/``kwargs`` may
    be concrete arrays or ``jax.ShapeDtypeStruct``s; nothing is
    executed, only traced.

    ``recipe`` (a :class:`paddle_tpu.analysis.shard_rules.ShardRecipe`,
    optional) declares the mesh + per-argument shardings this
    entrypoint ships with in production; when present,
    :func:`paddle_tpu.analysis.shard_rules.shard_check` additionally
    lowers the program under that mesh and runs the SPMD rule family
    (collective placement, replication waste, reshard churn) plus the
    per-shard HBM footprint estimate.  Recipe-less targets lint
    single-device exactly as before.
    """
    name: str
    fn: Callable
    args: Tuple = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    recipe: Any = None           # ShardRecipe | None (no import cycle)


# --------------------------------------------------------------- suppression

_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([\w\-,]+)")


def _suppressed(file: Optional[str], line: Optional[int],
                rule_id: str) -> bool:
    """True if the flagged source line (or the line above it) carries a
    ``# tpu-lint: disable=<rule[,rule...]>`` or ``disable=all`` comment."""
    if file is None or line is None:
        return False
    for ln in (line, line - 1):
        if ln < 1:
            continue
        m = _SUPPRESS_RE.search(linecache.getline(file, ln))
        if m:
            names = {n.strip() for n in m.group(1).split(",")}
            if "all" in names or rule_id in names:
                return True
    return False


def _user_frame(eqn) -> Tuple[Optional[str], Optional[int]]:
    try:
        fr = source_info_util.user_frame(eqn.source_info)
    except Exception:
        fr = None
    if fr is None:
        return None, None
    return fr.file_name, fr.start_line


# ------------------------------------------------------------------ context


class LintContext:
    """Accumulates findings for one :func:`lint` run, applying
    suppressions and rule disables at report time."""

    def __init__(self, disable: Sequence[str] = (),
                 cost: Optional[Dict[str, float]] = None,
                 opaque_kernels: bool = False,
                 keep_suppressed: bool = False):
        self.findings: List[Finding] = []
        self.disable = set(disable)
        self.cost = cost          # whole-program cost_analysis(), if any
        # escape hatch for third-party kernels: skip the kernel-rule
        # descent into pallas_call bodies (lint(opaque_kernels=True))
        self.opaque_kernels = opaque_kernels
        # keep source-suppressed findings, flagged, instead of dropping
        # them (the ``--json`` artifact records what was silenced)
        self.keep_suppressed = keep_suppressed

    def report(self, rule, path: str, message: str, *, eqn=None,
               suggestion: str = "", file: Optional[str] = None,
               line: Optional[int] = None, attach_cost: bool = False):
        if rule.rule_id in self.disable:
            return
        if eqn is not None and file is None:
            file, line = _user_frame(eqn)
        suppressed = _suppressed(file, line, rule.rule_id)
        if suppressed and not self.keep_suppressed:
            return
        self.findings.append(Finding(
            rule_id=rule.rule_id, severity=rule.severity, path=path,
            message=message, suggestion=suggestion, file=file, line=line,
            cost=self.cost if attach_cost else None,
            family=getattr(rule, "family", "jaxpr"),
            suppressed=suppressed))


# ------------------------------------------------------------------- walker


@dataclasses.dataclass
class WalkState:
    """Per-sub-jaxpr walk state handed to every rule."""
    path: str = ""
    loop_depth: int = 0
    tainted: frozenset = frozenset()     # ids of vars derived from carries

    def at(self, segment: str, *, enter_loop: bool = False,
           tainted=None) -> "WalkState":
        return WalkState(
            path=f"{self.path}/{segment}" if self.path else segment,
            loop_depth=self.loop_depth + (1 if enter_loop else 0),
            tainted=self.tainted if tainted is None else tainted)

    def is_tainted(self, var) -> bool:
        return id(var) in self.tainted


def _inner_taint(state: WalkState, outer_invars, inner_invars,
                 extra_tainted=()) -> frozenset:
    """Map taint across a sub-jaxpr boundary: inner invar i is tainted
    iff the outer operand feeding it is, plus any explicitly-seeded
    vars (loop carries)."""
    tainted = {id(v) for v in extra_tainted}
    for outer, inner in zip(outer_invars, inner_invars):
        if isinstance(outer, jcore.Var) and state.is_tainted(outer):
            tainted.add(id(inner))
    return frozenset(tainted)


def _closed(j):
    """Normalize Jaxpr / ClosedJaxpr to ClosedJaxpr."""
    if isinstance(j, jcore.ClosedJaxpr):
        return j
    return jcore.ClosedJaxpr(j, ())


def _walk(closed_jaxpr, rules, ctx: LintContext, state: WalkState):
    jaxpr = closed_jaxpr.jaxpr
    for rule in rules:
        check = getattr(rule, "check_jaxpr", None)
        if check is not None:
            check(jaxpr, state, ctx)
    tainted = set(state.tainted)
    for eqn in jaxpr.eqns:
        # taint propagation: any output of an eqn fed by a tainted var
        # is itself iteration-varying
        if any(isinstance(v, jcore.Var) and id(v) in tainted
               for v in eqn.invars):
            tainted.update(id(v) for v in eqn.outvars)
        eqn_state = dataclasses.replace(state, tainted=frozenset(tainted))
        for rule in rules:
            check = getattr(rule, "check_eqn", None)
            if check is not None:
                check(eqn, eqn_state, ctx)
        _descend(eqn, rules, ctx, eqn_state, jaxpr)


def _descend(eqn, rules, ctx: LintContext, state: WalkState,
             enclosing_jaxpr=None):
    """Recurse into an equation's sub-jaxprs with the right loop-depth
    and carry-taint seeding per control-flow primitive."""
    prim = eqn.primitive.name
    params = eqn.params
    if prim == "pjit":
        inner = _closed(params["jaxpr"])
        seg = f"pjit:{params.get('name', '?')}"
        t = _inner_taint(state, eqn.invars, inner.jaxpr.invars)
        _walk(inner, rules, ctx, state.at(seg, tainted=t))
    elif prim == "while":
        cond = _closed(params["cond_jaxpr"])
        body = _closed(params["body_jaxpr"])
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        # carries = body invars past the consts; they (and anything
        # they feed) change every iteration
        carries = body.jaxpr.invars[bn:]
        t = _inner_taint(state, eqn.invars[cn + bn:],
                         body.jaxpr.invars[bn:], extra_tainted=carries)
        _walk(body, rules, ctx,
              state.at("while.body", enter_loop=True, tainted=t))
        tc = _inner_taint(state, eqn.invars[cn + bn:],
                          cond.jaxpr.invars[cn:],
                          extra_tainted=cond.jaxpr.invars[cn:])
        _walk(cond, rules, ctx,
              state.at("while.cond", enter_loop=True, tainted=tc))
    elif prim == "scan":
        inner = _closed(params["jaxpr"])
        nc = params["num_consts"]
        # carries AND the per-iteration xs slices vary across steps
        varying = inner.jaxpr.invars[nc:]
        t = _inner_taint(state, eqn.invars[nc:], inner.jaxpr.invars[nc:],
                         extra_tainted=varying)
        _walk(inner, rules, ctx,
              state.at("scan.body", enter_loop=True, tainted=t))
    elif prim == "cond":
        for i, br in enumerate(params["branches"]):
            br = _closed(br)
            t = _inner_taint(state, eqn.invars[1:], br.jaxpr.invars)
            _walk(br, rules, ctx,
                  state.at(f"cond.branch{i}", tainted=t))
    elif prim in ("custom_jvp_call", "custom_vjp_call",
                  "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"):
        inner = params.get("call_jaxpr") or params.get("fun_jaxpr")
        if inner is not None:
            inner = _closed(inner)
            t = _inner_taint(state, eqn.invars, inner.jaxpr.invars)
            _walk(inner, rules, ctx, state.at(prim, tainted=t))
    elif prim == "pallas_call":
        # Kernel bodies get their OWN rule family (kernel_rules.py):
        # the inner jaxpr runs under Mosaic's machine model (VMEM refs,
        # explicit grid pipelining), where XLA-HBM rules like
        # gather-in-decode are category errors — a kernel's ref
        # indexing would false-fire them — so the XLA rules still skip
        # it, and the kernel-scoped family (vmem-budget,
        # scratch-accum-dtype, oob-index-map, masking-completeness)
        # checks the kernel contract instead.  ``opaque_kernels=True``
        # restores the old skip for third-party kernels.
        if not getattr(ctx, "opaque_kernels", False):
            from paddle_tpu.analysis.kernel_rules import check_pallas_call
            check_pallas_call(eqn, state, ctx, enclosing_jaxpr)
        return
    else:
        # generic fallback (remat/checkpoint, closed_call, ...): walk any
        # jaxpr-valued param without taint mapping — better to see inside
        # with imprecise taint than to skip a subtree
        for key, val in params.items():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v in vals:
                if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    _walk(_closed(v), rules, ctx,
                          state.at(f"{prim}.{key}"))


# -------------------------------------------------------------------- lint


def _program_cost(lowered) -> Optional[Dict[str, float]]:
    """Best-effort whole-program ``cost_analysis()`` (flops / bytes
    accessed) from the compiled executable — the static twin of the
    ROADMAP's measured gather-traffic crossover."""
    try:
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return {k: float(v) for k, v in ca.items()
                if k in ("flops", "bytes accessed")}
    except Exception:
        return None


def lint(fn: Callable, args: Tuple = (), kwargs: Optional[Dict] = None,
         *, name: str = "", rules=None, disable: Sequence[str] = (),
         with_cost: bool = False, opaque_kernels: bool = False,
         keep_suppressed: bool = False) -> List[Finding]:
    """Trace ``fn(*args, **kwargs)`` and run the rule registry over the
    resulting jaxpr.  Returns findings sorted most-severe-first.

    ``args`` may be concrete arrays or ``ShapeDtypeStruct``s — nothing
    executes.  ``disable`` removes rules by id for this run;
    ``with_cost=True`` additionally compiles the program (CPU) and
    attaches whole-program flops/bytes to cost-aware findings.
    ``opaque_kernels=True`` skips the kernel-rule descent into
    ``pallas_call`` bodies (third-party kernels the kernel contract
    does not apply to).
    """
    if rules is None:
        from paddle_tpu.analysis.rules import active_rules
        rules = active_rules()
    kwargs = kwargs or {}
    closed = jax.make_jaxpr(fn)(*args, **kwargs)

    lowered = None
    if hasattr(fn, "lower"):
        try:
            lowered = fn.lower(*args, **kwargs)
        except Exception:
            lowered = None
    cost = _program_cost(lowered) if (with_cost and lowered) else None

    ctx = LintContext(disable=disable, cost=cost,
                      opaque_kernels=opaque_kernels,
                      keep_suppressed=keep_suppressed)
    _walk(closed, rules, ctx, WalkState(path=name))

    # function-level rules (donation-audit) see the lowering, not eqns
    for rule in rules:
        check = getattr(rule, "check_fn", None)
        if check is not None and rule.rule_id not in ctx.disable:
            check(fn, lowered, ctx, name or getattr(fn, "__name__", "fn"))
    ctx.findings.sort(key=lambda f: (-severity_rank(f.severity),
                                     f.rule_id, f.file or "", f.line or 0))
    return ctx.findings


def lint_target(target: LintTarget, **kw) -> List[Finding]:
    return lint(target.fn, target.args, target.kwargs,
                name=target.name, **kw)
