"""tpu-lint POOL rule family: ownership & refcount discipline for the
paged KV block pool, proved from the AST of the pool's client modules.

The paged block pool (``ops/paged_attention.py``) is refcounted: a
block is free at rc 0, exclusively owned at rc 1, shared/pinned above
that.  Every serving feature since the paged engine landed — prefix
sharing, speculative rollback, the cluster handoff, the host-RAM spill
tier — is one more *owner* of the same pool, each with a documented
acquire/release contract that was, until this family, defended only by
randomized runtime property tests.  This module makes the contract a
per-commit static check, the exact move ``host_rules.py`` made for the
lock discipline: build a model from the AST, run rules over it, anchor
findings to real source lines.

Per registered client module (:data:`POOL_CLIENT_MODULES`) the
analysis builds an **ownership model** from the ``paged_*`` API
surface:

* every call site of a pool op — ``paged_reserve`` / ``paged_free`` /
  ``paged_share`` / ``paged_rc_add`` / ``paged_cow`` /
  ``paged_rollback`` / ``paged_append`` / ``paged_export_block(s)`` /
  ``paged_import_blocks`` — classified as ACQUIRE (reserve, import),
  RELEASE (free, rollback), SHARE, PIN (rc_add), EXPORT, or USE
  (append, cow, advance), with jitted engine aliases resolved through
  ``self.X = jax.jit(paged.paged_Y, ...)`` assignments (the serving
  engine calls ``self._free``, never ``paged_free`` directly);
* per function (class methods, module functions, AND nested defs —
  the traced step programs are closures, each its own ownership
  scope): the ordered op-event list, the binding each ACQUIRE's
  result lands in, and how that binding escapes (returned, stored to
  an attribute, passed whole to another call — ownership transfer);
* per class: op-effect summaries threaded through intra-class
  ``self.method()`` call edges, the same flood the host family uses
  for thread roots — a ledger enforce living in a helper the writer
  calls still counts.

The rule registry then checks:

* ``unbalanced-acquire`` — an ACQUIRE whose result binding never
  escapes: not released, not returned, not stored, not handed to
  another op.  The claimed blocks' refcounts were committed on device
  and the handle dropped on the floor — the refcount-leak class the
  randomized properties hunt at runtime.  An explicit ``raise``
  between the acquire and the first escape is the exception-edge form
  of the same leak and reports too.
* ``share-before-pin`` — on an import path (restore/handoff), a
  ``paged_share`` that runs before the ``paged_rc_add`` pin.  The
  write-then-pin-then-share ordering exists because a concurrent
  claim can zero a just-restored page the instant it is shared but
  not yet pinned; PR 16 documents it, this rule enforces it.
* ``cow-slack-bypass`` — an admission-side increase of the
  ``_reserved`` / ``_pinned`` ledger with neither a capacity check
  against the pool bound (``nb``) nor a balancing transfer on another
  ledger field in reach (own function or a self-callee).  Growth
  without enforce is how a pool overcommits past the COW slack.
* ``append-after-free`` — a name passed to ``paged_free`` /
  ``paged_rollback`` flowing into a later ``paged_append`` /
  ``paged_share`` in the same function: the freed/rolled-back slot id
  is stale; appending through it writes into blocks the allocator may
  already have handed to someone else.
* ``export-mutation`` — a pool mutation (reserve / share / cow /
  import / append / advance) after a ``paged_export_block(s)`` in the
  same function.  Exports copy, so the pages are safe — but the
  payload's block ids and length describe a pool state that no longer
  exists when it reaches the wire: the stale-payload class.
  Releasing the exported slot (``paged_free`` — the handoff epilogue)
  is the sanctioned order and stays quiet.

Proved vs tested (honest caveats, mirrored in
``docs/design/analysis.md``): the model is name-based, not points-to
— escape analysis tracks the binding a result lands in, so rebinding
through a container index or threading state through an object the
walker cannot see escapes conservatively (no finding); dataflow in
``append-after-free`` is same-name, same-function; the ordering rules
compare source positions, not path-sensitive dominance, so an
acquire/share inside one branch and its release/pin in another can
evade or over-report (none of the shipped clients are shaped that
way).  The runtime twin — :func:`~paddle_tpu.ops.paged_attention.
paged_reconcile` — keeps covering what the AST cannot see: it checks
the *materialized* pool (refcounts == table references + registry
pins, free set consistent) on live engines, and the consolidated
property helpers (``tests/helpers_pool.py``) drive both sides against
the same seeded leak.

``pool_self_check()`` is the wiring smoke ``--self-check`` rides: a
refcount-leak mutant and a share-before-pin ordering mutant must each
produce exactly one finding through the full ``pool_check`` path, and
their clean twins must stay quiet.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.core import Finding, LintContext, severity_rank

__all__ = [
    "POOL_CLIENT_MODULES", "POOL_RULES", "PoolRule", "PoolModuleModel",
    "active_pool_rules", "analyze_pool_module", "pool_check",
    "pool_check_sources", "pool_self_check", "register_pool_rule",
    "resolve_pool_modules",
]

#: The registered pool-client module set ``lint --pool`` covers: every
#: module that acquires, releases, shares, or ships paged blocks.
#: Modules with no direct pool calls today (speculative's host policy,
#: the cluster roles that drive engines through their public API) ride
#: along cheaply and prove they STAY free of raw pool access.
POOL_CLIENT_MODULES = (
    "paddle_tpu.serving",
    "paddle_tpu.prefix_cache",
    "paddle_tpu.speculative",
    "paddle_tpu.adapters",
    "paddle_tpu.cluster.worker",
    "paddle_tpu.cluster.controller",
)

#: op name -> ownership kind.  Anything else spelled ``paged_*``
#: (init, advance, concat, the attention entrypoints) is tracked as a
#: neutral USE so the event stream stays complete.  The LoRA adapter
#: pool (``ops/adapters.py``) spells its slot ownership through the
#: same verbs — ``paged_adapter_reserve`` / ``paged_adapter_free`` /
#: ``paged_adapter_rc_add`` — so its clients lint under the identical
#: acquire/release/pin discipline as the KV block pool's.
_ACQUIRE_OPS = {"paged_reserve", "paged_import_blocks",
                "paged_adapter_reserve"}
_RELEASE_OPS = {"paged_free", "paged_rollback", "paged_adapter_free"}
_SHARE_OPS = {"paged_share"}
_PIN_OPS = {"paged_rc_add", "paged_adapter_rc_add"}
_EXPORT_OPS = {"paged_export_block", "paged_export_blocks"}
#: mutations that invalidate an already-exported payload's block-id /
#: length description of the pool.  free/rollback are absent BY
#: CONTRACT: export-then-release is the handoff epilogue (the payload
#: is a copy; releasing the donor slot is the point of exporting).
_EXPORT_MUTATORS = {"paged_reserve", "paged_share", "paged_cow",
                    "paged_import_blocks", "paged_append",
                    "paged_advance"}
#: ops a freed/rolled-back id must never flow into
_STALE_USE_OPS = {"paged_append", "paged_share"}

#: host-side admission-ledger fields (serving.py): ``_reserved`` +
#: ``_pinned`` must stay <= the pool bound; ``blocks_reserved`` is the
#: per-request share of ``_reserved`` that transfers ledger weight.
_LEDGER_FIELDS = {"_reserved", "_pinned", "blocks_reserved"}
#: attribute/name leaves that count as the pool-capacity bound in a
#: comparison (``self.nb``, a local ``nb``)
_CAPACITY_NAMES = {"nb"}


def _dotted(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


# ------------------------------------------------------------------ model


@dataclasses.dataclass
class OpEvent:
    """One pool-op call site inside a function."""
    op: str                         # canonical paged_* name
    line: int
    result: Optional[str]           # name the (first) result binds to
    args: Tuple[Optional[str], ...]  # positional args: bare name or
    #                                  None placeholder, cache first
    via: str                        # "direct" | "alias:<attr>"


@dataclasses.dataclass
class Escape:
    """A use that transfers ownership of a binding out of the local
    frame: returned, stored to an attribute/subscript, or passed whole
    as a call argument."""
    name: str
    line: int
    how: str                        # "return" | "store" | "callarg"


@dataclasses.dataclass
class LedgerWrite:
    field: str
    line: int
    grows: bool                     # += (True) vs -= (False)


@dataclasses.dataclass
class PoolFnInfo:
    name: str
    qualname: str
    line: int
    events: List[OpEvent] = dataclasses.field(default_factory=list)
    escapes: List[Escape] = dataclasses.field(default_factory=list)
    raises: List[int] = dataclasses.field(default_factory=list)
    ledger_writes: List[LedgerWrite] = dataclasses.field(
        default_factory=list)
    capacity_checks: List[int] = dataclasses.field(default_factory=list)
    self_calls: Set[str] = dataclasses.field(default_factory=set)

    def ops(self) -> Set[str]:
        return {e.op for e in self.events}


@dataclasses.dataclass
class PoolClassModel:
    name: str
    module: str
    methods: Dict[str, PoolFnInfo] = dataclasses.field(
        default_factory=dict)
    #: self.attr -> canonical paged_* op (``self._free = jax.jit(
    #: paged.paged_free, ...)`` and friends)
    op_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: transitive op-effect summary per method (self-call closure)
    effects: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class PoolModuleModel:
    name: str
    file: str
    lines: List[str]
    classes: Dict[str, PoolClassModel] = dataclasses.field(
        default_factory=dict)
    functions: Dict[str, PoolFnInfo] = dataclasses.field(
        default_factory=dict)

    @property
    def short(self) -> str:
        return self.name.rpartition(".")[2]

    def all_fns(self):
        for cm in self.classes.values():
            for info in cm.methods.values():
                yield cm, info
        for info in self.functions.values():
            yield None, info


def _collect_op_aliases(cm: PoolClassModel, cnode: ast.ClassDef) -> None:
    """``self.X = jax.jit(paged.paged_Y, ...)`` (or a bare
    ``paged.paged_Y``) anywhere in the class body aliases attribute X
    to pool op Y — the serving engine's jitted-wrapper convention."""
    def paged_leaf(value) -> Optional[str]:
        d = _dotted(value)
        if d is not None:
            leaf = d.rpartition(".")[2]
            return leaf if leaf.startswith("paged_") else None
        if isinstance(value, ast.Call):
            d = _dotted(value.func)
            if d is not None and d.rpartition(".")[2] == "jit" \
                    and value.args:
                return paged_leaf(value.args[0])
        return None

    for stmt in ast.walk(cnode):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            op = paged_leaf(stmt.value)
            if op is not None:
                cm.op_aliases[tgt.attr] = op


class _PoolFnWalker:
    """One pass over a function body collecting the ownership events.
    Nested defs are NOT descended here — each gets its own walker (a
    traced step program is its own ownership scope)."""

    def __init__(self, model: PoolModuleModel,
                 cls: Optional[PoolClassModel], fn, qualname: str):
        self.model = model
        self.cls = cls
        self.info = PoolFnInfo(name=fn.name, qualname=qualname,
                               line=fn.lineno)
        for stmt in fn.body:
            self._walk_stmt(stmt)

    # --------------------------------------------------- classification

    def _op_of_call(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(canonical op, via) for a pool-op call, else None."""
        d = _dotted(call.func)
        if d is None:
            return None
        leaf = d.rpartition(".")[2]
        if leaf.startswith("paged_"):
            return leaf, "direct"
        parts = d.split(".")
        if (self.cls is not None and len(parts) == 2
                and parts[0] == "self"
                and parts[1] in self.cls.op_aliases):
            return self.cls.op_aliases[parts[1]], f"alias:{parts[1]}"
        return None

    @staticmethod
    def _bare_args(call: ast.Call) -> Tuple[Optional[str], ...]:
        # position-preserving: args[0] is always the cache argument,
        # whether spelled ``cache`` (None-free) or ``self.cache``
        # (placeholder) — the stale-id rule keys on positions past it
        return tuple(a.id if isinstance(a, ast.Name) else None
                     for a in call.args)

    def _record_op(self, call: ast.Call,
                   result: Optional[str]) -> bool:
        got = self._op_of_call(call)
        if got is None:
            return False
        op, via = got
        self.info.events.append(OpEvent(
            op=op, line=call.lineno, result=result,
            args=self._bare_args(call), via=via))
        return True

    # -------------------------------------------------------- statements

    def _walk_stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # own scope, walked separately
        if isinstance(stmt, ast.Assign):
            self._handle_assign(stmt.targets, stmt.value, stmt.lineno)
        elif isinstance(stmt, ast.AugAssign):
            self._handle_aug(stmt)
            self._scan_expr(stmt.value)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._handle_assign([stmt.target], stmt.value,
                                    stmt.lineno)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                for name in self._names_returned(stmt.value):
                    self.info.escapes.append(Escape(
                        name=name, line=stmt.lineno, how="return"))
                self._scan_expr(stmt.value)
        elif isinstance(stmt, ast.Raise):
            self.info.raises.append(stmt.lineno)
            if stmt.exc is not None:
                self._scan_expr(stmt.exc)
        elif isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, as_statement=True)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._note_capacity(stmt.test)
            self._scan_expr(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._walk_stmt(s)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter)
            for s in stmt.body + stmt.orelse:
                self._walk_stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            for s in stmt.body:
                self._walk_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in (stmt.body + stmt.orelse + stmt.finalbody):
                self._walk_stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._walk_stmt(s)
        elif isinstance(stmt, (ast.Assert,)):
            self._note_capacity(stmt.test)
            self._scan_expr(stmt.test)
        elif isinstance(stmt, ast.Delete):
            pass
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_expr(child)

    def _handle_assign(self, targets, value, line: int) -> None:
        # op call on the RHS: bind the (first) result name
        result = None
        if len(targets) == 1:
            tgt = targets[0]
            if isinstance(tgt, ast.Name):
                result = tgt.id
            elif (isinstance(tgt, ast.Tuple) and tgt.elts
                  and isinstance(tgt.elts[0], ast.Name)):
                # ``cache, ok = paged_reserve(...)`` — ownership rides
                # element 0 of every pool-op result tuple
                result = tgt.elts[0].id
        if isinstance(value, ast.Call) and self._record_op(value,
                                                           result):
            for a in value.args:
                self._scan_expr(a)
        else:
            self._scan_expr(value)
        # attribute / subscript stores transfer ownership out of the
        # local frame (``self.cache = cache``)
        for tgt in targets:
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                if isinstance(value, ast.Name):
                    self.info.escapes.append(Escape(
                        name=value.id, line=line, how="store"))
                elif isinstance(value, ast.Call):
                    # ``self.cache = self._rc_add(cache, ...)`` — the
                    # call-arg escape below already covers ``cache``;
                    # nothing extra to record for the store itself
                    pass

    def _handle_aug(self, stmt: ast.AugAssign) -> None:
        tgt = stmt.target
        if isinstance(tgt, ast.Attribute) and tgt.attr in _LEDGER_FIELDS:
            self.info.ledger_writes.append(LedgerWrite(
                field=tgt.attr, line=stmt.lineno,
                grows=isinstance(stmt.op, ast.Add)))

    @staticmethod
    def _names_returned(value) -> List[str]:
        if isinstance(value, ast.Name):
            return [value.id]
        if isinstance(value, ast.Tuple):
            return [e.id for e in value.elts
                    if isinstance(e, ast.Name)]
        return []

    def _note_capacity(self, test) -> None:
        for sub in ast.walk(test):
            if not isinstance(sub, ast.Compare):
                continue
            leaves = set()
            for n in ast.walk(sub):
                if isinstance(n, ast.Attribute):
                    leaves.add(n.attr)
                elif isinstance(n, ast.Name):
                    leaves.add(n.id)
            if leaves & _CAPACITY_NAMES and leaves & _LEDGER_FIELDS:
                self.info.capacity_checks.append(sub.lineno)

    # ------------------------------------------------------- expressions

    def _scan_expr(self, node, as_statement: bool = False) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            self._record_op(sub, None)
            # every bare-name argument passed WHOLE to any call is an
            # ownership transfer (merge_views(cache, ...), device_put,
            # self._rc_add(cache, delta), enforce helpers, ...)
            for a in sub.args:
                if isinstance(a, ast.Name):
                    self.info.escapes.append(Escape(
                        name=a.id, line=sub.lineno, how="callarg"))
            for kw in sub.keywords:
                if isinstance(kw.value, ast.Name):
                    self.info.escapes.append(Escape(
                        name=kw.value.id, line=sub.lineno,
                        how="callarg"))
            # intra-class edges for the effect closure
            d = _dotted(sub.func)
            if d is not None:
                parts = d.split(".")
                if len(parts) == 2 and parts[0] == "self":
                    self.info.self_calls.add(parts[1])
            # ``enforce(cond, ...)`` carries the capacity check as an
            # argument expression, not a statement test
            if sub.args:
                self._note_capacity(sub.args[0])


def _compute_effects(cm: PoolClassModel) -> None:
    """Transitive op-effect sets through self-call edges — the pool
    twin of the host family's thread-root flood: a release/enforce
    living in a helper still counts for its callers."""
    for name in cm.methods:
        seen: Set[str] = set()
        ops: Set[str] = set()
        stack = [name]
        while stack:
            m = stack.pop()
            if m in seen or m not in cm.methods:
                continue
            seen.add(m)
            info = cm.methods[m]
            ops |= info.ops()
            stack.extend(info.self_calls)
        cm.effects[name] = ops


def _reaches_ledger_relief(cm: Optional[PoolClassModel],
                           info: PoolFnInfo, field: str,
                           line: int) -> bool:
    """True when the growing ledger write at ``line`` is covered by a
    capacity check or a balancing transfer in the function itself or
    any self-callee (transitively)."""
    seen: Set[str] = set()
    stack = [info]
    while stack:
        fn = stack.pop()
        if fn.qualname in seen:
            continue
        seen.add(fn.qualname)
        if fn.capacity_checks:
            return True
        for w in fn.ledger_writes:
            if w.field != field or w.line != line:
                # any OTHER ledger write is a transfer: weight moved
                # between _reserved / _pinned / blocks_reserved, the
                # sum the capacity check already admitted
                return True
        if cm is not None:
            for callee in fn.self_calls:
                if callee in cm.methods:
                    stack.append(cm.methods[callee])
    return False


def analyze_pool_module(path: Optional[str] = None,
                        source: Optional[str] = None,
                        name: Optional[str] = None) -> PoolModuleModel:
    """Parse one module into its pool-ownership model.  ``path`` reads
    a file; ``source`` lints a string (tests, self-check mutants)."""
    if source is None:
        assert path is not None, "need path or source"
        with open(path) as f:
            source = f.read()
    file = path or f"<{name or 'pool-lint'}>"
    mod_name = name or (os.path.splitext(os.path.basename(file))[0]
                        if path else "mutant")
    tree = ast.parse(source, filename=file)
    model = PoolModuleModel(name=mod_name, file=file,
                            lines=source.splitlines())

    def collect_fns(body, cls: Optional[PoolClassModel], prefix: str,
                    key_prefix: str,
                    sink: Dict[str, PoolFnInfo]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                qual = f"{prefix}.{node.name}"
                # sink keys are CLASS-RELATIVE (``admit``, nested defs
                # ``admit.step``) so the self-call edges — which carry
                # bare method names — resolve against them
                key = f"{key_prefix}{node.name}"
                w = _PoolFnWalker(model, cls, node, qual)
                sink[key] = w.info
                # nested defs (traced step programs) are their own
                # ownership scopes, keyed by dotted name
                collect_fns(node.body, cls, qual, f"{key}.", sink)

    for cnode in tree.body:
        if isinstance(cnode, ast.ClassDef):
            cm = PoolClassModel(name=cnode.name, module=model.short)
            model.classes[cnode.name] = cm
            _collect_op_aliases(cm, cnode)
            collect_fns(cnode.body, cm,
                        f"{model.short}.{cnode.name}", "", cm.methods)
            _compute_effects(cm)
    collect_fns([n for n in tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))],
                None, model.short, "", model.functions)
    return model


# -------------------------------------------------------------- registry


class PoolRule:
    """Base pool-ownership rule.  ``check_module`` runs per module;
    ``check_program`` once over the whole analyzed set."""

    rule_id = "abstract-pool-rule"
    severity = "warn"
    family = "pool"
    doc = ""

    def check_module(self, model: PoolModuleModel,
                     ctx: LintContext) -> None:
        pass

    def check_program(self, models: Sequence[PoolModuleModel],
                      ctx: LintContext) -> None:
        pass


POOL_RULES: Dict[str, type] = {}


def register_pool_rule(cls):
    POOL_RULES[cls.rule_id] = cls
    return cls


def active_pool_rules() -> List[PoolRule]:
    return [cls() for cls in POOL_RULES.values()]


# ----------------------------------------------------------------- rules


@register_pool_rule
class UnbalancedAcquire(PoolRule):
    rule_id = "unbalanced-acquire"
    severity = "error"
    doc = ("reserved/imported blocks whose result binding is never "
           "released, returned, stored, or handed on — a refcount "
           "leak")

    def check_module(self, model: PoolModuleModel,
                     ctx: LintContext) -> None:
        for _, info in model.all_fns():
            for ev in info.events:
                if ev.op not in _ACQUIRE_OPS or ev.result is None:
                    continue
                later_escapes = [e for e in info.escapes
                                 if e.name == ev.result
                                 and e.line >= ev.line]
                if not later_escapes:
                    ctx.report(
                        self, info.qualname,
                        f"{ev.op} result {ev.result!r} is dropped: "
                        f"the claimed blocks' refcounts were "
                        f"committed but no release, store, return, "
                        f"or transfer ever sees them again",
                        suggestion="release with paged_free/"
                                   "paged_rollback, commit the new "
                                   "cache (self.cache = ...), or "
                                   "return it to the caller",
                        file=model.file, line=ev.line)
                    continue
                first_escape = min(e.line for e in later_escapes)
                bad_raise = [ln for ln in info.raises
                             if ev.line < ln < first_escape]
                if bad_raise:
                    ctx.report(
                        self, info.qualname,
                        f"explicit raise between the {ev.op} at line "
                        f"{ev.line} and the first escape of "
                        f"{ev.result!r} at line {first_escape} leaks "
                        f"the claimed blocks on the exception edge",
                        suggestion="release in a try/finally, or "
                                   "raise before acquiring",
                        file=model.file, line=bad_raise[0])


@register_pool_rule
class ShareBeforePin(PoolRule):
    rule_id = "share-before-pin"
    severity = "error"
    doc = ("on an import (restore/handoff) path, paged_share runs "
           "before the paged_rc_add pin — violates write-then-pin-"
           "then-share")

    def check_module(self, model: PoolModuleModel,
                     ctx: LintContext) -> None:
        for _, info in model.all_fns():
            imports = [e for e in info.events
                       if e.op == "paged_import_blocks"]
            if not imports:
                continue
            imp_line = min(e.line for e in imports)
            shares = [e for e in info.events if e.op in _SHARE_OPS
                      and e.line > imp_line]
            pins = [e for e in info.events if e.op in _PIN_OPS
                    and e.line > imp_line]
            if not shares or not pins:
                # share-only (handoff admission: share IS the pin) and
                # pin-only (restore: promote, no share here) paths are
                # both sanctioned shapes
                continue
            first_share = min(e.line for e in shares)
            first_pin = min(e.line for e in pins)
            if first_share < first_pin:
                ctx.report(
                    self, info.qualname,
                    f"imported blocks are shared (line {first_share}) "
                    f"before they are pinned (line {first_pin}) — a "
                    f"concurrent claim between the two can zero a "
                    f"just-restored page",
                    suggestion="pin first: paged_rc_add(+1) on the "
                               "imported ids, then paged_share "
                               "(write-then-pin-then-share)",
                    file=model.file, line=first_share)


@register_pool_rule
class CowSlackBypass(PoolRule):
    rule_id = "cow-slack-bypass"
    severity = "error"
    doc = ("admission-ledger growth (_reserved/_pinned +=) with no "
           "capacity check against the pool bound and no balancing "
           "ledger transfer in reach")

    def check_module(self, model: PoolModuleModel,
                     ctx: LintContext) -> None:
        for cm, info in model.all_fns():
            if info.name == "__init__":
                continue                # construction seeds the ledger
            for w in info.ledger_writes:
                if not w.grows or w.field == "blocks_reserved":
                    # blocks_reserved is per-request weight already
                    # admitted under the capacity check; only the
                    # class-wide _reserved/_pinned sums gate admission
                    continue
                if _reaches_ledger_relief(cm, info, w.field, w.line):
                    continue
                ctx.report(
                    self, info.qualname,
                    f"{w.field} grows with neither a capacity check "
                    f"against the pool bound nor a balancing ledger "
                    f"transfer in this function or its self-callees "
                    f"— admission can overcommit past the COW slack",
                    suggestion="enforce _reserved + _pinned (+ need "
                               "+ slack) <= nb before growing, or "
                               "transfer weight from another ledger "
                               "field",
                    file=model.file, line=w.line)


@register_pool_rule
class AppendAfterFree(PoolRule):
    rule_id = "append-after-free"
    severity = "error"
    doc = ("a name passed to paged_free/paged_rollback flows into a "
           "later paged_append/paged_share — stale slot id")

    def check_module(self, model: PoolModuleModel,
                     ctx: LintContext) -> None:
        for _, info in model.all_fns():
            freed: Dict[str, int] = {}
            for ev in sorted(info.events, key=lambda e: e.line):
                if ev.op in _RELEASE_OPS:
                    # args[0] is the cache; the rest name what was
                    # released (slot mask, rollback lengths)
                    for name in ev.args[1:]:
                        if name is not None:
                            freed.setdefault(name, ev.line)
                elif ev.op in _STALE_USE_OPS:
                    for name in ev.args[1:]:
                        if name in freed and freed[name] < ev.line:
                            ctx.report(
                                self, info.qualname,
                                f"{name!r} was passed to a release "
                                f"op at line {freed[name]} and flows "
                                f"into {ev.op} here — the blocks it "
                                f"names may already belong to "
                                f"another owner",
                                suggestion="re-derive the slot/block "
                                           "ids after the release, "
                                           "or reorder the release "
                                           "after the use",
                                file=model.file, line=ev.line)


@register_pool_rule
class ExportMutation(PoolRule):
    rule_id = "export-mutation"
    severity = "error"
    doc = ("pool mutated (reserve/share/cow/import/append/advance) "
           "after a paged_export in the same function — stale "
           "payload")

    def check_module(self, model: PoolModuleModel,
                     ctx: LintContext) -> None:
        for _, info in model.all_fns():
            exports = [e for e in info.events if e.op in _EXPORT_OPS]
            if not exports:
                continue
            first_export = min(e.line for e in exports)
            for ev in info.events:
                if ev.op in _EXPORT_MUTATORS \
                        and ev.line > first_export:
                    ctx.report(
                        self, info.qualname,
                        f"{ev.op} mutates the pool after the export "
                        f"at line {first_export} — the payload's "
                        f"block ids and length describe a pool state "
                        f"that no longer exists when it is sent",
                        suggestion="send (or fully pack) the payload "
                                   "before mutating, or export after "
                                   "the mutation; releasing the "
                                   "exported slot (paged_free) is "
                                   "the sanctioned epilogue and does "
                                   "not trip this rule",
                        file=model.file, line=ev.line)


# ------------------------------------------------------------ entrypoints


def resolve_pool_modules(
        filters: Optional[Sequence[str]] = None
) -> List[Tuple[str, str]]:
    """(dotted-name, file-path) for the registered pool-client
    modules, optionally restricted by substring filters (CLI
    positionals).  Same hard exit-2 contract as ``--host``."""
    import importlib.util
    out = []
    for dotted in POOL_CLIENT_MODULES:
        if filters and not any(f in dotted or dotted.endswith(f)
                               for f in filters):
            continue
        spec = importlib.util.find_spec(dotted)
        if spec is None or spec.origin is None:
            raise RuntimeError(
                f"pool-lint: registered module {dotted} not found")
        out.append((dotted, spec.origin))
    if filters and not out:
        # HARD usage error: a typo'd CI filter must not silently
        # guard nothing
        print(f"pool-lint: no registered pool-client module matches "
              f"{list(filters)}; registered: "
              + ", ".join(POOL_CLIENT_MODULES), file=sys.stderr)
        raise SystemExit(2)
    return out


def _run_rules(models: List[PoolModuleModel],
               disable: Sequence[str],
               keep_suppressed: bool = False) -> List[Finding]:
    ctx = LintContext(disable=disable, keep_suppressed=keep_suppressed)
    for rule in active_pool_rules():
        for model in models:
            rule.check_module(model, ctx)
        rule.check_program(models, ctx)
    ctx.findings.sort(key=lambda f: (f.suppressed,
                                     -severity_rank(f.severity),
                                     f.file or "", f.line or 0,
                                     f.rule_id))
    return ctx.findings


def pool_check(modules: Optional[Sequence[Tuple[str, str]]] = None,
               disable: Sequence[str] = (),
               keep_suppressed: bool = False) -> List[Finding]:
    """Lint the registered pool-client modules (or an explicit
    (name, path) list)."""
    if modules is None:
        modules = resolve_pool_modules()
    models = [analyze_pool_module(path=path, name=name)
              for name, path in modules]
    return _run_rules(models, disable, keep_suppressed)


def pool_check_sources(sources: Sequence[Tuple[str, str]],
                       disable: Sequence[str] = (),
                       files: Optional[Sequence[str]] = None
                       ) -> List[Finding]:
    """Lint (name, source) pairs — the same full path ``pool_check``
    takes, for tests and the self-check mutants."""
    models = []
    for i, (name, src) in enumerate(sources):
        path = files[i] if files else None
        models.append(analyze_pool_module(path=path, source=src,
                                          name=name))
    return _run_rules(models, ())


# ------------------------------------------------------------- self-check

_LEAK_MUTANT = """
from paddle_tpu.ops import paged_attention as paged

def admit(cache, want):
    grown, ok = paged.paged_reserve(cache, want)
    if not bool(ok):
        return cache
    return cache._replace(refcounts=grown.refcounts)
"""

_LEAK_CLEAN = """
from paddle_tpu.ops import paged_attention as paged

def admit(cache, want):
    grown, ok = paged.paged_reserve(cache, want)
    if not bool(ok):
        return cache
    return grown
"""

_ORDERING_MUTANT = """
from paddle_tpu.ops import paged_attention as paged

def restore(cache, payload, slot, bid, nmap, new_len, delta):
    cache, ids = paged.paged_import_blocks(cache, payload)
    cache = paged.paged_share(cache, slot, bid, nmap, new_len)
    cache = paged.paged_rc_add(cache, delta)
    return cache
"""

_ORDERING_CLEAN = """
from paddle_tpu.ops import paged_attention as paged

def restore(cache, payload, slot, bid, nmap, new_len, delta):
    cache, ids = paged.paged_import_blocks(cache, payload)
    cache = paged.paged_rc_add(cache, delta)
    cache = paged.paged_share(cache, slot, bid, nmap, new_len)
    return cache
"""


def pool_self_check() -> str:
    """Wiring smoke for the pool family, run by ``--self-check``: a
    refcount-leak mutant and a share-before-pin ordering mutant must
    each fire EXACTLY once through the full ``pool_check`` path, and
    their clean twins must stay quiet — so a refactor that silently
    stops building the ownership model (or unregisters a rule) fails
    CI loudly instead of linting nothing."""
    required = {"unbalanced-acquire", "share-before-pin",
                "cow-slack-bypass", "append-after-free",
                "export-mutation"}
    missing = required - set(POOL_RULES)
    if missing:
        raise RuntimeError(
            f"pool-rule registry lost {sorted(missing)}")
    cases = [
        ("unbalanced-acquire", _LEAK_MUTANT, _LEAK_CLEAN),
        ("share-before-pin", _ORDERING_MUTANT, _ORDERING_CLEAN),
    ]
    for rule_id, mutant, clean in cases:
        got = pool_check_sources([("mutant", mutant)])
        hits = [f for f in got if f.rule_id == rule_id]
        if len(hits) != 1 or len(got) != 1:
            raise RuntimeError(
                f"pool self-check: {rule_id} mutant produced "
                f"{[f.rule_id for f in got]}, expected exactly one "
                f"{rule_id} finding")
        quiet = pool_check_sources([("clean", clean)])
        if quiet:
            raise RuntimeError(
                f"pool self-check: {rule_id} clean twin produced "
                f"{[f.rule_id for f in quiet]}, expected none")
    return ("pool-rule self-check OK: refcount-leak and "
            "share-before-pin mutants each fired exactly once, "
            "clean twins quiet")
