"""shard-check: SPMD sharding analysis of meshed entrypoints.

PR 2's tpu-lint sees the single-device jaxpr; every open multi-chip
item (paged serving over a mesh, the sp×ep MoE NaN) fails in the
*partitioned* program — the one GSPMD writes after ``in_shardings``
are applied.  This module lowers a registered entrypoint under its
declared :class:`ShardRecipe` — a real ``jax.sharding.Mesh`` over CPU
devices, shapes straight from the entrypoint registry — and runs a
second rule family over two artifacts:

* the **pjit-annotated jaxpr** (spec propagation from ``in_shardings``
  through pjit boundaries: mesh-axis validation, conflicting specs
  feeding one dot, ``with_sharding_constraint`` churn);
* the **compiled SPMD module** (the optimized HLO text, where GSPMD's
  inserted collectives are visible by name, with source metadata):
  collective placement relative to while/scan decode bodies.

The rules (catalog in docs/design/analysis.md):

==========================  =====  ==================================
rule                        sev    fires when
==========================  =====  ==================================
collective-in-decode        error  all-gather/all-reduce/all-to-all/
                                   reduce-scatter/collective-permute
                                   inside a while body/cond — per-step
                                   latency on the serving hot path
mesh-axis-mismatch          error  in_shardings name axes the mesh
                                   does not have, or the two operands
                                   of one dot contract over dims
                                   sharded on DIFFERENT mesh axes
replicated-large-param      warn   an input leaf >= threshold bytes
                                   left fully replicated on a >1-
                                   device mesh
reshard-churn               warn   the same value hit by chained or
                                   repeated sharding constraints
                                   between uses
jit-cache-key               warn   a declared spec differs only
                                   cosmetically (trailing None dims)
                                   from its canonical form — jit keys
                                   programs on the spec VERBATIM, so
                                   the first round-trip through a
                                   compiled output recompiles
==========================  =====  ==================================

Nothing executes: the mesh is CPU devices (``ci.sh`` forces
``--xla_force_host_platform_device_count``), programs are traced,
lowered and compiled but never run — GSPMD partitioning is backend-
independent, so the collective schedule the check sees is the one a
TPU slice would run.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax._src import core as jcore
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.analysis.core import (Finding, LintContext, LintTarget,
                                      severity_rank)
from paddle_tpu.parallel.sharding import spec_axes

__all__ = ["ShardRecipe", "ShardRule", "SHARD_RULES",
           "register_shard_rule", "active_shard_rules", "shard_check",
           "build_mesh", "resolve_in_shardings", "COLLECTIVE_OPS"]


# ------------------------------------------------------------------ recipe


@dataclasses.dataclass(frozen=True)
class ShardRecipe:
    """The mesh + per-argument sharding contract of one entrypoint.

    ``axes``: ordered ``(name, size)`` pairs — the mesh shape.
    ``arg_specs``: one entry per positional argument —

    * ``None``: fully replicated (the default for missing entries);
    * a ``PartitionSpec``: applied to every array leaf of the arg;
    * a callable ``(arg, mesh) -> sharding pytree`` for per-leaf
      layouts (e.g. :func:`paddle_tpu.parallel.sharding.
      shardings_like` with a rule table).

    ``decode_collectives``: the collective kinds the decode body is
    CONTRACTED to carry (``()`` = none allowed, the default).  With
    kinds declared, collective-in-decode flips from "no collectives"
    to an exact-set assertion BOTH ways: a kind outside the list is
    the usual hot-path error, and a declared kind MISSING from the
    compiled program is also an error — the intended combine got
    elided, so the sharding is not doing what the recipe claims
    (e.g. the head-sharded paged step's attention-output all-gather).
    ``-start`` async forms count as their base kind.
    """
    axes: Tuple[Tuple[str, int], ...]
    arg_specs: Tuple[Any, ...] = ()
    note: str = ""
    decode_collectives: Tuple[str, ...] = ()

    @property
    def num_devices(self) -> int:
        n = 1
        for _, size in self.axes:
            n *= size
        return n

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.axes)


def build_mesh(recipe: ShardRecipe) -> Optional[Mesh]:
    """Real CPU-device mesh for the recipe, or None if the process has
    fewer devices than the recipe needs (the caller reports, not
    raises: lint must degrade loudly, never crash the gate)."""
    devs = jax.devices()
    if len(devs) < recipe.num_devices:
        return None
    shape = tuple(size for _, size in recipe.axes)
    arr = np.asarray(devs[:recipe.num_devices]).reshape(shape)
    return Mesh(arr, recipe.axis_names)


def resolve_in_shardings(recipe: ShardRecipe, mesh: Mesh, args: Tuple):
    """Per-argument sharding pytrees (full trees, one NamedSharding per
    array leaf) from the recipe's ``arg_specs``."""
    out = []
    for i, arg in enumerate(args):
        spec = (recipe.arg_specs[i]
                if i < len(recipe.arg_specs) else None)
        if callable(spec) and not isinstance(spec, P):
            out.append(spec(arg, mesh))
            continue
        s = NamedSharding(mesh, spec if isinstance(spec, P) else P())
        out.append(jax.tree_util.tree_map(lambda _leaf, _s=s: _s, arg))
    return tuple(out)


def _leaf_shardings(in_shardings) -> List[Any]:
    flat = []
    for tree in in_shardings:
        flat.extend(jax.tree_util.tree_leaves(
            tree, is_leaf=lambda x: isinstance(x, NamedSharding)))
    return flat


# -------------------------------------------------------- the SPMD program


@dataclasses.dataclass
class ShardAnalysis:
    """Everything a shard rule may read: the recipe, the realized mesh,
    the resolved shardings, the traced jaxpr of the meshed program and
    the compiled SPMD module text."""
    target: LintTarget
    recipe: ShardRecipe
    mesh: Mesh
    in_shardings: Tuple
    closed: Any                       # ClosedJaxpr of jit(fn, in_shardings)
    hlo: Optional[str]                # compiled optimized HLO text
    leaf_specs: List[Tuple[str, Any, Any]]   # (label, aval, NamedSharding)


def _arg_leaf_specs(args, in_shardings) -> List[Tuple[str, Any, Any]]:
    """Flatten (args, shardings) to labelled leaves: the label is the
    positional index plus the pytree key path, readable in findings."""
    out = []
    for i, (arg, shd) in enumerate(zip(args, in_shardings)):
        leaves, _ = jax.tree_util.tree_flatten_with_path(arg)
        sleaves = jax.tree_util.tree_leaves(
            shd, is_leaf=lambda x: isinstance(x, NamedSharding))
        for (path, leaf), s in zip(leaves, sleaves):
            label = f"arg{i}" + jax.tree_util.keystr(path)
            out.append((label, leaf, s))
    return out


# -------------------------------------------------------------- HLO parsing


COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "all-reduce-start", "all-gather-start",
    "collective-permute-start", "ragged-all-to-all",
})

_COMP_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w\.\-]+)\s*\(.*\{\s*$")
_CALLED_RE = re.compile(
    r"(?:condition|body|to_apply|calls|branch_computations|"
    r"called_computations)=(\{[^}]*\}|%?[\w\.\-]+)")
_COMP_NAME_RE = re.compile(r"%?([\w\.\-]+)")
_META_RE = re.compile(
    r'metadata=\{[^}]*?op_name="([^"]*)"'
    r'(?:[^}]*?source_file="([^"]*)")?(?:[^}]*?source_line=(\d+))?')


def _hlo_opcode(line: str) -> Optional[str]:
    """Opcode of one HLO instruction line (``%x = TYPE opcode(...)``);
    TYPE may itself be a parenthesized tuple."""
    if " = " not in line:
        return None
    rhs = line.split(" = ", 1)[1].lstrip()
    if rhs.startswith("("):                      # tuple-typed result
        depth = 0
        for i, c in enumerate(rhs):
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    rhs = rhs[i + 1:].lstrip()
                    break
    else:
        parts = rhs.split(None, 1)
        rhs = parts[1] if len(parts) > 1 else ""
    m = re.match(r"([\w\-]+)\(", rhs)
    return m.group(1) if m else None


def parse_hlo_computations(hlo: str) -> Dict[str, List[str]]:
    """HLO text -> {computation name: [instruction lines]}."""
    comps: Dict[str, List[str]] = {}
    current = None
    for line in hlo.splitlines():
        if not line.startswith((" ", "\t")):
            m = _COMP_HEADER_RE.match(line.strip())
            if m:
                current = m.group(1)
                comps[current] = []
            elif line.strip() == "}":
                current = None
            continue
        if current is not None and line.strip() and line.strip() != "}":
            comps[current].append(line.rstrip())
    return comps


def _called_computations(line: str) -> List[str]:
    out = []
    for m in _CALLED_RE.finditer(line):
        blob = m.group(1)
        if blob.startswith("{"):
            out.extend(n for n in _COMP_NAME_RE.findall(blob))
        else:
            out.append(blob.lstrip("%"))
    return out


def _transitive(comps: Dict[str, List[str]], roots: Sequence[str]):
    seen, stack = set(), [r for r in roots if r in comps]
    while stack:
        name = stack.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        for line in comps[name]:
            stack.extend(_called_computations(line))
    return seen


# -------------------------------------------------------------------- rules


class ShardRule:
    rule_id: str = ""
    severity: str = "warn"
    family: str = "shard"
    doc: str = ""

    def run(self, sa: ShardAnalysis, ctx: LintContext) -> None:
        raise NotImplementedError


SHARD_RULES: Dict[str, type] = {}


def register_shard_rule(cls):
    assert cls.rule_id and cls.rule_id not in SHARD_RULES, cls
    SHARD_RULES[cls.rule_id] = cls
    return cls


def active_shard_rules() -> List[ShardRule]:
    return [cls() for cls in SHARD_RULES.values()]


@register_shard_rule
class CollectiveInDecodeRule(ShardRule):
    """GSPMD placing a collective INSIDE a while/scan body is a
    per-decode-step latency tax — EQuARX (PAPERS.md) measures the
    collective share of distributed inference; a loop-invariant one
    (e.g. a weight all-gather) belongs hoisted, and XLA does hoist it
    when the spec allows.  One left inside means the sharding makes it
    genuinely iteration-dependent: an error before a slice is booked.
    """

    rule_id = "collective-in-decode"
    severity = "error"
    doc = ("GSPMD collective (all-gather/all-reduce/all-to-all/...) "
           "inside a while/scan decode body; with "
           "recipe.decode_collectives declared, an exact-set check — "
           "extra kinds AND missing declared kinds both fail")

    @staticmethod
    def _base_kind(op: str) -> str:
        return op[:-len("-start")] if op.endswith("-start") else op

    @staticmethod
    def _line_meta(line: str):
        meta = _META_RE.search(line)
        op_name = meta.group(1) if meta else ""
        file = meta.group(2) if meta and meta.group(2) else None
        lineno = int(meta.group(3)) if meta and meta.group(3) else None
        return op_name, file, lineno

    def run(self, sa, ctx):
        if not sa.hlo:
            return
        comps = parse_hlo_computations(sa.hlo)
        loop_comps = set()
        for name, lines in comps.items():
            for line in lines:
                if _hlo_opcode(line) == "while":
                    loop_comps |= _transitive(
                        comps, _called_computations(line))
        allowed = {self._base_kind(k)
                   for k in sa.recipe.decode_collectives}
        if allowed:
            # declared-combine mode: the compiled step program is
            # CONTRACTED to carry exactly these kinds.  Scan the WHOLE
            # module, not just while bodies: the engine's fixed-shape
            # step fn has no decode while (the host loop drives it),
            # and incidental whiles (sort/RNG utilities) must not
            # shrink the region the exact-set check covers.
            scan = set(comps)
            found: Dict[str, Tuple[str, str]] = {}
            for name in sorted(scan):
                for line in comps.get(name, ()):
                    op = _hlo_opcode(line)
                    if op in COLLECTIVE_OPS:
                        found.setdefault(self._base_kind(op),
                                         (name, line))
            for base in sorted(set(found) - allowed):
                name, line = found[base]
                op_name, file, lineno = self._line_meta(line)
                ctx.report(
                    self, f"{sa.target.name}/spmd/{name}",
                    f"{base} in the decode step "
                    f"({op_name or 'no op_name'}) is outside the "
                    f"recipe's declared set {sorted(allowed)} — an "
                    "undeclared per-step collective on the serving "
                    "hot path",
                    file=file, line=lineno,
                    suggestion="reshard so only the declared combine "
                    "crosses the mesh, or (if this collective is "
                    "genuinely the contract) add it to the recipe's "
                    "decode_collectives")
            for base in sorted(allowed - set(found)):
                ctx.report(
                    self, f"{sa.target.name}/spmd",
                    f"declared decode collective {base!r} is MISSING "
                    "from the compiled program — the intended combine "
                    "was elided, so the sharded layout is not being "
                    "exercised (a replicated input or an unconsumed "
                    "output usually hides it)",
                    suggestion="check the recipe's arg_specs actually "
                    "shard the pool and that the combined value is "
                    "consumed downstream")
            return
        for name in sorted(loop_comps):
            for line in comps.get(name, ()):
                op = _hlo_opcode(line)
                if op not in COLLECTIVE_OPS:
                    continue
                op_name, file, lineno = self._line_meta(line)
                ctx.report(
                    self, f"{sa.target.name}/spmd/{name}",
                    f"{op} inside the decode loop "
                    f"({op_name or 'no op_name'}) — it runs every "
                    "iteration on the serving hot path",
                    file=file, line=lineno,
                    suggestion="reshard so the contraction no longer "
                    "crosses the mesh inside the loop (e.g. shard the "
                    "batch, replicate the per-step operand), or hoist "
                    "the resharded value out of the carry")


@register_shard_rule
class MeshAxisMismatchRule(ShardRule):
    """Two static spec checks, both fatal before any lowering: (a) an
    ``in_shardings`` entry naming a mesh axis the recipe's mesh does
    not define — GSPMD would reject it at jit time with a stack trace
    instead of a finding; (b) the two operands of one ``dot_general``
    contracting over dims sharded on DIFFERENT mesh axes — GSPMD
    resolves that with a full reshard of one side, which is never what
    the spec author meant."""

    rule_id = "mesh-axis-mismatch"
    severity = "error"
    doc = ("in_shardings naming axes absent from the mesh, or one dot "
           "contracting dims sharded on different axes")

    def run(self, sa, ctx):
        # (a) is checked in shard_check BEFORE NamedShardings are
        # built (building one with an unknown axis raises).  Here: (b).
        if sa.closed is None:
            return
        specs: Dict[int, Any] = {}
        flat = _leaf_shardings(sa.in_shardings)
        invars = sa.closed.jaxpr.invars
        for var, s in zip(invars, flat):
            if isinstance(s, NamedSharding):
                specs[id(var)] = s.spec
        self._walk(sa.closed.jaxpr, specs, sa, ctx, sa.target.name)

    def _walk(self, jaxpr, specs, sa, ctx, path):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "dot_general":
                self._check_dot(eqn, specs, sa, ctx, path)
            inner = None
            if prim == "pjit":
                inner = eqn.params["jaxpr"]
            elif prim in ("custom_jvp_call", "custom_vjp_call"):
                inner = (eqn.params.get("call_jaxpr")
                         or eqn.params.get("fun_jaxpr"))
            if inner is not None:
                ij = getattr(inner, "jaxpr", inner)
                inner_specs = dict(specs)
                for outer, iv in zip(eqn.invars, ij.invars):
                    if isinstance(outer, jcore.Var) and id(outer) in specs:
                        inner_specs[id(iv)] = specs[id(outer)]
                self._walk(ij, inner_specs, sa, ctx,
                           f"{path}/pjit:{eqn.params.get('name', '?')}"
                           if prim == "pjit" else f"{path}/{prim}")

    def _check_dot(self, eqn, specs, sa, ctx, path):
        lhs, rhs = eqn.invars[:2]
        sl = specs.get(id(lhs))
        sr = specs.get(id(rhs))
        if sl is None or sr is None:
            return
        (lc, rc), _ = eqn.params["dimension_numbers"]

        def axis_at(spec, dim):
            entries = tuple(spec)
            if dim >= len(entries):
                return None
            e = entries[dim]
            return e if not isinstance(e, (tuple, list)) else tuple(e)

        for dl, dr in zip(lc, rc):
            al, ar = axis_at(sl, dl), axis_at(sr, dr)
            if al is not None and ar is not None and al != ar:
                ctx.report(
                    self, f"{path}/dot_general",
                    f"dot contracts lhs dim {dl} (sharded on "
                    f"{al!r}) against rhs dim {dr} (sharded on "
                    f"{ar!r}) — GSPMD will reshard a whole operand "
                    "to reconcile them",
                    eqn=eqn,
                    suggestion="shard both contraction dims on the "
                    "same mesh axis (partial-sum + all-reduce) or "
                    "leave one side replicated")


@register_shard_rule
class ReplicatedLargeParamRule(ShardRule):
    """'Automatic Cross-Replica Sharding of Weight Update ...'
    (PAPERS.md): replicated large tensors are the dominant HBM waste
    of data-parallel training.  Any input leaf at/over the threshold
    left fully replicated on a >1-device mesh gets flagged with the
    bytes it wastes per extra device."""

    rule_id = "replicated-large-param"
    severity = "warn"
    doc = "input leaf >= threshold bytes fully replicated on the mesh"

    def __init__(self, min_bytes: int = 1 << 20):
        self.min_bytes = min_bytes

    def run(self, sa, ctx):
        if sa.mesh.size <= 1:
            return
        from paddle_tpu.analysis.memory import aval_bytes
        for label, leaf, s in sa.leaf_specs:
            if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
                continue
            nbytes = aval_bytes(leaf)
            replicated = (not isinstance(s, NamedSharding)
                          or not spec_axes(s.spec))
            if replicated and nbytes >= self.min_bytes:
                ctx.report(
                    self, f"{sa.target.name}/{label}",
                    f"{label} ({nbytes} bytes) is fully replicated "
                    f"across the {dict(sa.recipe.axes)} mesh — "
                    f"{nbytes * (sa.mesh.size - 1)} redundant bytes",
                    suggestion="shard it (parallel.sharding rule "
                    "table) or note why replication wins (small, "
                    "read-every-step) with a tpu-lint disable")


@register_shard_rule
class ReshardChurnRule(ShardRule):
    """``with_sharding_constraint`` chains: constraining a value that
    is itself the fresh output of a constraint (or constraining the
    same value twice with no use in between) makes GSPMD materialize
    each intermediate layout — real all-to-all traffic, zero reads."""

    rule_id = "reshard-churn"
    severity = "warn"
    doc = "same value hit by chained/duplicate sharding constraints"

    _PRIM = "sharding_constraint"

    def run(self, sa, ctx):
        if sa.closed is None:
            return
        self._walk(sa.closed.jaxpr, sa, ctx, sa.target.name)

    def _walk(self, jaxpr, sa, ctx, path):
        producers: Dict[int, Any] = {}
        constrained: Dict[int, Any] = {}     # var id -> first constraint
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == self._PRIM:
                src = eqn.invars[0]
                prod = producers.get(id(src))
                if prod is not None and prod.primitive.name == self._PRIM:
                    ctx.report(
                        self, f"{path}/{self._PRIM}",
                        "sharding constraint applied to the "
                        "IMMEDIATE output of another constraint — "
                        "the intermediate layout is materialized and "
                        "never read",
                        eqn=eqn,
                        suggestion="keep only the final constraint")
                elif isinstance(src, jcore.Var) and id(src) in constrained:
                    ctx.report(
                        self, f"{path}/{self._PRIM}",
                        "the same value is resharded more than once "
                        "between uses",
                        eqn=eqn,
                        suggestion="constrain once, at the consumer "
                        "that needs the layout")
                if isinstance(src, jcore.Var):
                    constrained[id(src)] = eqn
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr",
                        "cond_jaxpr", "body_jaxpr"):
                inner = eqn.params.get(key) if eqn.params else None
                if inner is not None:
                    self._walk(getattr(inner, "jaxpr", inner), sa, ctx,
                               f"{path}/{eqn.primitive.name}")
            for key in ("branches",):
                for inner in (eqn.params.get(key) or ()):
                    self._walk(getattr(inner, "jaxpr", inner), sa, ctx,
                               f"{path}/{eqn.primitive.name}")
            for v in eqn.outvars:
                producers[id(v)] = eqn


@register_shard_rule
class JitCacheKeyRule(ShardRule):
    """Cosmetically-redundant PartitionSpecs poison the jit cache: jit
    keys compiled programs on the argument sharding VERBATIM, and
    compiled outputs come back with trailing ``None`` dims stripped
    (``P(None, None, 'mp', None)`` returns as ``P(None, None, 'mp')``).
    A declared spec carrying trailing ``None``s is therefore
    semantically identical to — but cache-key-DIFFERENT from — the
    sharding of the arrays that flow back in on the next call, which
    forces a spurious recompile on the first post-step reuse (the
    regression class ``paged_cache_shardings`` documents:
    parallel/sharding.py's 'no trailing None' comment).  Flags both
    declared ``in_shardings`` leaves and in-program
    ``with_sharding_constraint`` specs."""

    rule_id = "jit-cache-key"
    severity = "warn"
    doc = ("declared PartitionSpec differs only cosmetically (trailing "
           "None dims) from its canonical form — spurious recompile "
           "on the first compiled-output round-trip")

    @staticmethod
    def _trailing_nones(spec) -> int:
        entries = tuple(spec or ())
        n = 0
        for e in reversed(entries):
            if e is not None:
                break
            n += 1
        return n

    def _flag(self, ctx, path, spec, what, eqn=None):
        entries = tuple(spec)
        canon = entries[:len(entries) - self._trailing_nones(spec)]
        ctx.report(
            self, path,
            f"{what} P{entries!r} carries trailing None dim(s) — "
            f"canonical form is P{canon!r}; jit keys programs on the "
            "spec verbatim and compiled outputs come back canonical, "
            "so the first round-trip recompiles the whole step",
            eqn=eqn,
            suggestion="drop the trailing None dims (partial "
            "PartitionSpecs mean 'replicated on the rest' already)")

    def run(self, sa, ctx):
        seen = set()
        for label, _leaf, s in sa.leaf_specs:
            spec = getattr(s, "spec", None)
            if spec is None or not self._trailing_nones(spec):
                continue
            key = (tuple(spec),)
            if key in seen:        # one finding per distinct bad spec
                continue
            seen.add(key)
            self._flag(ctx, f"{sa.target.name}/{label}", spec,
                       f"in_shardings for {label}")
        if sa.closed is not None:
            self._walk(sa.closed.jaxpr, sa, ctx, sa.target.name)

    def _walk(self, jaxpr, sa, ctx, path):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "sharding_constraint":
                spec = getattr(eqn.params.get("sharding"), "spec", None)
                if spec is not None and self._trailing_nones(spec):
                    self._flag(ctx, f"{path}/sharding_constraint", spec,
                               "with_sharding_constraint spec",
                               eqn=eqn)
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr",
                        "cond_jaxpr", "body_jaxpr"):
                inner = eqn.params.get(key) if eqn.params else None
                if inner is not None:
                    self._walk(getattr(inner, "jaxpr", inner), sa, ctx,
                               f"{path}/{eqn.primitive.name}")
            for inner in (eqn.params.get("branches") or ()
                          if eqn.params else ()):
                self._walk(getattr(inner, "jaxpr", inner), sa, ctx,
                           f"{path}/{eqn.primitive.name}")


# -------------------------------------------------------------- shard_check


def _static_axis_findings(recipe: ShardRecipe, target_name: str,
                          ctx: LintContext) -> bool:
    """Check (a) of mesh-axis-mismatch: specs naming unknown axes.
    Runs before any NamedSharding is built.  Returns True if fatal."""
    rule = SHARD_RULES["mesh-axis-mismatch"]()
    known = set(recipe.axis_names)
    bad = False
    for i, spec in enumerate(recipe.arg_specs):
        if not isinstance(spec, P):
            continue
        unknown = spec_axes(spec) - known
        if unknown:
            bad = True
            ctx.report(
                rule, f"{target_name}/arg{i}",
                f"in_shardings for arg {i} name mesh "
                f"axis(es) {sorted(unknown)} but the recipe's mesh "
                f"has {sorted(known)}",
                suggestion="fix the PartitionSpec or add the axis to "
                "the recipe's mesh")
    return bad


def shard_check(target: LintTarget, recipe: Optional[ShardRecipe] = None,
                rules: Optional[Sequence[ShardRule]] = None,
                disable: Sequence[str] = (),
                keep_suppressed: bool = False) -> List[Finding]:
    """Lower ``target`` under its mesh recipe and run the SPMD rule
    family.  Returns findings sorted most-severe-first; a recipe-less
    target returns ``[]`` (it lints single-device via :func:`lint`).
    """
    recipe = recipe or getattr(target, "recipe", None)
    if recipe is None:
        return []
    rules = list(rules) if rules is not None else active_shard_rules()
    ctx = LintContext(disable=disable, keep_suppressed=keep_suppressed)

    mesh = build_mesh(recipe)
    if mesh is None:
        ctx.report(
            SHARD_RULES["mesh-axis-mismatch"](), target.name,
            f"recipe needs {recipe.num_devices} devices "
            f"({dict(recipe.axes)}) but only {len(jax.devices())} are "
            "visible — set XLA_FLAGS="
            "--xla_force_host_platform_device_count (ci.sh does)")
        return ctx.findings
    if _static_axis_findings(recipe, target.name, ctx):
        return ctx.findings       # NamedSharding would raise past here

    in_shardings = resolve_in_shardings(recipe, mesh, target.args)
    wrapped = jax.jit(target.fn, in_shardings=in_shardings)
    # Partitionable RNG for the meshed lowering: legacy threefry (the
    # jax<0.5 default) broadcasts its key with an all-reduce wherever
    # random bits feed a sharded shape — a config artifact any real
    # multi-chip deployment flips off (it IS the default from jax
    # 0.5), not a property of the recipe under check.
    from jax._src import config as _jconfig
    with _jconfig.threefry_partitionable(True):
        closed = jax.make_jaxpr(wrapped)(*target.args, **target.kwargs)
        hlo = None
        try:
            lowered = wrapped.lower(*target.args, **target.kwargs)
            hlo = lowered.compile().as_text()
        except Exception as e:      # compile failure IS a finding
            ctx.report(SHARD_RULES["mesh-axis-mismatch"](), target.name,
                       f"SPMD lowering failed under the recipe mesh: "
                       f"{e}")

    sa = ShardAnalysis(
        target=target, recipe=recipe, mesh=mesh,
        in_shardings=in_shardings, closed=closed, hlo=hlo,
        leaf_specs=_arg_leaf_specs(target.args, in_shardings))
    for rule in rules:
        if rule.rule_id not in ctx.disable:
            rule.run(sa, ctx)
    ctx.findings.sort(key=lambda f: (-severity_rank(f.severity),
                                     f.rule_id, f.file or "",
                                     f.line or 0))
    return ctx.findings
