"""CompileWatcher: the runtime companion to the static linter.

``tpu-lint`` proves properties of the traced program; the one serving
contract it cannot see statically is RETRACING — a decode step that
recompiles per request length (the bug class ``lm_serve_builder``'s
traced-``steps`` design exists to prevent).  ``serving.py`` counted
compiles ad hoc via each jitted function's ``_cache_size()``;
:class:`CompileWatcher` is that pattern as a reusable utility any test
or engine can hold::

    watch = CompileWatcher(decode=engine._decode)
    ... drive traffic ...
    assert watch.counts() == {"decode": 1}

or as a context manager that snapshots a baseline on entry (for
asserting a REGION adds no compiles over already-warm functions)::

    with CompileWatcher(serve=serve_fn) as w:
        serve_fn(...); serve_fn(...)
    w.assert_counts(serve=0)          # warm path must not retrace

Counts come from ``jit``'s own compile-cache size — exact, backend-
independent, zero overhead on the measured path.
"""

from __future__ import annotations

from typing import Callable, Dict

__all__ = ["CompileWatcher"]


def _cache_size(fn) -> int:
    size = getattr(fn, "_cache_size", None)
    if size is None:
        raise TypeError(
            f"CompileWatcher needs a jax.jit-wrapped callable (or any "
            f"object exposing _cache_size()), got {type(fn).__name__}")
    return int(size())


class CompileWatcher:
    """Tracks XLA compile counts of named jitted callables.

    The baseline snapshots at construction (so a watcher created next
    to ``jax.jit`` counts every compile the function ever does) and
    re-snapshots on ``__enter__`` (so a ``with`` block counts only the
    compiles the block adds).
    """

    def __init__(self, **fns: Callable):
        self._fns: Dict[str, Callable] = {}
        self._base: Dict[str, int] = {}
        for name, fn in fns.items():
            self.watch(name, fn)

    def watch(self, name: str, fn: Callable) -> "CompileWatcher":
        """Register another function; its baseline is its current
        cache size (a warm function starts at count 0)."""
        _cache_size(fn)             # fail loudly on non-jitted callables
        self._fns[name] = fn
        self._base[name] = _cache_size(fn)
        return self

    def __enter__(self) -> "CompileWatcher":
        for name, fn in self._fns.items():
            self._base[name] = _cache_size(fn)
        return self

    def __exit__(self, *exc) -> None:
        return None

    def counts(self) -> Dict[str, int]:
        """Compiles since baseline, per watched function."""
        return {name: _cache_size(fn) - self._base[name]
                for name, fn in self._fns.items()}

    def total(self) -> int:
        return sum(self.counts().values())

    def assert_counts(self, **expected: int) -> None:
        """Assert exact per-name compile counts; unlisted names are
        unchecked.  The failure message carries every count — the
        ``compiles == 1`` serving pin as one call."""
        actual = self.counts()
        bad = {k: (expected[k], actual.get(k))
               for k in expected if actual.get(k) != expected[k]}
        assert not bad, (
            f"compile counts diverged (expected != actual): {bad}; "
            f"all counts: {actual} — a retrace on the hot path means a "
            "trace key (shape/dtype/static arg) varies per call")
