"""CompileWatcher: the runtime companion to the static linter.

``tpu-lint`` proves properties of the traced program; the one serving
contract it cannot see statically is RETRACING — a decode step that
recompiles per request length (the bug class ``lm_serve_builder``'s
traced-``steps`` design exists to prevent).  ``serving.py`` counted
compiles ad hoc via each jitted function's ``_cache_size()``;
:class:`CompileWatcher` is that pattern as a reusable utility any test
or engine can hold::

    watch = CompileWatcher(decode=engine._decode)
    ... drive traffic ...
    assert watch.counts() == {"decode": 1}

or as a context manager that snapshots a baseline on entry (for
asserting a REGION adds no compiles over already-warm functions)::

    with CompileWatcher(serve=serve_fn) as w:
        serve_fn(...); serve_fn(...)
    w.assert_counts(serve=0)          # warm path must not retrace

Counts come from ``jit``'s own compile-cache size — exact, backend-
independent, zero overhead on the measured path.

A watcher can also REPORT, not just assert: :meth:`bind_metrics`
registers a ``compile_seconds{program=}`` histogram and :meth:`poll`
(called by the serving engine once per step / prefill) turns compile-
count growth into observations plus a ``recompile`` trace instant
naming the program on any compile after its first — so a broken
``compiles == {'step': 1}`` pin is attributable from the trace
timeline, not only countable after the fact.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

__all__ = ["CompileWatcher", "COMPILE_SECONDS_BUCKETS"]

#: XLA compiles run milliseconds (tiny test graphs) to minutes (full
#: models) — log-spaced wide, like DEFAULT_LATENCY_BUCKETS but shifted
#: up three decades.
COMPILE_SECONDS_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                           10.0, 30.0, 60.0, 120.0)


def _cache_size(fn) -> int:
    size = getattr(fn, "_cache_size", None)
    if size is None:
        raise TypeError(
            f"CompileWatcher needs a jax.jit-wrapped callable (or any "
            f"object exposing _cache_size()), got {type(fn).__name__}")
    return int(size())


class CompileWatcher:
    """Tracks XLA compile counts of named jitted callables.

    The baseline snapshots at construction (so a watcher created next
    to ``jax.jit`` counts every compile the function ever does) and
    re-snapshots on ``__enter__`` (so a ``with`` block counts only the
    compiles the block adds).
    """

    def __init__(self, **fns: Callable):
        self._fns: Dict[str, Callable] = {}
        self._base: Dict[str, int] = {}
        self._hist = None
        self._polled: Dict[str, int] = {}
        for name, fn in fns.items():
            self.watch(name, fn)

    def watch(self, name: str, fn: Callable) -> "CompileWatcher":
        """Register another function; its baseline is its current
        cache size (a warm function starts at count 0)."""
        _cache_size(fn)             # fail loudly on non-jitted callables
        self._fns[name] = fn
        self._base[name] = _cache_size(fn)
        return self

    def __enter__(self) -> "CompileWatcher":
        for name, fn in self._fns.items():
            self._base[name] = _cache_size(fn)
        self._polled = {}
        return self

    def __exit__(self, *exc) -> None:
        return None

    def counts(self) -> Dict[str, int]:
        """Compiles since baseline, per watched function."""
        return {name: _cache_size(fn) - self._base[name]
                for name, fn in self._fns.items()}

    # -------------------------------------------------------- reporting

    def bind_metrics(self, registry) -> "CompileWatcher":
        """Register the ``compile_seconds{program=}`` histogram on
        ``registry`` and route future :meth:`poll` observations into
        it.  Idempotent per registry (re-binding just re-resolves the
        family, same as any ``registry.histogram`` call)."""
        self._hist = registry.histogram(
            "compile_seconds",
            help="wall time of host calls that triggered an XLA "
                 "compile, by program= (upper bound: the call's full "
                 "duration, compile included)",
            buckets=COMPILE_SECONDS_BUCKETS)
        return self

    def poll(self, seconds_hint: Optional[float] = None,
             tracer=None) -> Dict[str, int]:
        """Detect compile-count growth since the last poll and report
        it; returns :meth:`counts`.  Call this right after the host
        call that may have compiled (the engine does, once per step
        and per prefill) — cost is one ``_cache_size`` read per
        watched function, same as :meth:`counts`.

        ``seconds_hint`` is the duration of the polled call; it is
        observed into ``compile_seconds`` once per program that grew —
        an UPPER BOUND on compile time (the call did other work too),
        which is exactly the operator question ("how long did the step
        that recompiled stall").  ``tracer`` gets a ``recompile``
        instant naming the program whenever its total count exceeds 1
        — the first compile per program is the contract, everything
        after is the bug the trace should show."""
        counts = self.counts()
        for name, n in counts.items():
            prev = self._polled.get(name, 0)
            if n <= prev:
                continue
            if self._hist is not None and seconds_hint is not None:
                self._hist.observe(float(seconds_hint), program=name)
            if tracer is not None and n > 1:
                tracer.instant("recompile", track="host", program=name,
                               compiles=int(n), new=int(n - prev))
        self._polled = counts
        return counts

    def total(self) -> int:
        return sum(self.counts().values())

    def assert_counts(self, **expected: int) -> None:
        """Assert exact per-name compile counts; unlisted names are
        unchecked.  The failure message carries every count — the
        ``compiles == 1`` serving pin as one call."""
        actual = self.counts()
        bad = {k: (expected[k], actual.get(k))
               for k in expected if actual.get(k) != expected[k]}
        assert not bad, (
            f"compile counts diverged (expected != actual): {bad}; "
            f"all counts: {actual} — a retrace on the hot path means a "
            "trace key (shape/dtype/static arg) varies per call")
