"""tpu-lint: jaxpr-level static analysis of this repo's jitted programs.

The invariants PR 1 enforced by hand — f32 matmul accumulation,
device-resident decode loops, ``compiles == 1``, donated step buffers
— generalized into a rule registry that walks any traced entrypoint::

    from paddle_tpu.analysis import lint
    findings = lint(my_jitted_step, (args,))      # traces, never runs

    python -m paddle_tpu.analysis --self-check    # the CI gate

Pieces:

* :func:`lint` / :class:`LintTarget` — trace + walk (``core.py``);
* the rule registry (``rules.py``): accum-dtype, weak-type-promotion,
  host-callback-in-loop, gather-in-decode, dead-code, donation-audit;
* :class:`CompileWatcher` — the runtime companion: compile-count
  assertions for the retrace contract statics cannot see
  (``watch.py``);
* the entrypoint registry (``entrypoints.py``) — what ``--self-check``
  covers; register yours with :func:`register_entrypoint`;
* the SPMD rule family (``shard_rules.py``): entrypoints carrying a
  :class:`ShardRecipe` are lowered under a real multi-device CPU mesh
  and their compiled HLO checked for collective-in-decode,
  mesh-axis-mismatch, replicated-large-param, reshard-churn;
* the static HBM estimator (``memory.py``): per-shard peak live bytes
  from a liveness scan, gated against ``analysis/budgets.json`` by
  ``--memory --budgets``;
* :func:`nan_check` (``nans.py``): checkify-backed value-level NaN
  localization behind ``lint --nans``.

Suppress a finding at source with ``# tpu-lint: disable=<rule-id>``.
Catalog and severity policy: ``docs/design/analysis.md``.
"""

from paddle_tpu.analysis.core import (Finding, LintTarget, lint,
                                      lint_target, SEVERITIES,
                                      severity_rank)
from paddle_tpu.analysis.rules import RULES, Rule, active_rules, \
    register_rule
from paddle_tpu.analysis.watch import CompileWatcher
from paddle_tpu.analysis.entrypoints import (ENTRYPOINTS,
                                             register_entrypoint,
                                             self_check_targets)
from paddle_tpu.analysis.shard_rules import (SHARD_RULES, ShardRecipe,
                                             ShardRule,
                                             active_shard_rules,
                                             register_shard_rule,
                                             shard_check)
from paddle_tpu.analysis.memory import (MemoryReport, check_budgets,
                                        estimate_target, load_budgets)
from paddle_tpu.analysis.kernel_rules import (KERNEL_RULES,
                                              KernelAnalysis,
                                              KernelRule,
                                              active_kernel_rules,
                                              analyze_pallas_call,
                                              derive_kernel_vmem,
                                              kernel_self_check,
                                              max_kernel_vmem,
                                              register_kernel_rule)
from paddle_tpu.analysis.nans import nan_check
from paddle_tpu.analysis.host_rules import (HOST_MODULES, HOST_RULES,
                                            HostRule, active_host_rules,
                                            analyze_host_module,
                                            host_check,
                                            host_check_sources,
                                            host_self_check,
                                            register_host_rule,
                                            resolve_host_modules)
from paddle_tpu.analysis.pool_rules import (POOL_CLIENT_MODULES,
                                            POOL_RULES, PoolRule,
                                            active_pool_rules,
                                            analyze_pool_module,
                                            pool_check,
                                            pool_check_sources,
                                            pool_self_check,
                                            register_pool_rule,
                                            resolve_pool_modules)

__all__ = [
    "Finding", "LintTarget", "lint", "lint_target", "SEVERITIES",
    "severity_rank", "RULES", "Rule", "active_rules", "register_rule",
    "CompileWatcher", "ENTRYPOINTS", "register_entrypoint",
    "self_check_targets", "SHARD_RULES", "ShardRecipe", "ShardRule",
    "active_shard_rules", "register_shard_rule", "shard_check",
    "MemoryReport", "check_budgets", "estimate_target", "load_budgets",
    "KERNEL_RULES", "KernelAnalysis", "KernelRule",
    "active_kernel_rules", "analyze_pallas_call", "derive_kernel_vmem",
    "kernel_self_check", "max_kernel_vmem", "register_kernel_rule",
    "nan_check",
    "HOST_MODULES", "HOST_RULES", "HostRule", "active_host_rules",
    "analyze_host_module", "host_check", "host_check_sources",
    "host_self_check", "register_host_rule", "resolve_host_modules",
    "POOL_CLIENT_MODULES", "POOL_RULES", "PoolRule",
    "active_pool_rules", "analyze_pool_module", "pool_check",
    "pool_check_sources", "pool_self_check", "register_pool_rule",
    "resolve_pool_modules",
]
