"""tpu-lint command line.

Two spellings, one implementation::

    python -m paddle_tpu.analysis --self-check          # CI gate
    python -m paddle_tpu.analysis mypkg.mymod:target    # lint anything
    python -m paddle_tpu lint --self-check              # cli.py alias

A target is ``module:attr`` where ``attr`` is either

* a zero-argument factory returning a
  :class:`~paddle_tpu.analysis.core.LintTarget` (the entrypoint-
  registry convention — build the jitted fn and example args), or
* any traceable callable, with ``--shapes`` giving the example
  arguments as avals, e.g. ``--shapes "f32[4,8],i32[4]"`` (dtype
  shorthand: f32/bf16/f16/i32/i64/u32/bool).

Findings render as a table (or ``--json``); the exit status is the
gate: 0 = clean at the ``--fail-on`` severity (default ``error``),
1 = findings at/above it, 2 = usage error.

Beyond the jaxpr walk, targets whose entrypoint ships a
:class:`~paddle_tpu.analysis.shard_rules.ShardRecipe` are also lowered
under a real multi-device CPU mesh and checked by the SPMD rule family
(collective-in-decode, mesh-axis-mismatch, ...).  Three more modes:

* ``--memory`` prints the per-shard HBM footprint estimate of every
  target; with ``--budgets analysis/budgets.json`` any entrypoint over
  (or missing from) its checked-in budget is an error finding.
* ``--warn-ratchet analysis/warn_baseline.json`` fails when the
  post-suppression warn count exceeds the checked-in baseline — warns
  can only go DOWN; ``--write-warn-baseline`` records a new floor.
* ``--nans`` RUNS each target (tiny shapes, CPU) under checkify float
  checks and reports the first non-finite-producing op with its source
  line.  A debug helper, not a tracing-only gate.
"""

from __future__ import annotations

import argparse
import importlib
import json
import re
import sys
from typing import List, Optional, Sequence

__all__ = ["main"]

_DTYPES = {"f32": "float32", "f64": "float64", "bf16": "bfloat16",
           "f16": "float16", "i32": "int32", "i64": "int64",
           "i8": "int8", "u32": "uint32", "u8": "uint8", "bool": "bool_"}


def _parse_shapes(spec: str):
    """``"f32[4,8],i32[4],bf16[]"`` -> tuple of ShapeDtypeStructs."""
    import jax
    import jax.numpy as jnp
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        m = re.fullmatch(r"(\w+)\[([\d;\s]*)\]", part)
        if not m or m.group(1) not in _DTYPES:
            raise SystemExit(
                f"--shapes: cannot parse {part!r} (want dtype[d;d;...], "
                f"dtypes: {', '.join(sorted(_DTYPES))})")
        dims = tuple(int(d) for d in m.group(2).split(";") if d.strip())
        out.append(jax.ShapeDtypeStruct(
            dims, getattr(jnp, _DTYPES[m.group(1)])))
    return tuple(out)


def _resolve_target(spec: str, shapes: Optional[str]):
    from paddle_tpu.analysis.core import LintTarget
    if ":" not in spec:
        # bare name: a registered entrypoint.  An unknown name is a
        # HARD usage error — silently skipping a misspelled entrypoint
        # would exit 0 with the gate never having run.
        from paddle_tpu.analysis.entrypoints import ENTRYPOINTS
        if spec in ENTRYPOINTS:
            return ENTRYPOINTS[spec]()
        print(f"tpu-lint: unknown entrypoint {spec!r} (and not a "
              "module:attr target).  Registered entrypoints:\n  "
              + "\n  ".join(sorted(ENTRYPOINTS)), file=sys.stderr)
        raise SystemExit(2)
    mod_name, attr = spec.split(":", 1)
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        raise SystemExit(f"cannot import {mod_name}: {e}")
    try:
        obj = getattr(mod, attr)
    except AttributeError:
        raise SystemExit(f"{mod_name} has no attribute {attr!r}")
    if isinstance(obj, LintTarget):
        return obj
    if shapes is not None:
        return LintTarget(spec, obj, _parse_shapes(shapes))
    # factory convention: call with no args, expect a LintTarget
    try:
        made = obj()
    except TypeError:
        raise SystemExit(
            f"{spec} takes arguments — pass --shapes to describe them, "
            "or point at a zero-arg factory returning a LintTarget")
    if not isinstance(made, LintTarget):
        raise SystemExit(
            f"{spec}() returned {type(made).__name__}, expected a "
            "LintTarget (fn + example args)")
    return made


# -------------------------------------------------------------- rendering


def _render_table(findings, out=None) -> None:
    # resolve sys.stdout per call, not at import (redirects, capsys)
    out = out if out is not None else sys.stdout
    if not findings:
        print("no findings", file=out)
        return
    rows = []
    for f in findings:
        loc = f.location()
        # repo-relative paths read better and keep the table narrow
        loc = re.sub(r"^.*?/paddle_tpu/", "paddle_tpu/", loc)
        rows.append((f.severity.upper(), f.rule_id, loc, f.path,
                     f.message))
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    for (sev, rule, loc, path, msg), f in zip(rows, findings):
        print(f"{sev:<{widths[0]}}  {rule:<{widths[1]}}  "
              f"{loc:<{widths[2]}}  {path:<{widths[3]}}  {msg}",
              file=out)
        if f.suggestion:
            pad = " " * (widths[0] + 2)
            print(f"{pad}-> {f.suggestion}", file=out)
        if f.cost:
            pad = " " * (widths[0] + 2)
            cost = ", ".join(f"{k}={v:.3g}" for k, v in f.cost.items())
            print(f"{pad}   program cost: {cost}", file=out)


def _live(findings):
    """Findings that count for gates/ratchets/summaries: a source-
    suppressed finding kept for the --json artifact never fails a run."""
    return [f for f in findings if not f.suppressed]


def _gate(findings, fail_on: str) -> int:
    from paddle_tpu.analysis.core import severity_rank
    bar = severity_rank(fail_on)
    return 1 if any(severity_rank(f.severity) >= bar
                    for f in _live(findings)) else 0


# ------------------------------------------------------------------- main


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu-lint",
        description="jaxpr-level static analysis of jitted entrypoints")
    parser.add_argument("targets", nargs="*",
                        help="module:attr — a LintTarget factory, or a "
                             "callable with --shapes")
    parser.add_argument("--self-check", action="store_true",
                        help="lint every registered entrypoint (trainer "
                             "step, dense/paged serve steps, eval step, "
                             "engine decode step)")
    parser.add_argument("--shapes", default=None,
                        help='example avals for a plain callable, e.g. '
                             '"f32[4;8],i32[4]"')
    parser.add_argument("--disable", default="",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--cost", action="store_true",
                        help="compile (CPU) and attach whole-program "
                             "flops/bytes to cost-aware findings")
    parser.add_argument("--fail-on", choices=("info", "warn", "error"),
                        default="error",
                        help="exit nonzero at this severity or above "
                             "(default: error)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--memory", action="store_true",
                        help="report the static per-shard HBM footprint "
                             "estimate of every target")
    parser.add_argument("--budgets", default=None, metavar="PATH",
                        help="budgets.json to gate --memory against: any "
                             "target over (or missing) its peak_bytes "
                             "budget is an error finding")
    parser.add_argument("--warn-ratchet", default=None, metavar="PATH",
                        help="fail when the post-suppression warn count "
                             "exceeds the baseline file's warn_count")
    parser.add_argument("--write-warn-baseline", default=None,
                        metavar="PATH",
                        help="record the current warn count as the new "
                             "ratchet baseline and exit")
    parser.add_argument("--nans", action="store_true",
                        help="RUN each target under checkify float "
                             "checks and localize the first non-finite "
                             "op (debug helper; executes the program)")
    parser.add_argument("--host", action="store_true",
                        help="run the host-concurrency family (thread "
                             "model + lock discipline, AST-level) over "
                             "the registered serving host modules; "
                             "positional args filter the module list")
    parser.add_argument("--pool", action="store_true",
                        help="run the pool-ownership family (paged-"
                             "block acquire/release/pin discipline, "
                             "AST-level) over the registered pool-"
                             "client modules; positional args filter "
                             "the module list")
    args = parser.parse_args(argv)

    # the analyzer must NEVER touch (or hang on) an attached chip: all
    # tracing runs on the CPU backend, same discipline as ci.sh lint.
    # Shard recipes need >=2 devices, so provision the same 8-virtual-
    # device CPU platform tests/conftest.py uses — BEFORE backend init.
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import paddle_tpu
    paddle_tpu._honor_env_platform(force=True)

    from paddle_tpu.analysis.rules import active_rules
    if args.list_rules:
        # grouped by family so the four registries stop interleaving
        from paddle_tpu.analysis.host_rules import active_host_rules
        from paddle_tpu.analysis.kernel_rules import active_kernel_rules
        from paddle_tpu.analysis.shard_rules import active_shard_rules
        print("jaxpr rules:")
        for rule in active_rules():
            print(f"  {rule.rule_id:<22} {rule.severity:<6} {rule.doc}")
        print("shard rules:")
        for rule in active_shard_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"  {rule.rule_id:<22} {rule.severity:<6} {doc}")
        print("kernel rules:")
        for rule in active_kernel_rules():
            print(f"  {rule.rule_id:<22} {rule.severity:<6} {rule.doc}")
        print("host rules:")
        for rule in active_host_rules():
            print(f"  {rule.rule_id:<22} {rule.severity:<6} {rule.doc}")
        print("pool rules:")
        from paddle_tpu.analysis.pool_rules import active_pool_rules
        for rule in active_pool_rules():
            print(f"  {rule.rule_id:<22} {rule.severity:<6} {rule.doc}")
        return 0

    from paddle_tpu.analysis.core import lint_target
    targets = []
    all_findings = []
    disable = tuple(filter(None, args.disable.split(",")))
    # --json is the CI artifact: it keeps source-suppressed findings,
    # flagged ``"suppressed": true``, so consumers see what was
    # silenced; gates/ratchets/summaries filter them out (_live).
    keep_suppressed = args.json
    host_mods = []
    pool_mods = []
    if args.host:
        # AST-level family: no tracing, positional args filter the
        # registered module list instead of naming entrypoints
        from paddle_tpu.analysis.host_rules import (host_check,
                                                    resolve_host_modules)
        host_mods = resolve_host_modules(args.targets or None)
        findings = host_check(host_mods, disable=disable,
                              keep_suppressed=keep_suppressed)
        all_findings.extend(findings)
        if not args.json:
            errs = sum(f.severity == "error" for f in findings)
            warns = sum(f.severity == "warn" for f in findings)
            print(f"== host: {len(host_mods)} module(s), "
                  f"{errs} error(s), {warns} warning(s)")
            _render_table(findings)
    if args.pool:
        # same contract as --host for the pool-ownership family
        from paddle_tpu.analysis.pool_rules import (pool_check,
                                                    resolve_pool_modules)
        pool_mods = resolve_pool_modules(args.targets or None)
        findings = pool_check(pool_mods, disable=disable,
                              keep_suppressed=keep_suppressed)
        all_findings.extend(findings)
        if not args.json:
            errs = sum(f.severity == "error" for f in findings)
            warns = sum(f.severity == "warn" for f in findings)
            print(f"== pool: {len(pool_mods)} module(s), "
                  f"{errs} error(s), {warns} warning(s)")
            _render_table(findings)
    if args.self_check:
        from paddle_tpu.analysis.entrypoints import self_check_targets
        targets.extend(self_check_targets())
        # kernel-rule wiring smoke BEFORE any entrypoint traces: a
        # registry break (rule unregistered, descent disconnected)
        # must fail fast as an error finding, not silently lint
        # kernels with half the family missing
        from paddle_tpu.analysis.core import Finding
        from paddle_tpu.analysis.kernel_rules import kernel_self_check
        try:
            msg = kernel_self_check()
            if not args.json:
                print(msg)
        except Exception as e:
            all_findings.append(Finding(
                rule_id="kernel-rule-smoke", severity="error",
                path="--self-check",
                message=f"kernel-rule wiring smoke failed: {e}",
                suggestion="analysis/kernel_rules.py registration or "
                           "core.py pallas_call descent broke"))
        # host-rule wiring smoke, same contract: the deadlock-cycle
        # and unguarded-write mutants must each fire exactly once
        # through the full host_check path, clean twins quiet
        from paddle_tpu.analysis.host_rules import host_self_check
        try:
            msg = host_self_check()
            if not args.json:
                print(msg)
        except Exception as e:
            all_findings.append(Finding(
                rule_id="host-rule-smoke", severity="error",
                path="--self-check",
                message=f"host-rule wiring smoke failed: {e}",
                suggestion="analysis/host_rules.py registration or "
                           "thread-model construction broke"))
        # pool-rule wiring smoke, same contract: the refcount-leak and
        # share-before-pin mutants must each fire exactly once through
        # the full pool_check path, clean twins quiet
        from paddle_tpu.analysis.pool_rules import pool_self_check
        try:
            msg = pool_self_check()
            if not args.json:
                print(msg)
        except Exception as e:
            all_findings.append(Finding(
                rule_id="pool-rule-smoke", severity="error",
                path="--self-check",
                message=f"pool-rule wiring smoke failed: {e}",
                suggestion="analysis/pool_rules.py registration or "
                           "ownership-model construction broke"))
    if not (args.host or args.pool):
        for spec in args.targets:
            targets.append(_resolve_target(spec, args.shapes))
    if not targets and not (args.host or args.pool):
        parser.print_usage(sys.stderr)
        print("tpu-lint: nothing to lint (pass targets, --self-check, "
              "--host or --pool)", file=sys.stderr)
        return 2

    if args.nans:
        from paddle_tpu.analysis.nans import nan_check
        for target in targets:
            findings = nan_check(target)
            all_findings.extend(findings)
            if not args.json:
                print(f"== {target.name}: "
                      f"{'NON-FINITE' if findings else 'all finite'}")
                _render_table(findings)
        if args.json:
            print(json.dumps([f.to_dict() for f in all_findings],
                             indent=2))
        return _gate(all_findings, args.fail_on)

    from paddle_tpu.analysis.shard_rules import shard_check
    for target in targets:
        findings = lint_target(target, disable=disable,
                               with_cost=args.cost,
                               keep_suppressed=keep_suppressed)
        findings.extend(shard_check(target, disable=disable,
                                    keep_suppressed=keep_suppressed))
        all_findings.extend(findings)
        if not args.json:
            errs = sum(f.severity == "error" for f in findings)
            warns = sum(f.severity == "warn" for f in findings)
            print(f"== {target.name}: {errs} error(s), "
                  f"{warns} warning(s)")
            _render_table(findings)

    reports = []
    if args.memory or args.budgets:
        from paddle_tpu.analysis.memory import (check_budgets,
                                                estimate_target,
                                                load_budgets)
        reports = [estimate_target(t) for t in targets]
        if not args.json:
            print("== memory: static per-shard footprint ==")
            for rep in reports:
                xla = (f"  (xla temp {rep.xla['temp_size_in_bytes']}B)"
                       if rep.xla else "")
                kv = (f"  kernel-vmem {rep.kernel_vmem_bytes}B"
                      if rep.kernel_vmem_bytes else "")
                print(f"{rep.name:<22} mesh={rep.mesh:<12} "
                      f"peak/shard {rep.peak_bytes}B  "
                      f"args {rep.args_bytes}B  "
                      f"largest-transient "
                      f"{rep.largest_transient_bytes}B{xla}{kv}")
        if args.budgets:
            budget_findings = check_budgets(reports,
                                            load_budgets(args.budgets))
            all_findings.extend(budget_findings)
            if not args.json:
                _render_table(budget_findings) if budget_findings else \
                    print(f"memory budgets OK ({args.budgets})")

    warns = sum(f.severity == "warn" for f in _live(all_findings))
    if args.write_warn_baseline:
        with open(args.write_warn_baseline, "w") as f:
            json.dump({"warn_count": warns}, f, indent=2)
            f.write("\n")
        print(f"tpu-lint: wrote warn baseline {warns} -> "
              f"{args.write_warn_baseline}")
        return 0

    rc = _gate(all_findings, args.fail_on)
    if args.warn_ratchet:
        with open(args.warn_ratchet) as f:
            baseline = int(json.load(f)["warn_count"])
        if warns > baseline:
            rc = 1
            print(f"tpu-lint: warn ratchet FAIL — {warns} warning(s) "
                  f"exceeds the checked-in baseline {baseline} "
                  f"({args.warn_ratchet}); fix or justify with a "
                  "'# tpu-lint: disable=' comment, never by raising "
                  "the baseline casually", file=sys.stderr)
        elif not args.json:
            print(f"warn ratchet OK ({warns} <= baseline {baseline})")

    if args.json:
        payload = [f.to_dict() for f in all_findings]
        if reports:
            print(json.dumps({"findings": payload,
                              "memory": [r.to_dict() for r in reports]},
                             indent=2))
        else:
            print(json.dumps(payload, indent=2))
    else:
        scanned = []
        if targets:
            scanned.append(f"{len(targets)} entrypoint(s)")
        if host_mods:
            scanned.append(f"{len(host_mods)} host module(s)")
        if pool_mods:
            scanned.append(f"{len(pool_mods)} pool module(s)")
        print(f"tpu-lint: {' + '.join(scanned) or '0 targets'}, "
              f"{len(all_findings)} finding(s) — "
              f"{'FAIL' if rc else 'OK'} at --fail-on={args.fail_on}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
