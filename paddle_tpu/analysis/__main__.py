"""``python -m paddle_tpu.analysis`` — the tpu-lint CLI (see cli.py)."""

import sys

from paddle_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
