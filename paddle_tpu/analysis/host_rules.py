"""tpu-lint HOST rule family: thread-safety and lock discipline for
the serving host layer, proved from the AST instead of a trace.

Every other tpu-lint family works on a *traced* artifact — jaxprs
(``rules.py``), compiled HLO (``shard_rules.py``), Pallas kernel
bodies (``kernel_rules.py``).  The host-side concurrency layer that
drives those programs in production (frontend worker threads, the
cluster controller's accept/reader threads, tracer ring buffers, the
prefix ledgers) never crosses a trace boundary, so until now it was
guarded only by seeded chaos schedules — probabilistic coverage for a
deterministic failure class.  This module closes that gap with an
AST-level pass that builds, per module:

* a **thread model** — thread roots from ``threading.Thread(target=
  ...)`` / ``threading.Timer`` spawn sites, plus the public API
  surface as the implicit "caller" root (every public method can run
  on whatever thread the embedder calls from), with intra-class
  ``self.method()`` call edges assigning each method to the roots
  that can reach it;
* the set of **shared mutable attributes** — instance fields (and
  ``global``-declared module state) accessed from >= 2 distinct
  thread roots with at least one write;
* a **lock-scope map** — ``with self._lock:`` regions (any context
  manager whose name ends in a ``lock`` token, or an attribute
  initialised from ``threading.Lock/RLock/Condition/Semaphore``),
  plus the repo's ``_locked``-suffix convention: a method named
  ``*_locked`` is taken to run with its class's ``self._lock`` held
  (frontend.py's existing discipline, now machine-checked).

The rule registry then checks:

* ``unguarded-shared-write`` — a shared field written outside every
  lock scope that guards its other accesses.  Declare intent with a
  ``# guarded-by: <lock>`` comment on (or above) the write, or
  suppress with the usual ``# tpu-lint: disable=`` + rationale.
* ``lock-order-cycle`` — the cross-module lock-acquisition graph
  (syntactic ``with`` nesting + call edges resolved through
  ``self.attr = ClassName(...)`` component types) must be acyclic:
  static deadlock detection.
* ``blocking-under-lock`` — ``time.sleep`` / ``Event.wait`` / socket
  ``recv``/``accept``/``connect`` / ``Thread.join`` / subprocess
  waits / ``.block_until_ready()`` inside a lock scope — the
  hung-step-watchdog failure class caught before it fires.
* ``leaked-lock`` — a bare ``.acquire()`` with no ``with`` block and
  no ``.release()`` in a dominating ``finally``.

Proved vs tested (the honest caveats, mirrored in
``docs/design/analysis.md``): the model is name-based, not
points-to — two attributes spelled ``self._lock`` on different
classes are different locks (sound for cycles: merging would only
ADD edges); fields on objects other than ``self`` (e.g. the
frontend's ``seat.*``) escape the per-class model; callbacks invoked
through registries run on whichever root calls them and are folded
into "caller"; ``queue.Queue`` hand-off (``.put``/``.get``) is
deliberately not a "write" — it IS the sanctioned lock-free channel
(the cluster's documented contract).  The chaos schedules keep
covering what the AST cannot see; this family makes the lock
discipline itself a per-commit contract.

``host_self_check()`` is the wiring smoke ``--self-check`` rides: a
two-lock deadlock mutant and an unguarded-shared-write mutant must
each produce exactly one finding through the full ``host_check``
path, and their clean twins must stay quiet.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.core import Finding, LintContext, severity_rank

__all__ = [
    "HOST_MODULES", "HOST_RULES", "HostRule", "ModuleModel",
    "active_host_rules", "analyze_host_module", "host_check",
    "host_check_sources", "host_self_check", "register_host_rule",
    "resolve_host_modules",
]

#: The registered host-layer module set ``lint --host`` covers: every
#: module that owns threads, locks, or cross-thread state on the
#: serving path.  Pure-policy modules (autoscaler) ride along cheaply
#: and prove they STAY lock-free.
HOST_MODULES = (
    "paddle_tpu.serving",
    "paddle_tpu.frontend",
    "paddle_tpu.prefix_cache",
    "paddle_tpu.cluster.controller",
    "paddle_tpu.cluster.worker",
    "paddle_tpu.cluster.autoscaler",
    "paddle_tpu.cluster.handoff",
    "paddle_tpu.cluster.wire",
    "paddle_tpu.cluster.selfcheck",
    "paddle_tpu.telemetry.metrics",
    "paddle_tpu.telemetry.trace",
    "paddle_tpu.telemetry.httpd",
)

# A name segment is lock-like when "lock" appears as a whole token
# ("_lock", "active_lock", "rlock") — NOT as a substring ("block",
# "num_blocks" must never classify as locks).
_LOCK_NAME_RE = re.compile(r"(?:^|_)r?lock(?:$|_|s$)", re.IGNORECASE)

#: ``threading`` constructors whose product is a lock for scope/graph
#: purposes even when the attribute name says nothing.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

#: Method calls that mutate their receiver in place — a write to the
#: field holding the receiver.  ``queue.Queue.put/get`` are absent on
#: purpose: the queue IS the sanctioned lock-free cross-thread channel.
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop",
             "popleft", "popitem", "remove", "clear", "add", "discard",
             "update", "setdefault", "sort", "reverse"}

#: Attribute calls that block the calling thread.  ``.join`` is only
#: blocking when the receiver isn't a string constant and the call has
#: no positional args (``Thread.join(timeout=...)`` vs ``sep.join(
#: parts)``); ``.get`` is excluded (dict.get) — documented caveat.
_BLOCKING_METHODS = {"sleep", "wait", "join", "accept", "connect",
                     "recv", "recv_into", "recvfrom", "communicate",
                     "check_call", "check_output",
                     "block_until_ready", "recv_msg"}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([\w.\-]+)")

_CALLER_ROOT = "caller"


def _is_lock_name(segment: str) -> bool:
    return bool(_LOCK_NAME_RE.search(segment))


def _dotted(node: ast.expr) -> Optional[str]:
    """``self._lock`` -> "self._lock"; None for non-name chains."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


# ------------------------------------------------------------------ model


@dataclasses.dataclass
class Access:
    """One read or write of a tracked field at a source line."""
    attr: str
    kind: str                       # "read" | "write"
    line: int
    locks: FrozenSet[str]           # lock ids held at the site
    guarded_by: Optional[str]       # "# guarded-by: X" annotation


@dataclasses.dataclass
class CallSite:
    """A call the lock-graph may need to resolve."""
    kind: str                       # "self" | "attr" | "name"
    target: Tuple                   # ("m",) | (attr, "m") | ("fn",)
    line: int
    locks: FrozenSet[str]


@dataclasses.dataclass
class Acquisition:
    lock: str
    line: int
    held: FrozenSet[str]            # locks already held when acquired


@dataclasses.dataclass
class BlockingCall:
    what: str
    line: int
    locks: FrozenSet[str]


@dataclasses.dataclass
class FnInfo:
    name: str
    qualname: str
    line: int
    accesses: List[Access] = dataclasses.field(default_factory=list)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    acquisitions: List[Acquisition] = dataclasses.field(
        default_factory=list)
    blocking: List[BlockingCall] = dataclasses.field(
        default_factory=list)
    bare_acquires: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    finally_releases: Set[str] = dataclasses.field(default_factory=set)
    with_releases: Set[str] = dataclasses.field(default_factory=set)
    implicit_locks: FrozenSet[str] = frozenset()


@dataclasses.dataclass
class ClassModel:
    name: str
    module: str
    methods: Dict[str, FnInfo] = dataclasses.field(default_factory=dict)
    spawn_targets: Set[str] = dataclasses.field(default_factory=set)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    call_edges: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)
    method_roots: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)

    def lock_id(self, attr: str) -> str:
        return f"{self.module}:{self.name}.{attr}"


@dataclasses.dataclass
class ModuleModel:
    name: str                       # dotted module name
    file: str
    lines: List[str]
    classes: Dict[str, ClassModel] = dataclasses.field(
        default_factory=dict)
    functions: Dict[str, FnInfo] = dataclasses.field(
        default_factory=dict)
    spawn_targets: Set[str] = dataclasses.field(default_factory=set)
    global_mutables: Set[str] = dataclasses.field(default_factory=set)
    fn_roots: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)

    @property
    def short(self) -> str:
        return self.name.rpartition(".")[2]


class _FnWalker:
    """One pass over a function body tracking the held-lock set through
    ``with`` nesting, collecting accesses / calls / acquisitions."""

    def __init__(self, model: ModuleModel, cls: Optional[ClassModel],
                 fn: ast.FunctionDef, qualname: str,
                 global_names: Set[str]):
        self.model = model
        self.cls = cls
        self.qualname = qualname
        implicit: FrozenSet[str] = frozenset()
        # the repo's convention: a *_locked method runs under its
        # class's self._lock (frontend.py discipline, machine-checked)
        if cls is not None and fn.name.endswith("_locked"):
            implicit = frozenset({cls.lock_id("_lock")})
        self.info = FnInfo(name=fn.name, qualname=qualname,
                           line=fn.lineno, implicit_locks=implicit)
        self.fn_globals: Set[str] = set()
        self.fn_locals: Set[str] = {a.arg for a in fn.args.args}
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                self.fn_globals.update(node.names)
        self._walk_body(fn.body, implicit, in_finally=False)

    # -------------------------------------------------- lock classification

    def _lock_of(self, expr: ast.expr) -> Optional[str]:
        """Canonical lock id for a with-item / acquire receiver, or
        None when the expression isn't lock-like."""
        dotted = _dotted(expr)
        if dotted is None:
            return None
        parts = dotted.split(".")
        named = _is_lock_name(parts[-1])
        ctor = False
        if (self.cls is not None and len(parts) == 2
                and parts[0] == "self"):
            ctor = parts[1] in getattr(self.cls, "_lock_ctor_attrs",
                                       set())
            if named or ctor:
                return self.cls.lock_id(parts[1])
            return None
        if not named:
            return None
        if parts[0] == "self":       # self.a.b_lock — qualify by class
            cls = self.cls.name if self.cls is not None else "?"
            return f"{self.model.name}:{cls}.{'.'.join(parts[1:])}"
        return f"{self.model.name}:{dotted}"

    # ------------------------------------------------------- statement walk

    def _walk_body(self, body, held: FrozenSet[str],
                   in_finally: bool) -> None:
        for stmt in body:
            self._walk_stmt(stmt, held, in_finally)

    def _walk_stmt(self, stmt, held: FrozenSet[str],
                   in_finally: bool) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in stmt.items:
                lock = self._lock_of(item.context_expr)
                if lock is None:
                    self._walk_expr(item.context_expr, held)
                else:
                    self.info.acquisitions.append(Acquisition(
                        lock=lock, line=stmt.lineno,
                        held=frozenset(inner)))
                    self.info.with_releases.add(lock)
                    inner.add(lock)
            self._walk_body(stmt.body, frozenset(inner), in_finally)
        elif isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, held, in_finally)
            for h in stmt.handlers:
                self._walk_body(h.body, held, in_finally)
            self._walk_body(stmt.orelse, held, in_finally)
            self._walk_body(stmt.finalbody, held, in_finally=True)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._walk_expr(stmt.test, held)
            self._walk_body(stmt.body, held, in_finally)
            self._walk_body(stmt.orelse, held, in_finally)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._walk_expr(stmt.iter, held)
            self._record_store_target(stmt.target)
            self._walk_body(stmt.body, held, in_finally)
            self._walk_body(stmt.orelse, held, in_finally)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: body runs later, possibly without the lock —
            # but conservatively attribute its accesses to this scope
            self.fn_locals.add(stmt.name)
            self._walk_body(stmt.body, held, in_finally)
        elif isinstance(stmt, ast.ClassDef):
            pass
        else:
            self._walk_leaf(stmt, held, in_finally)

    def _record_store_target(self, target) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                self.fn_locals.add(n.id)

    # ------------------------------------------------------ leaf statements

    def _walk_leaf(self, stmt, held: FrozenSet[str],
                   in_finally: bool) -> None:
        # explicit write targets first (assign / augassign / del)
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for t in targets:
            self._record_write_target(t, stmt.lineno, held)
        self._walk_expr(stmt, held, in_finally=in_finally)

    def _record_write_target(self, target, line: int,
                             held: FrozenSet[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write_target(elt, line, held)
            return
        node = target
        while isinstance(node, (ast.Subscript, ast.Starred)):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and self.cls is not None):
            self._access(node.attr, "write", line, held)
        elif isinstance(node, ast.Name):
            if node.id in self.fn_globals:
                self._global_access(node.id, "write", line, held)
            else:
                self.fn_locals.add(node.id)

    def _access(self, attr: str, kind: str, line: int,
                held: FrozenSet[str]) -> None:
        self.info.accesses.append(Access(
            attr=attr, kind=kind, line=line,
            locks=held | self.info.implicit_locks,
            guarded_by=self._annotation(line)))

    def _global_access(self, name: str, kind: str, line: int,
                       held: FrozenSet[str]) -> None:
        self.info.accesses.append(Access(
            attr=f"global:{name}", kind=kind, line=line,
            locks=held | self.info.implicit_locks,
            guarded_by=self._annotation(line)))

    def _annotation(self, line: int) -> Optional[str]:
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.model.lines):
                m = _GUARDED_BY_RE.search(self.model.lines[ln - 1])
                if m:
                    return m.group(1)
        return None

    # ------------------------------------------------------ expression walk

    def _walk_expr(self, node, held: FrozenSet[str],
                   in_finally: bool = False) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._record_call(sub, held, in_finally)
            elif isinstance(sub, ast.Attribute):
                if (isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and isinstance(sub.ctx, ast.Load)
                        and self.cls is not None):
                    self._access(sub.attr, "read", sub.lineno, held)
            elif isinstance(sub, ast.Name):
                if (isinstance(sub.ctx, ast.Load)
                        and sub.id in self.model.global_mutables
                        and sub.id not in self.fn_locals):
                    self._global_access(sub.id, "read", sub.lineno,
                                        held)
            elif isinstance(sub, (ast.Lambda,)):
                pass  # body visited by the same ast.walk, same held set

    def _record_call(self, call: ast.Call, held: FrozenSet[str],
                     in_finally: bool) -> None:
        func = call.func
        self._record_spawn(call)
        if isinstance(func, ast.Attribute):
            meth, recv = func.attr, func.value
            # in-place mutator -> a write to the receiver field
            if meth in _MUTATORS:
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and self.cls is not None):
                    self._access(recv.attr, "write", call.lineno, held)
                elif (isinstance(recv, ast.Name)
                      and recv.id in self.model.global_mutables
                      and recv.id not in self.fn_locals):
                    self._global_access(recv.id, "write", call.lineno,
                                        held)
            # lock protocol
            lock = self._lock_of(recv)
            if lock is not None and meth == "acquire":
                self.info.bare_acquires.append((lock, call.lineno))
            if lock is not None and meth == "release":
                if in_finally:
                    self.info.finally_releases.add(lock)
            # blocking while holding a lock
            if meth in _BLOCKING_METHODS and held:
                if not self._join_exempt(meth, recv, call):
                    what = _dotted(func) or f"?.{meth}"
                    self.info.blocking.append(BlockingCall(
                        what=what, line=call.lineno, locks=held))
            # call-graph edges the lock-cycle rule resolves
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.info.calls.append(CallSite(
                    kind="self", target=(meth,), line=call.lineno,
                    locks=held | self.info.implicit_locks))
            elif (isinstance(recv, ast.Attribute)
                  and isinstance(recv.value, ast.Name)
                  and recv.value.id == "self"):
                self.info.calls.append(CallSite(
                    kind="attr", target=(recv.attr, meth),
                    line=call.lineno,
                    locks=held | self.info.implicit_locks))
        elif isinstance(func, ast.Name):
            if func.id == "sleep" and held:
                self.info.blocking.append(BlockingCall(
                    what="sleep", line=call.lineno, locks=held))
            self.info.calls.append(CallSite(
                kind="name", target=(func.id,), line=call.lineno,
                locks=held | self.info.implicit_locks))

    @staticmethod
    def _join_exempt(meth: str, recv, call: ast.Call) -> bool:
        """``sep.join(parts)`` is string formatting, not blocking:
        exempt ``.join`` with a constant-string receiver or any
        positional argument (``Thread.join`` takes only timeout=)."""
        if meth != "join":
            return False
        if isinstance(recv, ast.Constant) and isinstance(recv.value,
                                                        str):
            return True
        return bool(call.args)

    def _record_spawn(self, call: ast.Call) -> None:
        func = call.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name not in ("Thread", "Timer"):
            return
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if name == "Timer" and target is None and len(call.args) >= 2:
            target = call.args[1]
        if target is None:
            return
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and self.cls is not None):
            self.cls.spawn_targets.add(target.attr)
        elif isinstance(target, ast.Name):
            self.model.spawn_targets.add(target.id)


# --------------------------------------------------------------- analysis


def _collect_attr_types(cls_model: ClassModel,
                        init: ast.FunctionDef) -> None:
    """``self.x = Cls(...)`` (directly or through one local alias) in
    __init__ types the component attribute for cross-class call
    resolution in the lock graph."""
    local_types: Dict[str, str] = {}

    def ctor_name(value) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        d = _dotted(value.func)
        if d is None:
            return None
        leaf = d.rpartition(".")[2]
        return leaf if leaf[:1].isupper() else None

    for stmt in ast.walk(init):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        cname = ctor_name(stmt.value)
        if isinstance(tgt, ast.Name) and cname:
            local_types[tgt.id] = cname
        elif (isinstance(tgt, ast.Attribute)
              and isinstance(tgt.value, ast.Name)
              and tgt.value.id == "self"):
            if cname:
                cls_model.attr_types[tgt.attr] = cname
            elif (isinstance(stmt.value, ast.Name)
                  and stmt.value.id in local_types):
                cls_model.attr_types[tgt.attr] = \
                    local_types[stmt.value.id]
            if (ctor_name(stmt.value) in _LOCK_CTORS
                    or (isinstance(stmt.value, ast.Call)
                        and _dotted(stmt.value.func) is not None
                        and _dotted(stmt.value.func).rpartition(".")[2]
                        in _LOCK_CTORS)):
                getattr(cls_model, "_lock_ctor_attrs").add(tgt.attr)


def _compute_roots(methods: Dict[str, FnInfo],
                   spawn_targets: Set[str],
                   public: Set[str]) -> Dict[str, Set[str]]:
    """Assign each method/function the set of thread roots that can
    reach it through self-/name-call edges."""
    edges: Dict[str, Set[str]] = {}
    for name, info in methods.items():
        edges[name] = {c.target[0] for c in info.calls
                       if c.kind in ("self", "name")
                       and c.target[0] in methods
                       # same-key self vs name calls resolved by caller
                       }
    roots: Dict[str, Set[str]] = {name: set() for name in methods}

    def flood(root: str, entries: Set[str]) -> None:
        stack = [e for e in entries if e in methods]
        seen: Set[str] = set()
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            roots[m].add(root)
            stack.extend(edges.get(m, ()))

    flood(_CALLER_ROOT, public)
    for t in sorted(spawn_targets):
        flood(f"thread:{t}", {t})
    # a method no root reaches (registered callback, getattr dispatch)
    # is folded into the caller root — it runs on whoever invokes it
    for name, r in roots.items():
        if not r:
            r.add(_CALLER_ROOT)
    return roots


def analyze_host_module(path: Optional[str] = None,
                        source: Optional[str] = None,
                        name: Optional[str] = None) -> ModuleModel:
    """Parse one module into its thread/lock model.  ``path`` reads a
    file; ``source`` lints a string (tests, self-check mutants)."""
    if source is None:
        assert path is not None, "need path or source"
        with open(path) as f:
            source = f.read()
    file = path or f"<{name or 'host-lint'}>"
    mod_name = name or (os.path.splitext(os.path.basename(file))[0]
                        if path else "mutant")
    tree = ast.parse(source, filename=file)
    model = ModuleModel(name=mod_name, file=file,
                        lines=source.splitlines())

    # pass 0: global-declared mutable module state
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            model.global_mutables.update(node.names)

    # pass 1: build class/function models (two sweeps so spawn sites
    # and lock-ctor attrs discovered mid-walk inform root computation)
    classes = [n for n in tree.body if isinstance(n, ast.ClassDef)]
    functions = [n for n in tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
    for cnode in classes:
        cm = ClassModel(name=cnode.name, module=model.short)
        object.__setattr__(cm, "_lock_ctor_attrs", set())
        model.classes[cnode.name] = cm
        init = next((m for m in cnode.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is not None:
            _collect_attr_types(cm, init)
        for m in cnode.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = _FnWalker(model, cm, m,
                              f"{model.short}.{cnode.name}.{m.name}",
                              model.global_mutables)
                cm.methods[m.name] = w.info
        public = {n for n in cm.methods
                  if not n.startswith("_") or n == "__init__"
                  or (n.startswith("__") and n.endswith("__"))}
        cm.method_roots = _compute_roots(cm.methods, cm.spawn_targets,
                                         public)
    for fnode in functions:
        w = _FnWalker(model, None, fnode,
                      f"{model.short}.{fnode.name}",
                      model.global_mutables)
        model.functions[fnode.name] = w.info
    pub_fns = {n for n in model.functions if not n.startswith("_")}
    model.fn_roots = _compute_roots(model.functions,
                                    model.spawn_targets, pub_fns)
    return model


# -------------------------------------------------------------- registry


class HostRule:
    """Base host-concurrency rule.  ``check_module`` runs per module;
    ``check_program`` once over the whole analyzed set (cross-module
    properties like the lock graph)."""

    rule_id = "abstract-host-rule"
    severity = "warn"
    family = "host"
    doc = ""

    def check_module(self, model: ModuleModel,
                     ctx: LintContext) -> None:
        pass

    def check_program(self, models: Sequence[ModuleModel],
                      ctx: LintContext) -> None:
        pass


HOST_RULES: Dict[str, type] = {}


def register_host_rule(cls):
    HOST_RULES[cls.rule_id] = cls
    return cls


def active_host_rules() -> List[HostRule]:
    return [cls() for cls in HOST_RULES.values()]


# ----------------------------------------------------------------- rules


def _field_groups(model: ModuleModel):
    """Yield (scope_name, roots_of_fn, accesses_by_field) for every
    class plus the module-function pseudo-scope."""
    for cname, cm in sorted(model.classes.items()):
        yield (f"{model.short}.{cname}", cm.method_roots, cm.methods,
               False)
    yield (model.short, model.fn_roots, model.functions, True)


@register_host_rule
class UnguardedSharedWrite(HostRule):
    rule_id = "unguarded-shared-write"
    severity = "warn"
    doc = ("field accessed from >=2 thread roots, written outside "
           "every lock scope that guards its other accesses")

    def check_module(self, model: ModuleModel,
                     ctx: LintContext) -> None:
        for scope, roots, fns, is_module in _field_groups(model):
            per_field: Dict[str, List[Tuple[str, Access]]] = {}
            for fname, info in fns.items():
                if not is_module and fname in ("__init__", "__del__"):
                    continue   # construction happens-before publish
                for acc in info.accesses:
                    if is_module != acc.attr.startswith("global:"):
                        continue
                    per_field.setdefault(acc.attr, []).append(
                        (fname, acc))
            for field, sites in sorted(per_field.items()):
                a_roots: Set[str] = set()
                writers = []
                for fname, acc in sites:
                    a_roots |= roots.get(fname, {_CALLER_ROOT})
                    if acc.kind == "write":
                        writers.append((fname, acc))
                # module-level globals: each public fn is its own root
                # (module functions have no owning thread)
                if is_module:
                    a_roots = {f"{_CALLER_ROOT}:{f}" if r ==
                               _CALLER_ROOT else r
                               for f, _ in sites
                               for r in roots.get(f, {_CALLER_ROOT})}
                if len(a_roots) < 2 or not writers:
                    continue
                guards: Set[str] = set()
                for _, acc in sites:
                    guards |= acc.locks
                    if acc.guarded_by:
                        guards.add(acc.guarded_by)
                reported_never = False
                for fname, acc in writers:
                    if acc.locks or acc.guarded_by:
                        continue
                    pretty = field.replace("global:", "")
                    if guards:
                        locks = ", ".join(sorted(guards))
                        ctx.report(
                            self, f"{scope}.{fname}",
                            f"write to shared field {pretty!r} holds "
                            f"no lock, but its other accesses are "
                            f"guarded by {locks}",
                            suggestion="take the guarding lock, or "
                                       "declare intent with "
                                       "'# guarded-by: <lock>'",
                            file=model.file, line=acc.line)
                    elif not reported_never:
                        reported_never = True
                        r = ", ".join(sorted(a_roots))
                        ctx.report(
                            self, f"{scope}.{fname}",
                            f"shared field {pretty!r} (accessed from "
                            f"{r}) is written with no lock held "
                            f"anywhere",
                            suggestion="guard every access with one "
                                       "lock, or suppress with a "
                                       "rationale if the race is "
                                       "benign by design",
                            file=model.file, line=acc.line)


@register_host_rule
class LockOrderCycle(HostRule):
    rule_id = "lock-order-cycle"
    severity = "error"
    doc = ("cross-module lock-acquisition graph has a cycle — "
           "static deadlock")

    def check_program(self, models: Sequence[ModuleModel],
                      ctx: LintContext) -> None:
        class_index: Dict[str, Tuple[ModuleModel, ClassModel]] = {}
        for m in models:
            for cname, cm in m.classes.items():
                class_index.setdefault(cname, (m, cm))
        acquired_memo: Dict[int, FrozenSet[str]] = {}

        def resolve(model, cls, call: CallSite):
            if call.kind == "self" and cls is not None:
                return model, cls, cls.methods.get(call.target[0])
            if call.kind == "attr" and cls is not None:
                tname = cls.attr_types.get(call.target[0])
                if tname and tname in class_index:
                    tm, tc = class_index[tname]
                    return tm, tc, tc.methods.get(call.target[1])
            if call.kind == "name":
                return model, None, model.functions.get(
                    call.target[0])
            return model, cls, None

        def acquired(model, cls, info: Optional[FnInfo],
                     stack: Set[int]) -> FrozenSet[str]:
            if info is None:
                return frozenset()
            key = id(info)
            if key in acquired_memo:
                return acquired_memo[key]
            if key in stack:
                return frozenset()
            stack.add(key)
            locks = {a.lock for a in info.acquisitions}
            locks |= info.implicit_locks
            for call in info.calls:
                tm, tc, ti = resolve(model, cls, call)
                if ti is not None and ti is not info:
                    locks |= acquired(tm, tc, ti, stack)
            stack.discard(key)
            acquired_memo[key] = frozenset(locks)
            return acquired_memo[key]

        edges: Dict[str, Dict[str, Tuple[str, int]]] = {}

        def edge(a: str, b: str, file: str, line: int) -> None:
            if a != b:
                edges.setdefault(a, {}).setdefault(b, (file, line))

        for m in models:
            scopes = [(m, cm, info) for cm in m.classes.values()
                      for info in cm.methods.values()]
            scopes += [(m, None, info)
                       for info in m.functions.values()]
            for model, cls, info in scopes:
                for acq in info.acquisitions:
                    for h in acq.held | info.implicit_locks:
                        edge(h, acq.lock, model.file, acq.line)
                for call in info.calls:
                    if not call.locks:
                        continue
                    tm, tc, ti = resolve(model, cls, call)
                    if ti is None or ti is info:
                        continue
                    for l in acquired(tm, tc, ti, set()):
                        for h in call.locks:
                            edge(h, l, model.file, call.line)

        # Tarjan SCC over the lock graph; any SCC of >=2 locks is a
        # potential deadlock (self-edges skipped: RLock re-entry)
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strong(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in edges.get(v, {}):
                if w not in index:
                    strong(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

        nodes = set(edges)
        for tos in edges.values():
            nodes.update(tos)
        for v in sorted(nodes):
            if v not in index:
                strong(v)

        for comp in sorted(sccs):
            anchor = None
            for a in comp:
                for b, loc in sorted(edges.get(a, {}).items()):
                    if b in comp:
                        anchor = loc
                        break
                if anchor:
                    break
            file, line = anchor if anchor else (models[0].file, 1)
            ctx.report(
                self, "lock-graph",
                "lock-acquisition cycle: "
                + " <-> ".join(comp)
                + " — two threads taking these in opposite order "
                  "deadlock",
                suggestion="impose one global acquisition order "
                           "(document it where the locks are made)",
                file=file, line=line)


@register_host_rule
class BlockingUnderLock(HostRule):
    rule_id = "blocking-under-lock"
    severity = "error"
    doc = ("sleep/wait/join/socket-recv/subprocess/"
           "block_until_ready called while holding a lock")

    def check_module(self, model: ModuleModel,
                     ctx: LintContext) -> None:
        scopes = [(cm, info) for cm in model.classes.values()
                  for info in cm.methods.values()]
        scopes += [(None, info) for info in model.functions.values()]
        for _, info in scopes:
            for b in info.blocking:
                locks = ", ".join(sorted(b.locks))
                ctx.report(
                    self, info.qualname,
                    f"blocking call {b.what}() while holding "
                    f"{locks} — every other thread needing the lock "
                    f"stalls for the full wait",
                    suggestion="move the wait outside the lock "
                               "(collect under the lock, block "
                               "after), or bound it and suppress "
                               "with a rationale",
                    file=model.file, line=b.line)


@register_host_rule
class LeakedLock(HostRule):
    rule_id = "leaked-lock"
    severity = "error"
    doc = ("bare .acquire() without a 'with' block or a .release() "
           "in a dominating finally")

    def check_module(self, model: ModuleModel,
                     ctx: LintContext) -> None:
        scopes = [info for cm in model.classes.values()
                  for info in cm.methods.values()]
        scopes += list(model.functions.values())
        for info in scopes:
            for lock, line in info.bare_acquires:
                if lock in info.finally_releases:
                    continue
                ctx.report(
                    self, info.qualname,
                    f"{lock} is acquire()d with no release() in a "
                    f"finally — any exception on the path leaks the "
                    f"lock and wedges every other thread",
                    suggestion="use 'with <lock>:' (or try/finally "
                               "release)",
                    file=model.file, line=line)


# ------------------------------------------------------------ entrypoints


def resolve_host_modules(
        filters: Optional[Sequence[str]] = None
) -> List[Tuple[str, str]]:
    """(dotted-name, file-path) for the registered host modules,
    optionally restricted by substring filters (CLI positionals)."""
    import importlib.util
    out = []
    for dotted in HOST_MODULES:
        if filters and not any(f in dotted or dotted.endswith(f)
                               for f in filters):
            continue
        spec = importlib.util.find_spec(dotted)
        if spec is None or spec.origin is None:
            raise RuntimeError(
                f"host-lint: registered module {dotted} not found")
        out.append((dotted, spec.origin))
    if filters and not out:
        # HARD usage error, same contract as a misspelled entrypoint
        # name: a typo'd CI filter must not silently guard nothing
        print(f"host-lint: no registered host module matches "
              f"{list(filters)}; registered: "
              + ", ".join(HOST_MODULES), file=sys.stderr)
        raise SystemExit(2)
    return out


def _run_rules(models: List[ModuleModel],
               disable: Sequence[str],
               keep_suppressed: bool = False) -> List[Finding]:
    ctx = LintContext(disable=disable, keep_suppressed=keep_suppressed)
    for rule in active_host_rules():
        for model in models:
            rule.check_module(model, ctx)
        rule.check_program(models, ctx)
    ctx.findings.sort(key=lambda f: (-severity_rank(f.severity),
                                     f.file or "", f.line or 0,
                                     f.rule_id))
    return ctx.findings


def host_check(modules: Optional[Sequence[Tuple[str, str]]] = None,
               disable: Sequence[str] = (),
               keep_suppressed: bool = False) -> List[Finding]:
    """Lint the registered host modules (or an explicit
    (name, path) list).  The whole set is analyzed together so the
    lock graph sees cross-module acquisition edges."""
    if modules is None:
        modules = resolve_host_modules()
    models = [analyze_host_module(path=path, name=name)
              for name, path in modules]
    return _run_rules(models, disable, keep_suppressed)


def host_check_sources(sources: Sequence[Tuple[str, str]],
                       disable: Sequence[str] = (),
                       files: Optional[Sequence[str]] = None
                       ) -> List[Finding]:
    """Lint (name, source) pairs — the same full path ``host_check``
    takes, for tests and the self-check mutants.  ``files`` optionally
    names on-disk twins so ``# tpu-lint: disable=`` resolution works."""
    models = []
    for i, (name, src) in enumerate(sources):
        path = files[i] if files else None
        models.append(analyze_host_module(path=path, source=src,
                                          name=name))
    return _run_rules(models, disable)


# ------------------------------------------------------------- self-check

_DEADLOCK_MUTANT = """
import threading

class Exchange:
    def __init__(self):
        self._book_lock = threading.Lock()
        self._fill_lock = threading.Lock()

    def place(self):
        with self._book_lock:
            with self._fill_lock:
                return 1

    def settle(self):
        with self._fill_lock:
            with self._book_lock:
                return 2
"""

_DEADLOCK_CLEAN = """
import threading

class Exchange:
    def __init__(self):
        self._book_lock = threading.Lock()
        self._fill_lock = threading.Lock()

    def place(self):
        with self._book_lock:
            with self._fill_lock:
                return 1

    def settle(self):
        with self._book_lock:
            with self._fill_lock:
                return 2
"""

_UNGUARDED_MUTANT = """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._depth = 0
        self._thread = threading.Thread(target=self._worker)
        self._thread.start()

    def _worker(self):
        while True:
            self._depth += 1

    def poll(self):
        with self._lock:
            return self._depth
"""

_UNGUARDED_CLEAN = """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._depth = 0
        self._thread = threading.Thread(target=self._worker)
        self._thread.start()

    def _worker(self):
        while True:
            with self._lock:
                self._depth += 1

    def poll(self):
        with self._lock:
            return self._depth
"""


def host_self_check() -> str:
    """Wiring smoke for the host family, run by ``--self-check``:
    a deadlock-cycle mutant and an unguarded-shared-write mutant must
    each fire EXACTLY once through the full ``host_check`` path, and
    their clean twins must stay quiet — so a refactor that silently
    stops building the thread model (or unregisters a rule) fails CI
    loudly instead of linting nothing."""
    required = {"unguarded-shared-write", "lock-order-cycle",
                "blocking-under-lock", "leaked-lock"}
    missing = required - set(HOST_RULES)
    if missing:
        raise RuntimeError(
            f"host-rule registry lost {sorted(missing)}")
    cases = [
        ("lock-order-cycle", _DEADLOCK_MUTANT, _DEADLOCK_CLEAN),
        ("unguarded-shared-write", _UNGUARDED_MUTANT,
         _UNGUARDED_CLEAN),
    ]
    for rule_id, mutant, clean in cases:
        got = host_check_sources([("mutant", mutant)])
        hits = [f for f in got if f.rule_id == rule_id]
        if len(hits) != 1 or len(got) != 1:
            raise RuntimeError(
                f"host self-check: {rule_id} mutant produced "
                f"{[f.rule_id for f in got]}, expected exactly one "
                f"{rule_id} finding")
        quiet = host_check_sources([("clean", clean)])
        if quiet:
            raise RuntimeError(
                f"host self-check: {rule_id} clean twin produced "
                f"{[f.rule_id for f in quiet]}, expected none")
    return ("host-rule self-check OK: deadlock-cycle and "
            "unguarded-write mutants each fired exactly once, "
            "clean twins quiet")
