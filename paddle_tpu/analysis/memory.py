"""Static per-shard HBM footprint estimation for lint entrypoints.

``paddle_tpu lint --memory`` answers, before any chip is booked: *how
many bytes does one shard of this entrypoint keep live at peak?*  The
estimate is computed from the traced jaxpr's avals divided by each
value's sharding factor (the product of the mesh-axis sizes its
PartitionSpec names) — params and KV/block pools enter through the
argument avals, transients through a last-use liveness scan over the
equations:

* a value is live from the equation that produces it to its last use
  (function outputs stay live to the end);
* ``pjit`` bodies are walked inline; ``while``/``scan``/``cond``
  bodies contribute their own internal peak on top of the live set at
  the call site (minus the carried operands already counted);
* an equation output's shard factor is the most conservative (min) of
  its input factors — intermediates are never assumed better-sharded
  than their inputs.

This is an ESTIMATE of the logical program, not XLA's allocator:
fusion removes materializations the scan counts, rematerialization
adds ones it cannot see.  It is deliberately stable across compiler
versions — that is what makes it a useful CI budget (checked-in
``analysis/budgets.json``, gated by ci.sh).  When the program also
compiles, :func:`estimate_target` attaches XLA's own
``memory_analysis()`` numbers for cross-reference.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax._src import core as jcore

from paddle_tpu.analysis.core import Finding, LintTarget

__all__ = ["MemoryReport", "aval_bytes", "shard_factor",
           "estimate_target", "load_budgets", "check_budgets"]


def aval_bytes(aval) -> int:
    """Bytes of one (unsharded) value.  Extended dtypes (PRNG keys)
    report their key-data size; anything unsized counts 0."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = getattr(dtype, "itemsize", 4)
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(itemsize)


def shard_factor(sharding) -> int:
    """How many ways a NamedSharding splits its value: the product of
    the mesh-axis sizes its spec names.  1 for replicated/None."""
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return 1
    from paddle_tpu.parallel.sharding import spec_axes
    f = 1
    for name in spec_axes(spec):
        f *= dict(zip(mesh.axis_names, mesh.devices.shape))[name]
    return max(1, f)


@dataclasses.dataclass
class MemoryReport:
    """Per-shard byte accounting for one entrypoint."""
    name: str
    mesh: str                       # "{'dp': 2}" or "single-device"
    shards: int
    args_bytes: int                 # params + pools + inputs, per shard
    out_bytes: int
    peak_bytes: int                 # liveness-scan peak, per shard
    largest_transient_bytes: int    # biggest single equation output
    xla: Optional[Dict[str, int]] = None   # memory_analysis(), if any
    # largest per-grid-step Pallas kernel VMEM footprint in the traced
    # program (kernel_rules.max_kernel_vmem; 0 = no pallas_call).
    # Separate ledger from peak_bytes on purpose: kernel working sets
    # live in VMEM under Mosaic's allocator, not HBM under XLA's — the
    # liveness scan keeps treating pallas_call as a leaf.
    kernel_vmem_bytes: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ----------------------------------------------------------- liveness scan


def _sub_jaxprs(eqn) -> List[Tuple[Any, List]]:
    """(inner jaxpr, outer operands feeding its invars) pairs for the
    control-flow primitives the scan recurses into."""
    prim, params = eqn.primitive.name, eqn.params
    out = []
    if prim == "pjit":
        inner = params["jaxpr"].jaxpr
        out.append((inner, list(eqn.invars)))
    elif prim == "while":
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        body = params["body_jaxpr"].jaxpr
        out.append((body, list(eqn.invars[cn:])))
    elif prim == "scan":
        inner = params["jaxpr"].jaxpr
        out.append((inner, list(eqn.invars)))
    elif prim == "cond":
        for br in params["branches"]:
            out.append((br.jaxpr, list(eqn.invars[1:])))
    elif prim in ("custom_jvp_call", "custom_vjp_call"):
        inner = params.get("call_jaxpr") or params.get("fun_jaxpr")
        if inner is not None:
            out.append((getattr(inner, "jaxpr", inner),
                        list(eqn.invars)))
    return out


def _peak(jaxpr, factors: Dict[int, int]) -> Tuple[int, int]:
    """(peak live bytes, largest single output) for one jaxpr under
    the given per-var shard factors (mutated with propagated entries).
    """
    def b(v) -> int:
        return aval_bytes(v.aval) // factors.get(id(v), 1)

    n = len(jaxpr.eqns)
    last: Dict[int, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last[id(v)] = i
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            last[id(v)] = n

    live: Dict[int, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        live[id(v)] = b(v)
    cur = sum(live.values())
    peak, largest = cur, 0

    for i, eqn in enumerate(jaxpr.eqns):
        in_f = [factors.get(id(v), 1) for v in eqn.invars
                if isinstance(v, jcore.Var)]
        out_f = min(in_f) if in_f else 1
        for v in eqn.outvars:
            factors.setdefault(id(v), out_f)

        inner_peak = 0
        for inner, operands in _sub_jaxprs(eqn):
            for outer, iv in zip(operands, inner.invars):
                if isinstance(outer, jcore.Var) and id(outer) in factors:
                    factors[id(iv)] = factors[id(outer)]
            ip, il = _peak(inner, factors)
            # the inner invars are the outer operands, already counted
            # in `cur` — only the inner EXTRA is new at this point
            extra = max(0, ip - sum(
                aval_bytes(v.aval) // factors.get(id(v), 1)
                for v in inner.invars))
            inner_peak = max(inner_peak, extra)
            largest = max(largest, il)

        out_bytes = sum(b(v) for v in eqn.outvars)
        largest = max(largest, out_bytes)
        if _sub_jaxprs(eqn):
            # a call-style eqn's outputs ARE the inner outvars: the
            # inner extra already covers the instant they materialize,
            # and by the time the call returns its transients are gone
            # — counting both at once would double the outputs
            peak = max(peak, cur + inner_peak, cur + out_bytes)
        else:
            peak = max(peak, cur + out_bytes)

        for v in eqn.outvars:
            if last.get(id(v), -1) > i:
                nb = b(v)
                live[id(v)] = nb
                cur += nb
        seen = set()
        for v in eqn.invars:
            if (isinstance(v, jcore.Var) and id(v) not in seen
                    and last.get(id(v)) == i and id(v) in live):
                cur -= live.pop(id(v))
                seen.add(id(v))
    return peak, largest


# ------------------------------------------------------------ entry points


def estimate_target(target: LintTarget, recipe=None, *,
                    with_xla: bool = True) -> MemoryReport:
    """Per-shard footprint of one entrypoint.  With a mesh recipe the
    argument factors come from the resolved in_shardings and the scan
    runs over the meshed program; recipe-less targets are a 1-shard
    estimate of the plain program."""
    from paddle_tpu.analysis import shard_rules as sr
    recipe = recipe or getattr(target, "recipe", None)
    mesh_desc, shards = "single-device", 1
    fn = target.fn
    arg_factors: List[int] = []

    flat_args = jax.tree_util.tree_leaves(target.args)
    if recipe is not None:
        mesh = sr.build_mesh(recipe)
        if mesh is not None:
            ins = sr.resolve_in_shardings(recipe, mesh, target.args)
            fn = jax.jit(target.fn, in_shardings=ins)
            arg_factors = [shard_factor(s)
                           for s in sr._leaf_shardings(ins)]
            mesh_desc, shards = str(dict(recipe.axes)), mesh.size

    closed = jax.make_jaxpr(fn)(*target.args, **target.kwargs)
    invars = closed.jaxpr.invars
    if len(arg_factors) != len(invars):
        arg_factors = [1] * len(invars)
    factors = {id(v): f for v, f in zip(invars, arg_factors)}

    peak, largest = _peak(closed.jaxpr, factors)
    args_bytes = sum(aval_bytes(v.aval) // f
                     for v, f in zip(invars, arg_factors))
    out_bytes = sum(aval_bytes(v.aval) // factors.get(id(v), 1)
                    for v in closed.jaxpr.outvars
                    if isinstance(v, jcore.Var))

    xla = None
    if with_xla and hasattr(fn, "lower"):
        try:
            from jax._src import config as _jconfig
            with _jconfig.threefry_partitionable(True):
                # same RNG stance as shard_check: meshed artifacts are
                # built the way a multi-chip deployment would build them
                ma = fn.lower(*target.args,
                              **target.kwargs).compile().memory_analysis()
            xla = {
                "argument_size_in_bytes":
                    int(ma.argument_size_in_bytes),
                "output_size_in_bytes": int(ma.output_size_in_bytes),
                "temp_size_in_bytes": int(ma.temp_size_in_bytes),
            }
        except Exception:
            xla = None
    from paddle_tpu.analysis.kernel_rules import max_kernel_vmem
    kernel_vmem = max_kernel_vmem(closed.jaxpr)

    _ = flat_args   # (leaves kept for future per-arg breakdowns)
    return MemoryReport(name=target.name, mesh=mesh_desc, shards=shards,
                        args_bytes=args_bytes, out_bytes=out_bytes,
                        peak_bytes=peak,
                        largest_transient_bytes=largest, xla=xla,
                        kernel_vmem_bytes=kernel_vmem)


# -------------------------------------------------------------- budget gate


def load_budgets(path: str) -> Dict[str, Dict[str, int]]:
    with open(path) as f:
        data = json.load(f)
    return {k: v for k, v in data.items() if not k.startswith("_")}


def check_budgets(reports: List[MemoryReport],
                  budgets: Dict[str, Dict[str, int]]) -> List[Finding]:
    """Error findings for every report over (or missing) its budget —
    the ci.sh memory gate.  A missing budget entry fails too: a new
    entrypoint must declare its footprint, that is the whole policy
    (docs/design/analysis.md)."""
    out = []

    class _B:                      # severity carrier for Finding rows
        rule_id, severity = "memory-budget", "error"

    for rep in reports:
        entry = budgets.get(rep.name)
        if entry is None:
            out.append(Finding(
                rule_id=_B.rule_id, severity=_B.severity, path=rep.name,
                message=f"no budget entry for {rep.name!r} in "
                        "budgets.json — add one (current peak "
                        f"{rep.peak_bytes} bytes/shard)",
                suggestion="add {\"%s\": {\"peak_bytes\": N}} with "
                           "headroom" % rep.name))
            continue
        budget = int(entry.get("peak_bytes", 0))
        if rep.peak_bytes > budget:
            out.append(Finding(
                rule_id=_B.rule_id, severity=_B.severity, path=rep.name,
                message=f"per-shard peak {rep.peak_bytes} bytes "
                        f"exceeds the checked-in budget {budget} — "
                        "an HBM regression this size would OOM the "
                        "serving slice before any measurement",
                suggestion="shrink the footprint, or raise the "
                           "budget in the SAME pr with the reason"))

        class _K:                  # kernel-VMEM twin of the gate above
            rule_id, severity = "kernel-vmem-budget", "error"

        if rep.kernel_vmem_bytes > 0:
            kv_budget = entry.get("kernel_vmem_bytes")
            if kv_budget is None:
                out.append(Finding(
                    rule_id=_K.rule_id, severity=_K.severity,
                    path=rep.name,
                    message=f"{rep.name!r} traces a pallas_call "
                            f"(derived per-grid-step VMEM "
                            f"{rep.kernel_vmem_bytes} bytes) but has "
                            "no kernel_vmem_bytes budget — a kernel-"
                            "bearing entrypoint must declare its VMEM "
                            "working set, same policy as peak_bytes",
                    suggestion="add \"kernel_vmem_bytes\": N to the "
                               f"{rep.name!r} entry in budgets.json"))
            elif rep.kernel_vmem_bytes > int(kv_budget):
                out.append(Finding(
                    rule_id=_K.rule_id, severity=_K.severity,
                    path=rep.name,
                    message=f"derived kernel VMEM "
                            f"{rep.kernel_vmem_bytes} bytes exceeds "
                            f"the checked-in {int(kv_budget)} — a "
                            "working-set regression this size moves "
                            "the supported-shape envelope "
                            "(paged_attention_supported) on a real "
                            "chip",
                    suggestion="shrink the block/group working set, "
                               "or raise the budget in the SAME pr "
                               "with the reason"))
    return out
