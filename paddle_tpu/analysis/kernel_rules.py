"""Kernel-scoped lint rules: tpu-lint's descent into ``pallas_call``.

Since PR 6 the jaxpr walker early-returned at ``pallas_call`` — the
ragged paged-attention kernel, the single hottest program in the repo,
was the one region the static analyzer could not see.  Its VMEM budget
was guarded only by the hand-maintained ``_paged_vmem_bytes``
estimator and interpret-mode tests.  This module is the descent: a
traced ``pallas_call`` equation carries everything the kernel contract
needs statically — the kernel body jaxpr, the grid, every BlockSpec's
block shape and index-map jaxpr, the scalar-prefetch operand count,
and the scratch avals — so the contract is PROVED from the trace
instead of hand-mirrored.

The family (all ``error`` severity — each one is a correctness or OOM
trap, not an advisory):

==========================  ==========================================
rule                        fires when
==========================  ==========================================
vmem-budget                 the per-grid-step VMEM footprint DERIVED
                            from block shapes + scratch avals exceeds
                            the resident budget, or (for the repo's
                            paged kernel) disagrees with
                            ``_paged_vmem_bytes`` — estimator drift
                            becomes a lint error, per entrypoint,
                            including the int8 5 B/elt arm
scratch-accum-dtype         an online-softmax / dot accumulator lives
                            in bf16/f16 — VMEM scratch avals and
                            in-kernel ``dot_general`` outputs must be
                            f32 even when the pools are bf16/int8
oob-index-map               a BlockSpec index map, evaluated in
                            interval arithmetic over the grid bounds,
                            can address past the operand's extent —
                            or a TABLE-GATHERED map's scalar-prefetch
                            operand has no clamp proof at the call
                            site (the bug class the ``-1``
                            tail-sentinel clip protects against)
masking-completeness        a softmax ``exp`` consumes data loaded
                            from a gathered page with no
                            ``kpos < lengths[r]+j+1``-shaped predicate
                            anywhere on its dataflow — the unmasked-
                            garbage-lane silent-wrong-answer bug
                            interpret tests miss at untested shapes
==========================  ==========================================

Each rule reports AT MOST ONE finding per ``pallas_call`` (violations
are aggregated into the message): the units of review are kernels, not
the dozens of taint paths a single dropped predicate poisons.

What is PROVED vs. TESTED (docs/design/analysis.md has the worked
examples): affine index maps are proved in-bounds or proved violating
by interval arithmetic over the grid corners — an interval the
arithmetic cannot bound stays QUIET (no false fires on exotic affine
maps).  Gathered maps invert the burden: their index is runtime table
data, so the rule DEMANDS a clamp proof on the operand's producer
chain (descending ``jnp.clip``'s ``pjit`` wrapper to its ``max``/
``min``/``clamp`` bounds) and errors when none exists.  Masking and
scratch dtypes are taint/aval proofs over the kernel jaxpr.  Numeric
parity with the XLA fallback remains the interpret-mode test suite's
job — lint proves shape/dataflow contracts, not values.

The XLA-HBM rule family (``rules.py``) still skips kernel bodies: a
kernel's ref indexing would false-fire gather-in-decode, and the HBM
liveness estimator keeps treating ``pallas_call`` as a leaf (kernel
VMEM is Mosaic's ledger — surfaced separately as
``MemoryReport.kernel_vmem_bytes`` and gated by ``budgets.json``'s
``kernel_vmem_bytes`` keys).  ``lint(..., opaque_kernels=True)``
restores the old skip for third-party kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

import numpy as np
from jax._src import core as jcore

__all__ = ["KernelRule", "KERNEL_RULES", "register_kernel_rule",
           "active_kernel_rules", "KernelAnalysis", "analyze_pallas_call",
           "check_pallas_call", "iter_pallas_calls", "derive_kernel_vmem",
           "max_kernel_vmem", "kernel_self_check"]


def _ppa():
    """The paged-attention kernel module, looked up LIVE at check time:
    the drift rule must see a monkeypatched ``_paged_vmem_bytes`` (the
    poison-the-estimator test), so nothing from it is bound at import.
    """
    from paddle_tpu.ops import pallas_paged_attention
    return pallas_paged_attention


class KernelRule:
    rule_id: str = ""
    severity: str = "error"
    family: str = "kernel"
    doc: str = ""

    def check_kernel(self, ka: "KernelAnalysis", state, ctx) -> None:
        raise NotImplementedError


KERNEL_RULES: Dict[str, type] = {}


def register_kernel_rule(cls):
    assert cls.rule_id and cls.rule_id not in KERNEL_RULES, cls
    KERNEL_RULES[cls.rule_id] = cls
    return cls


def active_kernel_rules() -> List[KernelRule]:
    return [cls() for cls in KERNEL_RULES.values()]


# ------------------------------------------------------- interval arithmetic
#
# Intervals are (lo, hi) pairs of ints; None on a side means unbounded.
# The arithmetic is deliberately conservative: anything it cannot bound
# widens to unknown, and the rules only act on what IS bounded (affine
# proofs) or on the gathered/unproven combination (clamp demands).

_UNKNOWN: Tuple[Optional[int], Optional[int]] = (None, None)


def _const_interval(val) -> Tuple[Optional[int], Optional[int]]:
    try:
        arr = np.asarray(val)
        if arr.size == 0 or arr.dtype.kind not in "iub":
            return _UNKNOWN
        return (int(arr.min()), int(arr.max()))
    except Exception:
        return _UNKNOWN


def _ivl_max(a, b):
    los = [x for x in (a[0], b[0]) if x is not None]
    lo = max(los) if los else None
    hi = (None if a[1] is None or b[1] is None else max(a[1], b[1]))
    return (lo, hi)


def _ivl_min(a, b):
    his = [x for x in (a[1], b[1]) if x is not None]
    hi = min(his) if his else None
    lo = (None if a[0] is None or b[0] is None else min(a[0], b[0]))
    return (lo, hi)


def _ivl_add(a, b):
    return (None if a[0] is None or b[0] is None else a[0] + b[0],
            None if a[1] is None or b[1] is None else a[1] + b[1])


def _ivl_sub(a, b):
    return (None if a[0] is None or b[1] is None else a[0] - b[1],
            None if a[1] is None or b[0] is None else a[1] - b[0])


def _ivl_mul(a, b):
    if None in a or None in b:
        return _UNKNOWN
    corners = [a[i] * b[j] for i in (0, 1) for j in (0, 1)]
    return (min(corners), max(corners))


def _combine(prim: str, ivs) -> Tuple[Optional[int], Optional[int]]:
    if prim == "add":
        return _ivl_add(ivs[0], ivs[1])
    if prim == "sub":
        return _ivl_sub(ivs[0], ivs[1])
    if prim == "mul":
        return _ivl_mul(ivs[0], ivs[1])
    if prim == "max":
        return _ivl_max(ivs[0], ivs[1])
    if prim == "min":
        return _ivl_min(ivs[0], ivs[1])
    if prim == "clamp":
        # clamp(min, x, max): each declared bound caps its side even
        # when x itself is unbounded — exactly the table-clip proof
        mn, x, mx = ivs
        return (mn[0] if mn[0] is not None else x[0],
                mx[1] if mx[1] is not None else x[1])
    if prim == "rem":
        a, b = ivs
        if (b[0] is not None and b[0] > 0 and b[1] is not None
                and a[0] is not None and a[0] >= 0):
            return (0, b[1] - 1)
        return _UNKNOWN
    return _UNKNOWN


def _producers(jaxpr) -> Dict[int, Any]:
    out: Dict[int, Any] = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[id(v)] = eqn
    return out


# value-preserving wrappers the producer walk looks through
_PASSTHROUGH = ("convert_element_type", "copy", "reshape", "squeeze",
                "broadcast_in_dim", "stop_gradient", "device_put")


def _value_interval(var, producers: Dict[int, Any],
                    env: Dict[int, Tuple], depth: int = 0):
    """Best-effort integer interval of one value inside a jaxpr, walking
    producer chains through ``pjit`` bodies (``jnp.clip`` traces as
    ``pjit:clip`` around ``max``/``min``) up to a small depth."""
    if isinstance(var, jcore.Literal):
        return _const_interval(var.val)
    if id(var) in env:
        return env[id(var)]
    if depth > 16:
        return _UNKNOWN
    eqn = producers.get(id(var))
    if eqn is None:
        return _UNKNOWN
    prim = eqn.primitive.name
    if prim in _PASSTHROUGH:
        return _value_interval(eqn.invars[0], producers, env, depth + 1)
    if prim == "pjit":
        inner = eqn.params["jaxpr"].jaxpr
        ienv = {id(iv): _value_interval(ov, producers, env, depth + 1)
                for ov, iv in zip(eqn.invars, inner.invars)}
        k = next((i for i, ov in enumerate(eqn.outvars) if ov is var),
                 None)
        if k is None or k >= len(inner.outvars):
            return _UNKNOWN
        return _value_interval(inner.outvars[k], _producers(inner),
                               ienv, depth + 1)
    if prim == "iota":
        dim = eqn.params.get("dimension", 0)
        shape = eqn.params.get("shape") or getattr(
            eqn.outvars[0].aval, "shape", ())
        try:
            return (0, max(0, int(shape[dim]) - 1))
        except Exception:
            return _UNKNOWN
    if prim in ("add", "sub", "mul", "max", "min", "clamp", "rem"):
        ivs = [_value_interval(v, producers, env, depth + 1)
               for v in eqn.invars]
        return _combine(prim, ivs)
    return _UNKNOWN


# ----------------------------------------------------------- the analysis


@dataclasses.dataclass
class KernelAnalysis:
    """Everything a kernel rule reads from one traced ``pallas_call``:
    the kernel body jaxpr, the grid, the per-operand block mappings
    (with index-map jaxprs), scratch avals, and which INPUTS are
    table-GATHERED (their index map reads a scalar-prefetch ref) —
    the distinction the VMEM charging, masking, and OOB proofs all
    pivot on."""
    eqn: Any                        # the pallas_call eqn
    enclosing_jaxpr: Any            # jaxpr containing it (clamp proofs)
    name: str                       # kernel fn name (name_and_src_info)
    jaxpr: Any                      # kernel body Jaxpr
    grid: Tuple[int, ...]
    num_prefetch: int
    num_inputs: int
    num_outputs: int
    in_block_mappings: Tuple
    out_block_mappings: Tuple
    scratch_avals: Tuple
    gathered_inputs: FrozenSet[int]   # input indices fetched by table

    def input_aval(self, i: int):
        return self.eqn.invars[self.num_prefetch + i].aval

    @property
    def prefetch_ref_ids(self) -> FrozenSet[int]:
        return frozenset(id(v)
                         for v in self.jaxpr.invars[:self.num_prefetch])

    @property
    def gathered_ref_ids(self) -> FrozenSet[int]:
        return frozenset(id(self.jaxpr.invars[self.num_prefetch + i])
                         for i in self.gathered_inputs)


def _index_map_reads_prefetch(imj, n_grid: int) -> bool:
    prefetch_ids = {id(v) for v in imj.invars[n_grid:]}
    return any(e.primitive.name == "get" and e.invars
               and id(e.invars[0]) in prefetch_ids for e in imj.eqns)


def analyze_pallas_call(eqn, enclosing_jaxpr) -> Optional[KernelAnalysis]:
    """Pull the kernel contract out of a traced ``pallas_call``; None
    when the metadata this jax version exposes does not match (the
    rules then skip rather than crash the gate)."""
    try:
        params = eqn.params
        gm = params["grid_mapping"]
        body = params["jaxpr"]
        body = getattr(body, "jaxpr", body)
        grid = tuple(int(g) for g in gm.grid)
        np_, ni, no = (int(gm.num_index_operands), int(gm.num_inputs),
                       int(gm.num_outputs))
        bms = tuple(gm.block_mappings)
        in_bms, out_bms = bms[:ni], bms[ni:ni + no]
        scratch = tuple(v.aval
                        for v in body.invars[np_ + ni + no:])
        gathered = frozenset(
            i for i, bm in enumerate(in_bms)
            if _index_map_reads_prefetch(bm.index_map_jaxpr.jaxpr,
                                         len(grid)))
        name = str(params.get("name_and_src_info", "")).split(" at ")[0]
        return KernelAnalysis(
            eqn=eqn, enclosing_jaxpr=enclosing_jaxpr, name=name or "?",
            jaxpr=body, grid=grid, num_prefetch=np_, num_inputs=ni,
            num_outputs=no, in_block_mappings=in_bms,
            out_block_mappings=out_bms, scratch_avals=scratch,
            gathered_inputs=gathered)
    except Exception:
        return None


def check_pallas_call(eqn, state, ctx, enclosing_jaxpr,
                      rules: Optional[List[KernelRule]] = None) -> None:
    """Entry point from ``core._descend``: run the kernel family over
    one traced ``pallas_call``."""
    ka = analyze_pallas_call(eqn, enclosing_jaxpr)
    if ka is None:
        return
    for rule in (active_kernel_rules() if rules is None else rules):
        rule.check_kernel(ka, state, ctx)


def iter_pallas_calls(jaxpr):
    """Yield ``(pallas_call eqn, enclosing jaxpr)`` pairs from a jaxpr
    tree, recursing through every jaxpr-valued equation param (pjit,
    while/scan/cond, shard_map, remat, ...)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            yield eqn, jaxpr
        for val in (eqn.params or {}).values():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v in vals:
                if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield from iter_pallas_calls(getattr(v, "jaxpr", v))


# --------------------------------------------------------- VMEM derivation


def _block_elems(block_shape) -> int:
    n = 1
    for d in block_shape:
        n *= 1 if d is None else int(d)
    return n


def _per_elt_streamed(dtype) -> int:
    """Bytes/element CHARGED for a double-buffered streamed block —
    deliberately the same policy ``_paged_vmem_bytes`` documents (bf16
    tiles stage through unpacked copies: 6; int8 streams 1 packed byte
    plus a 4-byte f32 dequant staging copy: 5; else 4).  The policy is
    duplicated here ON PURPOSE: deriving both sides from shared code
    would make estimator drift undetectable — disagreement IS the
    signal the vmem-budget rule exists for."""
    dt = np.dtype(dtype)
    if dt == np.dtype("bfloat16") if hasattr(np, "bfloat16") else False:
        return 6   # pragma: no cover - numpy lacks bfloat16 natively
    if str(dt) == "bfloat16" or dtype == "bfloat16":
        return 6
    if dt.itemsize == 1:
        return 5
    return 4


def derive_kernel_vmem(ka: KernelAnalysis) -> int:
    """Per-grid-step resident VMEM bytes derived from the traced kernel:
    gathered inputs stream double-buffered at the dtype's charge rate,
    non-gathered inputs and outputs stage double-buffered f32 (4 B),
    scratch counts its aval bytes verbatim."""
    total = 0
    for i, bm in enumerate(ka.in_block_mappings):
        elems = _block_elems(bm.block_shape)
        if i in ka.gathered_inputs:
            dtype = getattr(ka.input_aval(i), "dtype", np.float32)
            total += 2 * elems * _per_elt_streamed(dtype)
        else:
            total += 2 * elems * 4
    for bm in ka.out_block_mappings:
        total += 2 * _block_elems(bm.block_shape) * 4
    for aval in ka.scratch_avals:
        shape = getattr(aval, "shape", ())
        dtype = getattr(aval, "dtype", np.float32)
        try:
            itemsize = np.dtype(dtype).itemsize
        except TypeError:
            itemsize = 2 if str(dtype) == "bfloat16" else 4
        n = 1
        for d in shape:
            n *= int(d)
        total += n * itemsize
    return total


def max_kernel_vmem(jaxpr) -> int:
    """Largest derived kernel footprint over every ``pallas_call`` in a
    jaxpr tree (0 when there is none) — what ``memory.py`` surfaces as
    ``MemoryReport.kernel_vmem_bytes`` and ``budgets.json`` gates."""
    best = 0
    for eqn, encl in iter_pallas_calls(jaxpr):
        ka = analyze_pallas_call(eqn, encl)
        if ka is not None:
            best = max(best, derive_kernel_vmem(ka))
    return best


# ----------------------------------------------------------------- rules


@register_kernel_rule
class KernelVmemBudgetRule(KernelRule):
    """The derived footprint must fit the resident budget, and — for the
    repo's ragged paged-attention kernel — must EQUAL what
    ``_paged_vmem_bytes`` predicts for the same (block_size, group,
    head_dim, kv_dtype, max_q).  The hand estimator gates dispatch
    (``paged_attention_supported``); if it drifts from the traced
    kernel it silently mis-sizes the fallback envelope, so drift is an
    error per entrypoint — bf16's 6 B/elt and int8's 5 B/elt arms
    included."""

    rule_id = "vmem-budget"
    severity = "error"
    doc = ("kernel VMEM footprint derived from BlockSpecs/scratch "
           "exceeds the resident budget, or drifts from "
           "_paged_vmem_bytes on the paged kernel")

    def check_kernel(self, ka, state, ctx):
        ppa = _ppa()
        derived = derive_kernel_vmem(ka)
        budget = int(ppa._PAGED_RESIDENT_BUDGET)
        problems = []
        if derived > budget:
            problems.append(
                f"derived per-grid-step VMEM {derived} B exceeds the "
                f"resident budget {budget} B — Mosaic would OOM at "
                "compile time on a real chip")
        if (ka.name == ppa.PAGED_KERNEL_NAME and ka.gathered_inputs
                and len(ka.in_block_mappings) >= 2):
            gi = min(ka.gathered_inputs)
            kv_bs = ka.in_block_mappings[gi].block_shape
            qi = next((i for i in range(len(ka.in_block_mappings))
                       if i not in ka.gathered_inputs), None)
            if qi is not None and len(kv_bs) == 4:
                q_bs = ka.in_block_mappings[qi].block_shape
                bs, g, hd = int(kv_bs[1]), int(kv_bs[2]), int(kv_bs[3])
                tq = int(q_bs[1])
                kv_dtype = getattr(ka.input_aval(gi), "dtype",
                                   np.float32)
                est = int(ppa._paged_vmem_bytes(bs, g, hd, kv_dtype,
                                                tq))
                if est != derived:
                    problems.append(
                        f"estimator drift: _paged_vmem_bytes(block_size"
                        f"={bs}, group={g}, head_dim={hd}, kv_dtype="
                        f"{np.dtype(kv_dtype) if not isinstance(kv_dtype, str) else kv_dtype}, "
                        f"max_q={tq}) says {est} B but the traced "
                        f"kernel derives {derived} B — the dispatch "
                        "envelope (paged_attention_supported) is "
                        "sized by a number the kernel no longer "
                        "matches")
        if problems:
            ctx.report(
                self, f"{state.path}/pallas_call:{ka.name}",
                "; ".join(problems), eqn=ka.eqn,
                suggestion="re-anchor _paged_vmem_bytes to the kernel's "
                           "actual blocks/scratch (they must agree "
                           "exactly), or shrink the head group / block "
                           "size until the working set fits")


@register_kernel_rule
class KernelScratchDtypeRule(KernelRule):
    """The in-kernel twin of ``accum-dtype``: online-softmax state
    (running max / sum / acc in VMEM scratch) and ``dot_general``
    accumulators must be f32 even when the streamed pools are
    bf16/int8 — a bf16 accumulator re-rounds every page merge and the
    error grows with sequence length, the silent-precision-loss class
    PR 1 fixed in the XLA form."""

    rule_id = "scratch-accum-dtype"
    severity = "error"
    doc = ("bf16/f16 VMEM scratch accumulator or in-kernel dot "
           "accumulating in a narrow float")

    _NARROW = ("bfloat16", "float16")

    def _dtype_name(self, dtype) -> str:
        try:
            return np.dtype(dtype).name
        except TypeError:
            return str(dtype)

    def check_kernel(self, ka, state, ctx):
        problems = []
        for k, aval in enumerate(ka.scratch_avals):
            dn = self._dtype_name(getattr(aval, "dtype", None))
            if dn in self._NARROW:
                shape = tuple(getattr(aval, "shape", ()))
                problems.append(f"scratch ref #{k} ({dn}{shape}) "
                                "accumulates across the grid in a "
                                "narrow float")
        for eqn in _flat_eqns(ka.jaxpr):
            if eqn.primitive.name != "dot_general":
                continue
            dn = self._dtype_name(getattr(eqn.outvars[0].aval, "dtype",
                                          None))
            if dn in self._NARROW:
                problems.append(
                    f"in-kernel dot_general accumulates in {dn}")
        if problems:
            ctx.report(
                self, f"{state.path}/pallas_call:{ka.name}",
                "; ".join(problems), eqn=ka.eqn,
                suggestion="keep softmax state and dot accumulators in "
                           "f32 (pltpu.VMEM(..., jnp.float32), "
                           "preferred_element_type=jnp.float32); "
                           "downcast once, at the output write")


@register_kernel_rule
class KernelOobIndexMapRule(KernelRule):
    """Evaluate every BlockSpec index map symbolically over the grid
    bounds.  An AFFINE map is an error only when a corner PROVABLY
    addresses past the operand ((hi+1) * block_size > extent, or a
    negative block index); an interval the arithmetic cannot bound
    stays quiet.  A table-GATHERED map inverts the burden: its index is
    runtime data, so the scalar-prefetch operand feeding it must carry
    a clamp proof on its producer chain (the ``jnp.clip(table, 0,
    nb-1)`` every caller ships — the ``-1`` tail-sentinel class) whose
    bounds fit the pool; no proof is an error."""

    rule_id = "oob-index-map"
    severity = "error"
    doc = ("BlockSpec index map can address past the operand extent, "
           "or a gathered map's table operand lacks a clamp proof")

    def check_kernel(self, ka, state, ctx):
        outer_prods = _producers(ka.enclosing_jaxpr)

        def prefetch_bound(k: int):
            if k >= len(ka.eqn.invars):
                return _UNKNOWN
            return _value_interval(ka.eqn.invars[k], outer_prods, {})

        problems = []
        all_bms = (list(enumerate(ka.in_block_mappings))
                   + [(ka.num_inputs + j, bm)
                      for j, bm in enumerate(ka.out_block_mappings)])
        for oi, bm in all_bms:
            imj = bm.index_map_jaxpr.jaxpr
            extents = tuple(bm.array_shape_dtype.shape)
            label = (f"input {oi}" if oi < ka.num_inputs
                     else f"output {oi - ka.num_inputs}")
            results = self._eval_map(imj, ka.grid, prefetch_bound)
            for dim, ((lo, hi), gathered) in enumerate(results):
                if dim >= len(extents):
                    break
                bs_d = bm.block_shape[dim]
                span = 1 if bs_d is None else int(bs_d)
                ext = int(extents[dim])
                if lo is not None and hi is not None:
                    if lo < 0 or (hi + 1) * span > ext:
                        problems.append(
                            f"{label} dim {dim}: block index in "
                            f"[{lo}, {hi}] x block {span} can address "
                            f"past extent {ext}")
                elif gathered:
                    problems.append(
                        f"{label} dim {dim}: table-gathered block "
                        "index has no clamp proof at the call site — "
                        "a -1 (unmapped) or stale table entry would "
                        "fetch out of the pool")
        if problems:
            ctx.report(
                self, f"{state.path}/pallas_call:{ka.name}",
                "; ".join(problems), eqn=ka.eqn,
                suggestion="clip the block table at the call site "
                           "(jnp.clip(table, 0, num_blocks - 1), as "
                           "paged_ragged_attention_kernel does) and "
                           "keep affine maps inside the operand "
                           "extent at every grid corner")

    @staticmethod
    def _eval_map(imj, grid, prefetch_bound: Callable[[int], Tuple]):
        """Evaluate an index-map jaxpr over grid-corner intervals;
        returns per-output ``((lo, hi), gathered)``."""
        vals: Dict[int, Tuple] = {}     # var id -> ((lo, hi), gathered)
        ref_k: Dict[int, int] = {}      # var id of prefetch ref -> index
        n_grid = len(grid)
        for i, iv in enumerate(imj.invars):
            if i < n_grid:
                vals[id(iv)] = ((0, max(0, grid[i] - 1)), False)
            else:
                ref_k[id(iv)] = i - n_grid

        def read(v):
            if isinstance(v, jcore.Literal):
                return (_const_interval(v.val), False)
            return vals.get(id(v), (_UNKNOWN, False))

        for eqn in imj.eqns:
            prim = eqn.primitive.name
            if (prim == "get" and eqn.invars
                    and id(eqn.invars[0]) in ref_k):
                out = (prefetch_bound(ref_k[id(eqn.invars[0])]), True)
            else:
                ins = [read(v) for v in eqn.invars]
                gathered = any(g for _, g in ins)
                if prim in _PASSTHROUGH:
                    out = (ins[0][0] if ins else _UNKNOWN, gathered)
                elif prim in ("add", "sub", "mul", "max", "min",
                              "clamp", "rem"):
                    out = (_combine(prim, [iv for iv, _ in ins]),
                           gathered)
                else:
                    out = (_UNKNOWN, gathered)
            for ov in eqn.outvars:
                vals[id(ov)] = out
        return [read(ov) for ov in imj.outvars]


@register_kernel_rule
class KernelMaskingRule(KernelRule):
    """Every softmax ``exp`` that consumes gathered-page data must be
    dominated by a length-bound predicate: the rule taints (a) values
    loaded from table-gathered input refs (K/V page tiles), (b) values
    derived from scalar-prefetch SMEM reads (the per-row ``lengths``),
    and (c) outputs of comparisons whose operands derive from (b) —
    the ``kpos < lengths[r]+j+1`` shape.  An ``exp`` whose input is
    (a)-tainted but not (c)-tainted consumes unmasked garbage lanes —
    positions past the row's bound, unwritten pages behind ``-1``
    table entries — and the softmax silently weights them.  Taint
    flows through VMEM scratch (``swap`` marks the ref), so one
    dropped predicate poisons the whole online-softmax chain: the rule
    aggregates to ONE finding per kernel."""

    rule_id = "masking-completeness"
    severity = "error"
    doc = ("softmax exp consumes gathered-page data with no "
           "length-bound predicate on its dataflow")

    _CMP = ("lt", "le", "gt", "ge")

    def check_kernel(self, ka, state, ctx):
        if not ka.gathered_inputs:
            return
        tk: set = set()    # gathered-K/V taint
        tm: set = set()    # mask-predicate taint
        ts: set = set()    # scalar-prefetch-derived taint (lengths)
        seed = {}
        for vid in ka.gathered_ref_ids:
            seed[vid] = {"gathered_ref"}
        for vid in ka.prefetch_ref_ids:
            seed.setdefault(vid, set()).add("smem_ref")
        unmasked = self._walk(ka.jaxpr, tk, tm, ts, seed)
        if unmasked:
            ctx.report(
                self, f"{state.path}/pallas_call:{ka.name}",
                f"{unmasked} softmax exp(s) consume data loaded from "
                "gathered pages with NO length-bound predicate "
                "anywhere on their dataflow — garbage tail lanes and "
                "unwritten pages get nonzero weight (the silent-"
                "wrong-answer class interpret tests miss at untested "
                "shapes)", eqn=ka.eqn,
                suggestion="apply the per-query causal bound before "
                           "the softmax: bias = where(kpos < "
                           "lengths[r] + j + 1, 0, NEG_INF), added to "
                           "the scores ahead of every exp")

    def _walk(self, jaxpr, tk, tm, ts, refs: Dict[int, set]) -> int:
        """Forward taint propagation over one (sub-)jaxpr; returns the
        count of K-tainted-but-unmasked ``exp`` eqns.  ``refs`` maps
        ref-var ids to their roles; ``swap`` writes taint INTO a ref,
        ``get`` reads it back out."""
        unmasked = 0
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            invars = [v for v in eqn.invars if isinstance(v, jcore.Var)]
            k_in = any(id(v) in tk for v in invars)
            m_in = any(id(v) in tm for v in invars)
            s_in = any(id(v) in ts for v in invars)
            if prim == "get" and eqn.invars:
                roles = refs.get(id(eqn.invars[0]), ())
                if "gathered_ref" in roles:
                    k_in = True
                if "smem_ref" in roles:
                    s_in = True
            if prim in self._CMP and s_in:
                m_in = True
            if prim == "swap" and eqn.invars:
                # writing a tainted value into a ref taints the ref
                # itself: later reads (next group iteration's m_prev/
                # acc) inherit it
                rid = id(eqn.invars[0])
                if k_in:
                    tk.add(rid)
                if m_in:
                    tm.add(rid)
                if s_in:
                    ts.add(rid)
            if prim == "exp" and k_in and not m_in:
                unmasked += 1
            # recurse into sub-jaxprs (pl.when conds, where pjits)
            # with taints mapped across the boundary both ways
            unmasked += self._descend(eqn, tk, tm, ts, refs)
            for ov in eqn.outvars:
                if k_in:
                    tk.add(id(ov))
                if m_in:
                    tm.add(id(ov))
                if s_in:
                    ts.add(id(ov))
        return unmasked

    def _descend(self, eqn, tk, tm, ts, refs) -> int:
        inners = []
        prim = eqn.primitive.name
        params = eqn.params or {}
        if prim == "cond":
            inners = [(getattr(b, "jaxpr", b), list(eqn.invars[1:]))
                      for b in params.get("branches", ())]
        else:
            for val in params.values():
                vals = val if isinstance(val, (tuple, list)) else (val,)
                for v in vals:
                    if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                        inners.append((getattr(v, "jaxpr", v),
                                       list(eqn.invars)))
        total = 0
        for inner, operands in inners:
            imap = list(zip(operands, inner.invars))
            for ov, iv in imap:
                if not isinstance(ov, jcore.Var):
                    continue
                if id(ov) in tk:
                    tk.add(id(iv))
                if id(ov) in tm:
                    tm.add(id(iv))
                if id(ov) in ts:
                    ts.add(id(iv))
                if id(ov) in refs:
                    refs[id(iv)] = refs[id(ov)]
            total += self._walk(inner, tk, tm, ts, refs)
            # ref mutations inside the branch surface to the caller
            for ov, iv in imap:
                if not isinstance(ov, jcore.Var):
                    continue
                if id(iv) in tk:
                    tk.add(id(ov))
                if id(iv) in tm:
                    tm.add(id(ov))
                if id(iv) in ts:
                    ts.add(id(ov))
            for ov, iv in zip(eqn.outvars, inner.outvars):
                if isinstance(iv, jcore.Var):
                    if id(iv) in tk:
                        tk.add(id(ov))
                    if id(iv) in tm:
                        tm.add(id(ov))
                    if id(iv) in ts:
                        ts.add(id(ov))
        return total


def _flat_eqns(jaxpr):
    """All equations of a jaxpr tree, sub-jaxprs inlined (order
    preserved within each body)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in (eqn.params or {}).values():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v in vals:
                if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield from _flat_eqns(getattr(v, "jaxpr", v))


# ------------------------------------------------------------- smoke check


def kernel_self_check() -> str:
    """Registry wiring smoke for ``--self-check``: the four kernel
    rules must be registered, a deliberately-OOB mutant kernel must
    produce exactly the oob finding through the full ``lint()`` path
    (proving ``core._descend`` actually opens ``pallas_call``), and a
    clean copy kernel must produce none.  Raises on any break — the
    CLI converts that into an error finding so a wiring regression
    fails the gate fast, before any entrypoint traces."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.analysis.core import lint

    required = {"vmem-budget", "scratch-accum-dtype", "oob-index-map",
                "masking-completeness"}
    missing = required - set(KERNEL_RULES)
    if missing:
        raise RuntimeError(
            f"kernel rule registry is missing {sorted(missing)} — "
            "kernel_rules.py registration broke")

    def _copy(index_map):
        def fn(x):
            return pl.pallas_call(
                lambda x_ref, o_ref: o_ref.__setitem__(
                    slice(None), x_ref[:]),
                grid=(2,),
                in_specs=[pl.BlockSpec((4,), index_map)],
                out_specs=pl.BlockSpec((4,), lambda i: (i,)),
                out_shape=jax.ShapeDtypeStruct((8,), jnp.float32),
                interpret=True)(x)
        return fn

    x = jnp.zeros((8,), jnp.float32)
    bad = lint(_copy(lambda i: (i + 1,)), (x,), name="kernel-smoke-bad")
    oob = [f for f in bad if f.rule_id == "oob-index-map"]
    if len(oob) != 1:
        raise RuntimeError(
            "kernel-rule smoke: the OOB mutant kernel produced "
            f"{len(oob)} oob-index-map finding(s), expected exactly 1 "
            "— core.py is no longer descending into pallas_call")
    good = lint(_copy(lambda i: (i,)), (x,), name="kernel-smoke-good")
    noisy = [f for f in good if f.rule_id in KERNEL_RULES]
    if noisy:
        raise RuntimeError(
            "kernel-rule smoke: the clean copy kernel produced "
            f"{[(f.rule_id, f.message) for f in noisy]}")
    return (f"kernel-rule smoke OK ({len(KERNEL_RULES)} kernel rules "
            "registered; oob mutant fires, clean kernel quiet)")
