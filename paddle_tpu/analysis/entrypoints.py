"""Registered lint entrypoints: the jitted programs this repo ships.

``python -m paddle_tpu.analysis --self-check`` runs the full rule
registry over every entrypoint here — the trainer step, the dense and
paged serve decode steps, the eval step, and the continuous-batching
engine's decode step.  Each factory builds a TINY model (the lint is a
property of the PROGRAM STRUCTURE, not the dimensions: a 1-layer
16-dim transformer traces the same equation graph as the production
config) and returns a :class:`~paddle_tpu.analysis.core.LintTarget`.
Nothing executes — entrypoints are traced/lowered only, so the
self-check runs in CI's lint tier on the CPU backend.

Register project-specific entrypoints with::

    from paddle_tpu.analysis import register_entrypoint, LintTarget

    @register_entrypoint("my-step")
    def _target():
        return LintTarget("my-step", my_jitted_fn, (example_args,))

and the CI gate covers them from then on.

Entrypoints that ship with a mesh layout also carry a
:class:`~paddle_tpu.analysis.shard_rules.ShardRecipe` — then
``--self-check`` additionally lowers them under a real >=2-device CPU
mesh and runs the SPMD rule family (shard_rules.py), and ``--memory``
reports per-shard bytes under that mesh.  The trainer/dense-serve
recipes are DATA-PARALLEL: batch/slot-major args shard on ``dp``
(declared by the serving builders via ``_lint_batch_args`` /
``_decode_slot_args``), params replicate — a naive tensor-parallel
recipe would put a per-layer all-reduce inside the decode while body,
the exact shape ``collective-in-decode`` exists to reject.  The
mesh-native paged step entrypoints (``paged-serve-step*``,
``paged-engine-step-*``) instead carry HEAD-SHARDED recipes matching
serving.py's ``mesh=`` knob: the KV block pools split on the head
axis, bookkeeping replicates, and ``decode_collectives`` contracts
the decode body to exactly the attention-output all-gather — the rule
fails on any extra collective AND on the declared combine going
missing.  Recipe-less entrypoints lint single-device exactly as
before.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.analysis.core import LintTarget

__all__ = ["register_entrypoint", "ENTRYPOINTS", "self_check_targets"]

ENTRYPOINTS: Dict[str, Callable[[], LintTarget]] = {}


def register_entrypoint(name: str):
    def deco(factory: Callable[[], LintTarget]):
        assert name not in ENTRYPOINTS, f"duplicate entrypoint {name}"
        ENTRYPOINTS[name] = factory
        return factory
    return deco


def self_check_targets(names=None) -> List[LintTarget]:
    keys = sorted(ENTRYPOINTS) if names is None else list(names)
    unknown = [k for k in keys if k not in ENTRYPOINTS]
    if unknown:
        # a misspelled entrypoint silently skipping would green-light a
        # gate that never ran — fail loud with the valid names instead
        raise KeyError(
            f"unknown entrypoint(s) {unknown!r}; registered: "
            f"{', '.join(sorted(ENTRYPOINTS))}")
    return [ENTRYPOINTS[k]() for k in keys]


# ------------------------------------------------------------ tiny fixtures


@functools.lru_cache(maxsize=None)
def _tiny_cfg():
    from paddle_tpu.models.transformer import TransformerConfig
    return TransformerConfig(vocab_size=31, dim=16, num_heads=2,
                             num_layers=1, ffn_mult=2, max_len=16)


@functools.lru_cache(maxsize=None)
def _tiny_lm_params():
    import paddle_tpu.nn as nn
    from paddle_tpu.models.transformer import TransformerLM
    cfg = _tiny_cfg()
    model = nn.transform(lambda ids: TransformerLM(cfg, name="lm")(ids))
    params, _ = model.init(jax.random.key(0),
                           jnp.zeros((1, 4), jnp.int32))
    return params


@functools.lru_cache(maxsize=None)
def _tiny_trainer():
    from paddle_tpu import optim
    from paddle_tpu.models.transformer import lm_model_fn_builder
    from paddle_tpu.training.trainer import Trainer
    trainer = Trainer(lm_model_fn_builder(_tiny_cfg()), optim.sgd(0.01))
    trainer.init({"ids": jnp.zeros((2, 8), jnp.int32)})
    return trainer


# -------------------------------------------------------------- entrypoints


def _dp_recipe(n_args: int, sharded_args, note: str):
    """Two-device data-parallel ShardRecipe: the listed positional
    args shard their leading dim on ``dp``, everything else (params,
    pools, scalars) replicates."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.analysis.shard_rules import ShardRecipe
    specs = tuple(P("dp") if i in tuple(sharded_args) else None
                  for i in range(n_args))
    return ShardRecipe(axes=(("dp", 2),), arg_specs=specs, note=note)


def _paged_mp_recipe(n_args: int, cache_args, note: str):
    """Two-device HEAD-SHARDED ShardRecipe for the mesh-native paged
    step (serving.py ``mesh=``): the listed cache args carry the
    ``paged_cache_shardings`` layout (pools on the head axis, scales
    following, bookkeeping replicated), everything else replicates,
    and the decode body is contracted to EXACTLY the attention-output
    all-gather — collective-in-decode now fails on an extra collective
    AND on the combine going missing."""
    from paddle_tpu.analysis.shard_rules import ShardRecipe
    from paddle_tpu.parallel.sharding import paged_cache_shardings

    def cache_spec(arg, mesh):
        return paged_cache_shardings(arg, mesh, "mp")

    specs = tuple(cache_spec if i in tuple(cache_args) else None
                  for i in range(n_args))
    return ShardRecipe(axes=(("mp", 2),), arg_specs=specs, note=note,
                       decode_collectives=("all-gather",))


def _mesh_or_none(n: int = 2):
    """Serving ``mesh=`` knob for the sharded entrypoints: ``n`` when
    the process has the devices, else None so the factory still builds
    (shard_check then reports the device shortfall instead of the
    factory crashing the whole self-check)."""
    return n if len(jax.devices()) >= n else None


@register_entrypoint("trainer-train-step")
def _trainer_train_step() -> LintTarget:
    tr = _tiny_trainer()
    steps = tr.jitted_steps()
    batch = {"ids": jnp.zeros((2, 8), jnp.int32)}
    return LintTarget(
        "trainer-train-step", steps["train_step"],
        (tr.params, tr.net_state, tr.opt_state, batch,
         jnp.asarray(0, jnp.int32)),
        recipe=_dp_recipe(5, (3,), "dp over the batch; the gradient "
                          "all-reduce lands OUTSIDE any loop"))


@functools.lru_cache(maxsize=None)
def _tiny_trainer_health():
    from paddle_tpu import optim
    from paddle_tpu.models.transformer import lm_model_fn_builder
    from paddle_tpu.telemetry.health import HealthConfig
    from paddle_tpu.training.trainer import Trainer
    trainer = Trainer(lm_model_fn_builder(_tiny_cfg()), optim.sgd(0.01),
                      health=HealthConfig(cadence=1))
    trainer.init({"ids": jnp.zeros((2, 8), jnp.int32)})
    return trainer


@register_entrypoint("trainer-train-step-health")
def _trainer_train_step_health() -> LintTarget:
    # The health-instrumented twin: the step packs the in-graph
    # statistics vector into its outputs.  Linting it is the proof the
    # health reductions are pure jnp — host-callback-in-loop would fire
    # on any callback, and the dp lowering shows the stat all-reduces
    # land OUTSIDE any loop, fused with the gradient psum.
    tr = _tiny_trainer_health()
    steps = tr.jitted_steps()
    batch = {"ids": jnp.zeros((2, 8), jnp.int32)}
    return LintTarget(
        "trainer-train-step-health", steps["train_step"],
        (tr.params, tr.net_state, tr.opt_state, batch,
         jnp.asarray(0, jnp.int32)),
        recipe=_dp_recipe(5, (3,), "dp over the batch; health-stat "
                          "reductions ride the same out-of-loop "
                          "all-reduce as the gradient psum"))


@register_entrypoint("trainer-eval-step")
def _trainer_eval_step() -> LintTarget:
    tr = _tiny_trainer()
    steps = tr.jitted_steps()
    batch = {"ids": jnp.zeros((2, 8), jnp.int32)}
    return LintTarget("trainer-eval-step", steps["eval_step"],
                      (tr.params, tr.net_state, batch),
                      recipe=_dp_recipe(3, (2,), "dp over the batch"))


@register_entrypoint("dense-serve-step")
def _dense_serve_step() -> LintTarget:
    from paddle_tpu.models.transformer import lm_serve_builder
    serve = lm_serve_builder(_tiny_cfg())
    prompts = jnp.zeros((2, 4), jnp.int32)
    return LintTarget(
        "dense-serve-step", serve._jit,
        (_tiny_lm_params(), prompts, jnp.asarray(6, jnp.int32),
         0.0, None, None, None, None, None),
        recipe=_dp_recipe(9, serve._lint_batch_args,
                          "dp over prompt rows; a tp recipe would "
                          "all-reduce inside the decode loop"))


@register_entrypoint("paged-serve-step")
def _paged_serve_step() -> LintTarget:
    from paddle_tpu.serving import paged_serve_builder
    # The paged loop cannot dp-shard its batch (the block pool is
    # SLOT-SHARED, [nb, bs, h, hd] with no batch dim — row-sharded
    # append/reserve scatters would all-gather the pool every
    # iteration; shard-check proved 11 collective-in-decode errors
    # under a dp recipe).  It shards on the HEAD axis instead: the
    # builder's mesh= knob runs append/attend per head-shard under
    # shard_map, every input replicates, the in-jit pool is pinned to
    # the head-sharded layout, and the ONLY collective in the while
    # body is the per-layer attention-output all-gather the recipe
    # declares.
    serve = paged_serve_builder(_tiny_cfg(), block_size=8,
                                mesh=_mesh_or_none())
    prompts = jnp.zeros((2, 4), jnp.int32)
    return LintTarget(
        "paged-serve-step", serve._jit,
        (_tiny_lm_params(), prompts, jnp.asarray(6, jnp.int32),
         0.0, None, None, None, None, None),
        recipe=_paged_mp_recipe(9, (), "head-sharded pool built "
                                "in-jit (inputs replicate); decode "
                                "body carries exactly the attention-"
                                "output all-gather"))


@register_entrypoint("paged-engine-decode")
def _paged_engine_decode() -> LintTarget:
    # unified_step=False on this and the twins below: these entrypoints
    # pin the LEGACY multi-program engine's decode/verify shapes (the
    # baseline the unified step is measured against); the default
    # engine's one-program form lints as paged-engine-step-ragged.
    from paddle_tpu.serving import PagedServingEngine
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,), unified_step=False)
    S = eng.S
    return LintTarget(
        "paged-engine-decode", eng._decode,
        (eng.params, eng.cache, jnp.zeros((S,), jnp.int32),
         jnp.ones((S,), bool), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_dp_recipe(7, eng._decode_slot_args,
                          "dp over slot vectors; pool + block tables "
                          "replicated until the multi-chip pool item "
                          "lands (ROADMAP)"))


@register_entrypoint("paged-engine-decode-prefix")
def _paged_engine_decode_prefix() -> LintTarget:
    # The prefix-sharing twin: decode with ``prefix_cache=True`` traces
    # a copy-on-write un-share (refcount test + cond-gated block copy)
    # ahead of the reserve/append scatters.  Linting it proves the COW
    # machinery stays in-graph (no host callback resolves "is this
    # block shared?") and adds no attention gathers to the loop.
    from paddle_tpu.serving import PagedServingEngine
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,), prefix_cache=True,
                             unified_step=False)
    S = eng.S
    return LintTarget(
        "paged-engine-decode-prefix", eng._decode,
        (eng.params, eng.cache, jnp.zeros((S,), jnp.int32),
         jnp.ones((S,), bool), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_dp_recipe(7, eng._decode_slot_args,
                          "dp over slot vectors; the COW copy reads "
                          "and writes the replicated pool exactly like "
                          "reserve/append do"))


@register_entrypoint("paged-engine-decode-faults")
def _paged_engine_decode_faults() -> LintTarget:
    # The fault-injection twin: an engine with an armed FaultInjector
    # fires its points strictly in the HOST loop, so the traced decode
    # program must be byte-for-byte the plain engine's — same rules,
    # same budget, zero new suppressions.  Linting it pins the chaos
    # harness to the host side (an injection point inside the jitted
    # step would be the host-callback-in-loop error).
    from paddle_tpu.serving import PagedServingEngine
    from paddle_tpu.testing.faults import FaultInjector
    inj = FaultInjector()                 # empty schedule: count only
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,),
                             faults=inj.scope("lint"),
                             unified_step=False)
    S = eng.S
    return LintTarget(
        "paged-engine-decode-faults", eng._decode,
        (eng.params, eng.cache, jnp.zeros((S,), jnp.int32),
         jnp.ones((S,), bool), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_dp_recipe(7, eng._decode_slot_args,
                          "dp over slot vectors, exactly as "
                          "paged-engine-decode: the injector lives in "
                          "the host loop and contributes nothing to "
                          "the traced program"))


# Kernel-selected twins: the same serve programs with decode_kernel
# FORCED on (Pallas interpret mode on the CPU lint backend — the
# traced jaxpr carries the pallas_call eqn either way, which is what
# the gate is for: the attention gathers must be GONE from the decode
# loop with zero new suppressions, the XLA-HBM rules still skip the
# kernel body, and the KERNEL rule family (analysis/kernel_rules.py)
# opens it — vmem-budget cross-checks the derived footprint against
# _paged_vmem_bytes per dtype arm, scratch/oob/masking prove the
# kernel contract from the trace).  The serve twin shards like
# paged-serve-step: GSPMD cannot AUTO-partition a pallas_call, but the
# mesh path never asks it to — under the explicit shard_map each
# device runs its own pallas_call over its local head slice, so the
# kernel recipe flips to head-sharded with it.  The legacy engine
# decode twin below stays replicated (the legacy multi-program mode
# has no mesh knob; the unified step twins carry the sharded recipe).


@register_entrypoint("paged-serve-step-kernel")
def _paged_serve_step_kernel() -> LintTarget:
    from paddle_tpu.serving import paged_serve_builder
    serve = paged_serve_builder(_tiny_cfg(), block_size=8,
                                decode_kernel=True,
                                mesh=_mesh_or_none())
    prompts = jnp.zeros((2, 4), jnp.int32)
    return LintTarget(
        "paged-serve-step-kernel", serve._jit,
        (_tiny_lm_params(), prompts, jnp.asarray(6, jnp.int32),
         0.0, None, None, None, None, None),
        recipe=_paged_mp_recipe(9, (), "head-sharded like "
                                "paged-serve-step; each device runs "
                                "its own pallas_call on local heads "
                                "inside shard_map"))


@register_entrypoint("paged-engine-decode-kernel")
def _paged_engine_decode_kernel() -> LintTarget:
    from paddle_tpu.serving import PagedServingEngine
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,), decode_kernel=True,
                             unified_step=False)
    S = eng.S
    return LintTarget(
        "paged-engine-decode-kernel", eng._decode,
        (eng.params, eng.cache, jnp.zeros((S,), jnp.int32),
         jnp.ones((S,), bool), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_dp_recipe(7, (), "replicated under the mesh — slot "
                          "vectors could dp-shard, but GSPMD cannot "
                          "partition the pallas_call they feed"))


@register_entrypoint("paged-engine-decode-spec")
def _paged_engine_decode_spec() -> LintTarget:
    # The speculative-decoding VERIFY step: one chunked-attention
    # program scores all k+1 candidate positions per slot and appends
    # their KVs optimistically (the host rolls back rejects).  Linting
    # it proves the multi-token verify keeps the decode-loop
    # discipline: per-layer chunked gathers (amortized over the k+1
    # queries), in-graph reserve/COW, no host callbacks — the accept/
    # reject decision stays strictly on the host side.
    from paddle_tpu.serving import PagedServingEngine, SpecConfig
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,),
                             spec=SpecConfig(k=2, draft_layers=1),
                             unified_step=False)
    S, k = eng.S, eng.spec_k
    return LintTarget(
        "paged-engine-decode-spec", eng._verify,
        (eng.params, eng.cache, jnp.zeros((S, k + 1), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32)),
        recipe=_dp_recipe(5, eng._verify_slot_args,
                          "dp over slot-major verify inputs (toks/"
                          "valid/temps); pool + block tables "
                          "replicated exactly as the decode twin"))


@register_entrypoint("paged-engine-step-ragged")
def _paged_engine_step_ragged() -> LintTarget:
    # The UNIFIED ragged step (the default engine's ONE compiled
    # program): plain decode is a width-1 query window, chunked tail
    # prefill and k-token spec verify are wider windows, all appended
    # and scored through the same per-row ragged causal bounds.
    # Linting it proves the collapsed program keeps the decode-loop
    # discipline the three legacy programs pinned separately: in-graph
    # COW/reserve/append scatters, amortized chunked gathers, no host
    # callbacks — the accept/reject decision stays on the host.  Built
    # with spec= so the traced window width is k+1 (the widest form);
    # qlens=1 rows trace the same program plain decode runs.
    from paddle_tpu.serving import PagedServingEngine, SpecConfig
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,),
                             spec=SpecConfig(k=2, draft_layers=1),
                             mesh=_mesh_or_none())
    S, W = eng.S, eng.step_width
    return LintTarget(
        "paged-engine-step-ragged", eng._step,
        (eng.params, eng.cache, jnp.zeros((S, W), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_paged_mp_recipe(
            7, (1,), "head-sharded KV pool (paged_cache_shardings on "
            "the cache arg); params + slot vectors replicate; exactly "
            "the attention-output all-gather in the step"))


@register_entrypoint("paged-engine-step-lora")
def _paged_engine_step_lora() -> LintTarget:
    # The unified ragged step with the multi-tenant LoRA adapter pool
    # GATHERED in: each row takes its per-slot adapter id, the step
    # gathers that slot's A/B factors from the pooled f32 stacks and
    # applies ``h + scale * (x @ A) @ B`` per layer.  Linting it pins
    # the subsystem's two compiled-side contracts: the pool rides as a
    # jit ARGUMENT (static shapes — loading/evicting adapters never
    # recompiles, and the adapter stacks head-shard-compatibly
    # replicate under the mp=2 recipe), and the delta path keeps f32
    # accumulation (factors stored f32, both einsums accumulate f32,
    # ONE cast back to the activation dtype) with id=-1 rows handed
    # the base activations through a select.  Three distinct adapters
    # are loaded so the gather is exercised over a mixed pool, exactly
    # the N>=3-residents acceptance shape.
    from paddle_tpu.serving import PagedServingEngine
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,),
                             adapters=3, adapter_rank=4,
                             mesh=_mesh_or_none())
    cfg = _tiny_cfg()
    for i in range(3):
        eng.load_adapter(
            f"lint-{i}",
            {"a": np.full((cfg.num_layers, cfg.dim, 4), 0.01 * (i + 1),
                          np.float32),
             "b": np.full((cfg.num_layers, 4, cfg.dim), 0.01 * (i + 1),
                          np.float32),
             "scale": 1.0, "meta": {}},
            tenant=f"t{i}")
    S, W = eng.S, eng.step_width
    return LintTarget(
        "paged-engine-step-lora", eng._step,
        (eng.params, eng.cache, jnp.zeros((S, W), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0),
         eng.adapter_step_args()),
        recipe=_paged_mp_recipe(
            8, (1,), "head-sharded KV pool (paged_cache_shardings on "
            "the cache arg); params, slot vectors AND the gathered "
            "adapter stacks replicate; exactly the attention-output "
            "all-gather in the step"))


@register_entrypoint("paged-engine-step-spill")
def _paged_engine_step_spill() -> LintTarget:
    # The unified ragged step on an engine carrying the TIERED prefix
    # cache (radix registry + host-RAM spill store).  The whole tier
    # is host-side machinery — demotion serializes pages with eager
    # numpy reads, restore writes them back with eager .at[].set
    # imports BEFORE the step runs — so the traced step program must
    # be byte-for-byte the plain ragged step: same peak, same rule
    # set, no host callbacks smuggled in by the spill bookkeeping.
    # budgets.json pins its peak to paged-engine-step-ragged's ceiling
    # for exactly that reason.
    from paddle_tpu.serving import PagedServingEngine, SpecConfig
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,),
                             spec=SpecConfig(k=2, draft_layers=1),
                             prefix_cache=True,
                             prefix_host_bytes=1 << 20,
                             mesh=_mesh_or_none())
    S, W = eng.S, eng.step_width
    return LintTarget(
        "paged-engine-step-spill", eng._step,
        (eng.params, eng.cache, jnp.zeros((S, W), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_paged_mp_recipe(
            7, (1,), "head-sharded KV pool (paged_cache_shardings on "
            "the cache arg); params + slot vectors replicate; exactly "
            "the attention-output all-gather in the step"))


@register_entrypoint("paged-engine-step-ragged-kernel")
def _paged_engine_step_ragged_kernel() -> LintTarget:
    # The unified ragged step with the Pallas kernel FORCED on and a
    # bf16 KV pool: the arm that exercises _paged_vmem_bytes' 6 B/elt
    # charge (Mosaic stages packed bf16 tiles through unpacked copies).
    # The kernel rules open the pallas_call and re-derive the footprint
    # from its BlockSpecs — estimator drift on THIS arm fails lint
    # here, per entrypoint, exactly as the int8 twin below pins the
    # 5 B/elt arm.  Same head-sharded recipe as the XLA ragged step:
    # under explicit shard_map each device runs its own pallas_call on
    # its local head slice.
    from paddle_tpu.serving import PagedServingEngine, SpecConfig
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,), kv_dtype="bfloat16",
                             spec=SpecConfig(k=2, draft_layers=1),
                             mesh=_mesh_or_none(), decode_kernel=True)
    S, W = eng.S, eng.step_width
    return LintTarget(
        "paged-engine-step-ragged-kernel", eng._step,
        (eng.params, eng.cache, jnp.zeros((S, W), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_paged_mp_recipe(
            7, (1,), "head-sharded bf16 pool; each device runs its "
            "own pallas_call on local heads inside shard_map; same "
            "single all-gather contract as the XLA ragged twin"))


@register_entrypoint("paged-engine-step-int8-kernel")
def _paged_engine_step_int8_kernel() -> LintTarget:
    # The quantized kernel twin: unified ragged step, Pallas kernel
    # forced on, int8 pages + per-block scales.  Pins the estimator's
    # 5 B/elt int8 arm (1 packed byte streamed + 4-byte f32 dequant
    # staging) through the same derived-vs-estimator cross-check, and
    # proves the in-kernel dequant keeps f32 accumulation
    # (scratch-accum-dtype) and complete masking.
    from paddle_tpu.serving import PagedServingEngine, SpecConfig
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,), kv_dtype="int8",
                             spec=SpecConfig(k=2, draft_layers=1),
                             mesh=_mesh_or_none(), decode_kernel=True)
    S, W = eng.S, eng.step_width
    return LintTarget(
        "paged-engine-step-int8-kernel", eng._step,
        (eng.params, eng.cache, jnp.zeros((S, W), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_paged_mp_recipe(
            7, (1,), "head-sharded int8 pool + scales, kernel forced; "
            "same single all-gather contract as the int8 XLA twin"))


@register_entrypoint("paged-engine-step-int8")
def _paged_engine_step_int8() -> LintTarget:
    # The quantized twin of paged-engine-step-ragged: same unified
    # ragged step, same spec window, but the KV pool is int8 pages +
    # per-block f32 scales.  Two gates ride on it: (1) the dequant
    # write/read paths (quantize-on-append scatters, scale growth +
    # cursor requantize, dequant before the score dot) keep the
    # decode-loop discipline — f32 accumulation (the extended
    # accum-dtype rule's dequant-matmul face), no host callbacks, no
    # fresh gather suppressions; (2) the budgets.json peak RATCHETS
    # the footprint win — the quantized step's live bytes must stay
    # BELOW the bf16 twin's measured peak (31142), so the capacity
    # gain cannot silently regress.
    from paddle_tpu.serving import PagedServingEngine, SpecConfig
    eng = PagedServingEngine(_tiny_cfg(), _tiny_lm_params(),
                             num_slots=2, num_blocks=8, block_size=8,
                             prompt_buckets=(8,), kv_dtype="int8",
                             spec=SpecConfig(k=2, draft_layers=1),
                             mesh=_mesh_or_none())
    S, W = eng.S, eng.step_width
    return LintTarget(
        "paged-engine-step-int8", eng._step,
        (eng.params, eng.cache, jnp.zeros((S, W), jnp.int32),
         jnp.ones((S,), jnp.int32), jnp.zeros((S,), jnp.float32),
         jnp.zeros((S,), bool), jax.random.key(0)),
        recipe=_paged_mp_recipe(
            7, (1,), "head-sharded int8 pool + per-block scales "
            "(scales follow their pages' head split); same single "
            "all-gather contract as the bf16 ragged twin"))
