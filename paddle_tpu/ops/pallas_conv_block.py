"""Fused backward for the ``1x1-conv -> BatchNorm -> relu`` unit.

**STATUS: measured experiment, NOT wired into the model zoo.**  On a
v5e the fused backward benched ~2x SLOWER than XLA's chain at the hot
ResNet shapes (n=401k rows: 256->64 3.18 vs 1.55 ms, 64->256 6.04 vs
3.50 ms; n=100k 512->128 1.63 vs 1.34 ms).  The structural byte saving
the design targets exists only in the full-graph context (where XLA's
fusions re-read tensors across consumer fusions); in isolation XLA's
conv emitters out-tile Mosaic's dot_general enough to erase the margin.
Kept as a tested, documented negative result for the round-3 record —
see docs/design/kernels.md.

The round-2 roofline analysis (docs/design/kernels.md) showed XLA
executing the ResNet backward within ~5% of the HBM floor of its OWN
fusion structure — but that structure reads the big tensors 2-3 times:
the BN-stat reduces read (dy, s), the dx fusion re-reads them plus w,
and the dw fusion reads (x, dy) again.  This module restructures the
chain into two Pallas passes over row tiles:

  pass 1 (reduce):  read (dy, s)        -> dbeta, dgamma partials
  pass 2 (apply):   read (dy, s, x)     -> dx tile, dw += , done

so every big tensor is read at most twice total (dy, s) or once (x),
instead of 2-3 times.  dw/dgamma/dbeta accumulate in constant-index
output blocks (small, so Pallas's consecutive-revisit rule allows them —
unlike the LSTM dW case, which had to move outside the kernel).

Math (N = b*h*w rows, Co channels; eps inside istd):
  forward:   s = x @ w;  x_hat = (s - mean) * istd
             y = relu(gamma * x_hat + beta)
  backward:  dz     = dy * (y > 0)
             dbeta  = sum dz;      dgamma = sum dz * x_hat
             ds     = gamma * istd * (dz - dbeta/N - x_hat * dgamma/N)
             dx     = ds @ w^T;    dw = x^T @ ds

Exposed through :func:`conv1x1_bn_relu`, a ``custom_vjp`` whole-unit op
returning (y, mean, var) — batch statistics come out as plain outputs so
the module layer can thread running averages through the state system
OUTSIDE the pure vjp function.

Reference twin: the hand-fused building blocks in
``paddle/cuda/src/hl_batch_norm.cu`` + ``hl_cuda_cnn.cu`` — the same
"one kernel owns the chain" discipline, re-targeted at HBM traffic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


_LANE = 128


def block_supported(n: int, cin: int, cout: int) -> bool:
    """Row-tiled kernels need lane-aligned channel counts and enough rows
    for at least one (8-aligned) tile."""
    return (cin % _LANE == 0 and cout % _LANE == 0
            and n % 8 == 0 and n >= 8)


def _row_tile(n: int, cin: int, cout: int) -> int:
    """Row-tile height: big enough to keep the MXU busy, small enough
    that (x, dy, s, dx) tiles + w + accumulators stay under VMEM."""
    for tn in (1024, 512, 256, 128, 64, 32, 16, 8):
        if n % tn:
            continue
        words = (tn * cin * 2      # x, dx tiles
                 + tn * cout * 3   # dy, s, dz tiles
                 + 2 * cin * cout  # w + dw accumulator
                 + 4 * cout)
        if words * 4 <= 10 * 1024 * 1024:
            return tn
    return 0


# ---------------------------------------------------------------------------
# pass 1: dbeta/dgamma reduction over row tiles
# ---------------------------------------------------------------------------

def _reduce_kernel(dy_ref, s_ref, mask_ref, mean_ref, istd_ref,
                   dbeta_ref, dgamma_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dbeta_ref[:] = jnp.zeros_like(dbeta_ref)
        dgamma_ref[:] = jnp.zeros_like(dgamma_ref)

    s = s_ref[:].astype(jnp.float32)
    x_hat = (s - mean_ref[:]) * istd_ref[:]
    # mask is the exact forward relu sign (recomputing y from bf16 s
    # flips boundary elements).
    dz = dy_ref[:].astype(jnp.float32) * mask_ref[:].astype(jnp.float32)
    dbeta_ref[:] += jnp.sum(dz, axis=0, keepdims=True)
    dgamma_ref[:] += jnp.sum(dz * x_hat, axis=0, keepdims=True)


# ---------------------------------------------------------------------------
# pass 2: dx tiles + dw accumulation
# ---------------------------------------------------------------------------

def _apply_kernel(x_ref, dy_ref, s_ref, mask_ref, w_ref, mean_ref,
                  istd_ref, gamma_ref, sums_ref, dx_ref, dw_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    s = s_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    istd = istd_ref[:]
    x_hat = (s - mean) * istd
    gamma = gamma_ref[:]
    dz = dy_ref[:].astype(jnp.float32) * mask_ref[:].astype(jnp.float32)
    # sums_ref rows: 0 = dbeta/N, 1 = dgamma/N (pre-divided by caller)
    ds = gamma * istd * (dz - sums_ref[0] - x_hat * sums_ref[1])
    dsb = ds.astype(jnp.bfloat16)
    dx_ref[:] = lax.dot_general(
        dsb, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dx_ref.dtype)
    dw_ref[:] += lax.dot_general(
        x_ref[:], dsb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _block_bwd_pallas(x, dy, s, mask, w, mean, istd, gamma, tn: int,
                      interpret: bool):
    n, cin = x.shape
    cout = w.shape[1]
    kwargs = {}
    if not interpret and pltpu is not None:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    grid = (n // tn,)

    dbeta, dgamma = pl.pallas_call(
        _reduce_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, cout), lambda i: (i, 0)),
            pl.BlockSpec((tn, cout), lambda i: (i, 0)),
            pl.BlockSpec((tn, cout), lambda i: (i, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(dy, s, mask, mean[None], istd[None])

    sums = jnp.concatenate([dbeta, dgamma], axis=0) / float(n)
    dx, dw = pl.pallas_call(
        _apply_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, cin), lambda i: (i, 0)),
            pl.BlockSpec((tn, cout), lambda i: (i, 0)),
            pl.BlockSpec((tn, cout), lambda i: (i, 0)),
            pl.BlockSpec((tn, cout), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((2, cout), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tn, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, cin), x.dtype),
            jax.ShapeDtypeStruct((cin, cout), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(x, dy, s, mask, w.astype(jnp.bfloat16), mean[None], istd[None],
      gamma.astype(jnp.float32)[None], sums)
    return dx, dw, dgamma[0], dbeta[0]


# ---------------------------------------------------------------------------
# custom_vjp unit
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def conv1x1_bn_relu(x, w, gamma, beta, eps: float = 1e-5,
                    interpret: bool = False):
    """y = relu(BN_train(x @ w)) over rows; returns (y, mean, var).

    x: [n, cin] (bf16 or f32 rows — callers flatten NHWC spatial dims),
    w: [cin, cout]; gamma/beta: [cout] f32.  Batch statistics return as
    outputs so module wrappers can update running averages outside this
    pure function.
    """
    y, mean, var, _, _, _ = _unit_fwd_math(x, w, gamma, beta, eps)
    return y, lax.stop_gradient(mean), lax.stop_gradient(var)


def _unit_fwd_math(x, w, gamma, beta, eps):
    s = jnp.dot(x, w.astype(x.dtype),
                preferred_element_type=jnp.float32)
    mean = jnp.mean(s, axis=0)
    var = jnp.maximum(jnp.mean(jnp.square(s), axis=0)
                      - jnp.square(mean), 0.0)
    istd = lax.rsqrt(var + eps)
    x_hat = (s - mean) * istd
    z = gamma * x_hat + beta
    y = jnp.maximum(z, 0.0).astype(x.dtype)
    return y, mean, var, istd, s.astype(jnp.bfloat16), (z > 0.0)


def _unit_fwd(x, w, gamma, beta, eps, interpret):
    y, mean, var, istd, s, mask = _unit_fwd_math(x, w, gamma, beta, eps)
    return ((y, lax.stop_gradient(mean), lax.stop_gradient(var)),
            (x, w, gamma, mean, istd, s, mask))


def _unit_bwd(eps, interpret, res, grads):
    x, w, gamma, mean, istd, s, mask = res
    dy, dmean, dvar = grads
    # mean/var are emitted through stop_gradient in the primal (they feed
    # running averages, not the loss), so their cotangents are zero.
    del dmean, dvar
    n, cin = x.shape
    cout = w.shape[1]
    from paddle_tpu.core.errors import enforce
    enforce(block_supported(n, cin, cout),
            "conv1x1_bn_relu backward needs lane-aligned channels and "
            "8-aligned rows; got n=%d cin=%d cout=%d", n, cin, cout)
    tn = _row_tile(n, cin, cout)
    enforce(tn > 0, "conv1x1_bn_relu: no row tile fits VMEM for "
            "n=%d cin=%d cout=%d", n, cin, cout)
    dx, dw, dgamma, dbeta = _block_bwd_pallas(
        x.astype(jnp.bfloat16), dy.astype(jnp.bfloat16), s, mask,
        w, mean, istd, gamma, tn, interpret)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


conv1x1_bn_relu.defvjp(_unit_fwd, _unit_bwd)
