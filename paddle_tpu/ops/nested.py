"""Nested (2-level) sequence ops.

The reference carries nested variable-length sequences everywhere —
``Argument.subSequenceStartPositions`` (``parameter/Argument.h:93``), the
2-level ``LoD`` of the new IR (``lod_tensor.h:53``), and the sub-sequence
layer family (SubSequenceLayer, SequenceReshapeLayer,
SubNestedSequenceLayer, SequenceSoftmax over sub-sequences,
AverageLayer/MaxLayer at ``AverageLevel=kNonSeq|kSeq``).

TPU-native representation (docs/design/sequences.md): a nested batch is
``value [batch, outer, inner, ...]`` + ``mask [batch, outer, inner]`` —
one extra dense axis + one extra mask level, all shapes static.  The outer
sequence's own mask is ``outer_mask(mask) = mask.any(-1)``.

Every op here reduces to the flat ops of ``ops/sequence.py`` applied over
an extra leading axis (vmap-style reshaping), which is exactly how the
reference's layers loop sub-sequences inside each sequence.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.errors import enforce
from paddle_tpu.ops import sequence as seq


def outer_mask(mask: jax.Array) -> jax.Array:
    """[b, outer, inner] -> [b, outer]: which sub-sequences exist."""
    return mask.any(axis=-1)


def nested_pool(x: jax.Array, mask: jax.Array, pool_type: str = "avg"
                ) -> Tuple[jax.Array, jax.Array]:
    """Pool each sub-sequence to one vector — the reference's sequence
    pooling at ``kSeq`` level (nested input -> plain sequence output).

    x: [b, o, i, d...], mask: [b, o, i] -> ([b, o, d...], [b, o]).
    """
    x = jnp.asarray(x)
    mask = jnp.asarray(mask)
    b, o = mask.shape[:2]
    flat_x = x.reshape((b * o,) + x.shape[2:])
    flat_m = mask.reshape(b * o, mask.shape[2])
    # Empty sub-sequences: give them one fake valid step so pooling is
    # well-defined, then zero the result via the outer mask.
    safe_m = flat_m.at[:, 0].set(flat_m[:, 0] | ~flat_m.any(-1))
    pooled = seq.sequence_pool(flat_x, safe_m, pool_type)
    pooled = pooled.reshape((b, o) + pooled.shape[1:])
    om = outer_mask(mask)
    pooled = jnp.where(om.reshape((b, o) + (1,) * (pooled.ndim - 2)),
                       pooled, 0.0)
    return pooled, om


def flatten_nested(x: jax.Array, mask: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """Degrade a nested sequence to a flat one, compacting the per-row
    concatenation of its sub-sequences (SequenceReshapeLayer /
    Argument degrade-to-sequence twin).

    x: [b, o, i, d...], mask: [b, o, i] -> ([b, o*i, d...], [b, o*i])
    with all valid steps left-packed per batch row.
    """
    b, o, i = mask.shape
    t = o * i
    flat_x = x.reshape((b, t) + x.shape[3:])
    flat_m = mask.reshape(b, t)
    # left-pack: stable argsort of ~mask moves valid steps to the front
    order = jnp.argsort(~flat_m, axis=1, stable=True)
    packed = jnp.take_along_axis(
        flat_x, order.reshape((b, t) + (1,) * (flat_x.ndim - 2)), axis=1)
    packed_m = jnp.take_along_axis(flat_m, order, axis=1)
    packed = jnp.where(
        packed_m.reshape((b, t) + (1,) * (packed.ndim - 2)), packed,
        jnp.zeros((), packed.dtype))
    return packed, packed_m


def split_to_nested(x: jax.Array, mask: jax.Array, inner: int
                    ) -> Tuple[jax.Array, jax.Array]:
    """Promote a flat sequence to nested by cutting fixed-size ``inner``
    windows (the static-shape seq->nested reshape; the reference's
    SequenceReshapeLayer reshaped by a dimension factor the same way).

    x: [b, t, d...], mask: [b, t] -> ([b, ceil(t/inner), inner, d...], ...)
    """
    b, t = mask.shape
    o = -(-t // inner)
    pad = o * inner - t
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    return (x.reshape((b, o, inner) + x.shape[2:]),
            mask.reshape(b, o, inner))


def sub_nested_seq(x: jax.Array, mask: jax.Array, indices: jax.Array,
                   k: int) -> Tuple[jax.Array, jax.Array]:
    """Select ``k`` sub-sequences per row by index
    (SubNestedSequenceLayer twin — e.g. keep the kmax-scored ones).

    x: [b, o, i, d...], mask: [b, o, i], indices: [b, k] int32 ->
    ([b, k, i, d...], [b, k, i]).
    """
    b, o, i = mask.shape
    idx = jnp.clip(indices, 0, o - 1)
    sel = jnp.take_along_axis(
        x, idx.reshape((b, k) + (1,) * (x.ndim - 2)), axis=1)
    sel_m = jnp.take_along_axis(mask, idx[:, :, None], axis=1)
    valid = (indices >= 0) & (indices < o)
    sel_m = sel_m & valid[:, :, None]
    sel = jnp.where(sel_m.reshape((b, k, i) + (1,) * (sel.ndim - 3)),
                    sel, jnp.zeros((), sel.dtype))
    return sel, sel_m


def nested_softmax(x: jax.Array, mask: jax.Array) -> jax.Array:
    """Softmax within each sub-sequence (sequence_softmax at the
    sub-sequence level; x: [b, o, i] scores)."""
    neg = jnp.where(mask, x, -jnp.inf)
    m = jnp.max(neg, axis=-1, keepdims=True)
    e = jnp.exp(jnp.where(mask, x - m, -jnp.inf))
    e = jnp.where(mask, e, 0.0)
    denom = jnp.maximum(e.sum(axis=-1, keepdims=True), 1e-9)
    return e / denom


def nested_expand(vec: jax.Array, mask: jax.Array) -> jax.Array:
    """Broadcast one vector per sub-sequence over its steps
    (expand_layer at kSeq level).  vec: [b, o, d], mask: [b, o, i]."""
    out = jnp.broadcast_to(vec[:, :, None, :],
                           mask.shape + (vec.shape[-1],))
    return jnp.where(mask[..., None], out, 0.0)
