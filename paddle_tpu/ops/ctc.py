"""CTC loss via the log-space alpha recursion as a ``lax.scan``.

TPU-native twin of the reference's CTC stack (``gserver/layers/CTCLayer.cpp``
+ ``LinearChainCTC.cpp``, and the warp-ctc wrapper ``WarpCTCLayer.cpp`` /
``hl_warpctc_wrap``): instead of linking an external CUDA library, the
standard Graves dynamic program runs as a static-shape scan over time with
the extended label sequence (blank-interleaved) laid out densely — XLA
vectorizes the per-state transitions across the whole batch.

Conventions: ``blank`` is class 0 by default (matching warp-ctc).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _log_add(a, b):
    m = jnp.maximum(a, b)
    m = jnp.where(jnp.isfinite(m), m, 0.0)  # avoid -inf - -inf
    return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))


def ctc_loss(logits, logit_lengths, labels, label_lengths, blank: int = 0):
    """Per-example CTC negative log-likelihood.

    logits: [b, t, n] unnormalized; logit_lengths: [b];
    labels: [b, l] int (padded with anything); label_lengths: [b].
    Max label length l must satisfy 2*l+1 <= t for valid examples.
    """
    b, t, n = logits.shape
    l = labels.shape[1]
    s = 2 * l + 1

    logp = jax.nn.log_softmax(logits, axis=-1)

    # Extended sequence: [blank, y1, blank, y2, ..., blank]
    ext = jnp.full((b, s), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    # allow skip s-2 -> s when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s]
    can_skip = (ext != blank) & (ext != ext_prev2)

    # per-step emission logprob for each extended state
    logp_t = jnp.swapaxes(logp, 0, 1)                      # [t, b, n]

    def emit(lp):
        return jnp.take_along_axis(lp, ext, axis=-1)       # [b, s]

    alpha0 = jnp.full((b, s), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit(logp_t[0])[:, 0])
    valid1 = (label_lengths > 0)
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(valid1, emit(logp_t[0])[:, 1], NEG_INF))

    steps = jnp.arange(1, t)

    def step(alpha, ti):
        lp = logp_t[ti]
        a_prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                          constant_values=NEG_INF)[:, :s]
        a_prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                          constant_values=NEG_INF)[:, :s]
        acc = _log_add(alpha, a_prev1)
        acc = jnp.where(can_skip, _log_add(acc, a_prev2), acc)
        new = acc + emit(lp)
        active = (ti < logit_lengths)[:, None]
        new = jnp.where(active, new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, steps)

    # final prob: alpha at states 2*len (last blank) and 2*len-1 (last label)
    idx_blank = (2 * label_lengths)[:, None]
    idx_label = jnp.maximum(2 * label_lengths - 1, 0)[:, None]
    a_blank = jnp.take_along_axis(alpha, idx_blank, axis=1)[:, 0]
    a_label = jnp.take_along_axis(alpha, idx_label, axis=1)[:, 0]
    a_label = jnp.where(label_lengths > 0, a_label, NEG_INF)
    return -_log_add(a_blank, a_label)


def ctc_greedy_decode(logits, logit_lengths, blank: int = 0):
    """Best-path decoding: argmax per frame, collapse repeats, drop blanks.

    Returns (decoded [b, t] padded with -1, decoded_lengths [b]).
    """
    b, t, n = logits.shape
    best = jnp.argmax(logits, axis=-1)                     # [b, t]
    frame_valid = jnp.arange(t)[None, :] < logit_lengths[:, None]
    prev = jnp.pad(best, ((0, 0), (1, 0)), constant_values=-1)[:, :t]
    keep = frame_valid & (best != blank) & (best != prev)
    # stable compaction: position of each kept element
    pos = jnp.cumsum(keep, axis=1) - 1
    out = jnp.full((b, t), -1, best.dtype)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    out = out.at[rows, jnp.where(keep, pos, t - 1)].set(
        jnp.where(keep, best, -1), mode="drop")
    # note: when keep is False we write -1 at t-1 (harmless if slot unused)
    lengths = keep.sum(axis=1).astype(jnp.int32)
    # re-blank any trailing slot clobbered by the dummy writes
    out = jnp.where(jnp.arange(t)[None, :] < lengths[:, None], out, -1)
    return out, lengths
